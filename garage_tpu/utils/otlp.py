"""OTLP/HTTP span export: ship finished spans to a standard collector.

Ref parity: src/garage/tracing_setup.rs:13-37 — the reference installs
an opentelemetry-otlp pipeline (service.name=garage,
service.instance.id=first 8 bytes of the node id, batch export). This
build exports the same span topology over OTLP/HTTP **JSON**
(`POST {endpoint}/v1/traces`, Content-Type application/json), the
dependency-free encoding of the OTLP protocol, from a background
thread so a slow or dead collector never touches the data path.

Internal span ids are 8-hex trace / 8-hex span tokens
(utils/tracing.py); OTLP requires 16-byte trace ids and 8-byte span
ids, so ids are left-zero-padded to wire width.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import urllib.request
from typing import Optional

log = logging.getLogger("garage_tpu.otlp")

_BATCH = 256          # spans per POST
_FLUSH_SECS = 3.0     # max latency before a partial batch ships
_QUEUE_MAX = 8192     # drop-oldest beyond this: never block producers


def _attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def span_to_otlp(rec: dict) -> dict:
    """One tracer ring/JSONL record -> an OTLP Span object."""
    start_ns = rec["start_us"] * 1000
    end_ns = (rec["start_us"] + rec["dur_us"]) * 1000
    out = {
        "traceId": rec["trace"].rjust(32, "0"),
        "spanId": rec["span"].rjust(16, "0"),
        "name": rec["name"],
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
    }
    if rec.get("parent"):
        out["parentSpanId"] = rec["parent"].rjust(16, "0")
    attrs = [_attr(k, v) for k, v in (rec.get("attrs") or {}).items()]
    if attrs:
        out["attributes"] = attrs
    if rec.get("error"):
        out["status"] = {"code": 2, "message": rec["error"]}  # ERROR
    return out


class OtlpExporter:
    """Background OTLP/HTTP JSON exporter fed by a tracer sink."""

    def __init__(self, endpoint: str, instance_id: str,
                 service_name: str = "garage"):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.resource = {
            "attributes": [
                _attr("service.name", service_name),
                _attr("service.instance.id", instance_id),
            ]
        }
        self._q: queue.Queue = queue.Queue(maxsize=_QUEUE_MAX)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-export")
        self.sent_spans = 0
        self.dropped_spans = 0
        self.failed_posts = 0

    # ---- producer side (called from Tracer.emit) -----------------------

    def sink(self, rec: dict) -> None:
        try:
            self._q.put_nowait(rec)
        except queue.Full:
            self.dropped_spans += 1

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "OtlpExporter":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        try:
            self._q.put_nowait(None)  # wake the drain loop
        except queue.Full:
            pass  # the drain loop's flush tick notices _stop itself
        self._thread.join(timeout)

    # ---- consumer ------------------------------------------------------

    def _run(self) -> None:
        batch: list[dict] = []
        while True:
            try:
                rec = self._q.get(timeout=_FLUSH_SECS)
            except queue.Empty:
                rec = False  # timeout tick: flush partial batch
            if rec:
                batch.append(rec)
            if batch and (len(batch) >= _BATCH or not rec):
                self._post(batch)
                batch = []
            if rec is None or (self._stop.is_set() and self._q.empty()):
                if batch:
                    self._post(batch)
                return

    def _post(self, batch: list[dict]) -> None:
        payload = json.dumps({
            "resourceSpans": [{
                "resource": self.resource,
                "scopeSpans": [{
                    "scope": {"name": "garage_tpu"},
                    "spans": [span_to_otlp(r) for r in batch],
                }],
            }],
        }).encode()
        req = urllib.request.Request(
            self.url, data=payload,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=3.0) as resp:
                resp.read()
            self.sent_spans += len(batch)
        except Exception as e:  # collector down: drop, never propagate
            self.failed_posts += 1
            if self.failed_posts in (1, 10, 100):
                log.warning("OTLP export to %s failing (%s: %s)",
                            self.url, type(e).__name__, e)


_active: Optional[OtlpExporter] = None


def setup_otlp(endpoint: str, node_id: bytes) -> OtlpExporter:
    """Wire an exporter into the process tracer (ref:
    tracing_setup.rs init_tracing: instance id = first 8 node-id
    bytes). Enables span recording if it wasn't already."""
    global _active
    from .tracing import tracer

    exp = OtlpExporter(endpoint, node_id[:8].hex()).start()
    tracer.sinks.append(exp.sink)
    tracer.enabled = True
    _active = exp
    return exp
