"""Exclusive per-metadata-dir process lock.

Guards offline maintenance against a live server: an offline counter
recount racing a live count() would rewrite totals that then win the
CRDT merge cluster-wide with stale values (see IndexCounter.recount).
The running server holds `{metadata_dir}/garage.lock` for its
lifetime; `garage repair-offline` and `convert-db` take the same lock
and refuse to start while it is held. flock(2) locks are released by
the kernel if the holder dies, so a crash never wedges maintenance.
"""

from __future__ import annotations

import fcntl
import os


class AlreadyLocked(RuntimeError):
    pass


def acquire(meta_dir: str, role: str) -> int:
    """Take the exclusive meta-dir lock; -> fd to pass to release().
    Raises AlreadyLocked (naming the holder) if another process has it."""
    os.makedirs(meta_dir, exist_ok=True)
    path = os.path.join(meta_dir, "garage.lock")
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        holder = ""
        try:
            holder = os.read(fd, 256).decode(errors="replace").strip()
        except OSError:
            pass
        os.close(fd)
        raise AlreadyLocked(
            f"metadata dir {meta_dir} is in use by "
            f"{holder or 'another process'} — stop it before running "
            f"offline maintenance") from None
    os.ftruncate(fd, 0)
    os.write(fd, f"{role} pid={os.getpid()}".encode())
    return fd


def release(fd: int) -> None:
    try:
        fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)
