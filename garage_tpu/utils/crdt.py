"""CRDT library: merge-based conflict-free replicated data types.

Ref parity: src/util/crdt/ (crdt.rs:19-59 Crdt trait; lww.rs Lww; lww_map.rs
LwwMap; map.rs Map; bool.rs Bool; deletable.rs Deletable).

A Crdt value supports `merge(other)` which must be commutative, associative
and idempotent. All table entries are CRDTs; replica divergence is resolved by
merging, never by coordination.

Values here are immutable-by-convention: merge() returns a NEW value. (The
reference mutates in place; a functional style composes better with the
msgpack encoding and with property tests.)
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Generic, Iterator, Optional, Tuple, TypeVar

T = TypeVar("T")
K = TypeVar("K")
V = TypeVar("V")


class Crdt:
    """Base protocol. merge must be commutative/associative/idempotent."""

    def merge(self, other: "Crdt") -> "Crdt":
        raise NotImplementedError


def merge_auto(a: Any, b: Any) -> Any:
    """Merge two values: CRDTs merge; plain Ord values take the max.

    ref: AutoCrdt (src/util/crdt/crdt.rs:43-59) — max-merge via Ord for
    primitives, recursive merge for CRDT members.
    """
    if isinstance(a, Crdt):
        return a.merge(b)
    if a == b:
        return a
    try:
        return max(a, b)
    except TypeError:
        # unordered values (dicts, mixed types): deterministic tie-break
        # so merge stays commutative
        return max(a, b, key=repr)


def now_msec() -> int:
    return int(time.time() * 1000)


@dataclass(frozen=True)
class Lww(Crdt, Generic[T]):
    """Last-write-wins register: (timestamp, value); ties break on value.

    ref: src/util/crdt/lww.rs:41-114. As in the reference, `update` bumps the
    timestamp to max(now, ts+1) so a node with a slow clock still wins over
    its own previous write.
    """

    ts: int
    value: T

    @staticmethod
    def new(value: T, ts: Optional[int] = None) -> "Lww[T]":
        return Lww(now_msec() if ts is None else ts, value)

    def update(self, value: T) -> "Lww[T]":
        return Lww(max(now_msec(), self.ts + 1), value)

    # Migrate-friendly plain encoding
    def pack(self, pack_value=lambda v: v) -> list:
        return [self.ts, pack_value(self.value)]

    @staticmethod
    def unpack(raw: list, unpack_value=lambda v: v) -> "Lww":
        return Lww(raw[0], unpack_value(raw[1]))

    def merge(self, other: "Lww[T]") -> "Lww[T]":
        if other.ts > self.ts:
            return other
        if other.ts == self.ts:
            # deterministic tie-break: merge values (max for plain values)
            return Lww(self.ts, merge_auto(self.value, other.value))
        return self


@dataclass(frozen=True)
class Bool(Crdt):
    """True-wins boolean. ref: src/util/crdt/bool.rs"""

    value: bool

    def merge(self, other: "Bool") -> "Bool":
        return Bool(self.value or other.value)


class LwwMap(Crdt, Generic[K, V]):
    """Map of K -> Lww[V]; per-key last-write-wins, no deletion (use a
    tombstone value such as None/Deletable). ref: src/util/crdt/lww_map.rs.

    Stored as an immutable dict; iteration order is sorted key order to keep
    encodings canonical.
    """

    __slots__ = ("_items",)

    def __init__(self, items: Optional[dict] = None):
        self._items: dict = dict(items) if items else {}

    @staticmethod
    def from_item(k: K, lww: Lww) -> "LwwMap":
        return LwwMap({k: lww})

    def get(self, k: K) -> Optional[V]:
        lww = self._items.get(k)
        return lww.value if lww is not None else None

    def get_lww(self, k: K) -> Optional[Lww]:
        return self._items.get(k)

    def insert(self, k: K, value: V) -> "LwwMap":
        prev = self._items.get(k)
        lww = prev.update(value) if prev is not None else Lww.new(value)
        d = dict(self._items)
        d[k] = lww
        return LwwMap(d)

    def items(self) -> Iterator[Tuple[K, V]]:
        for k in sorted(self._items):
            yield k, self._items[k].value

    def items_lww(self) -> Iterator[Tuple[K, Lww]]:
        for k in sorted(self._items):
            yield k, self._items[k]

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, k: K) -> bool:
        return k in self._items

    def __eq__(self, other) -> bool:
        return isinstance(other, LwwMap) and self._items == other._items

    def merge(self, other: "LwwMap") -> "LwwMap":
        d = dict(self._items)
        for k, lww in other._items.items():
            mine = d.get(k)
            d[k] = lww if mine is None else mine.merge(lww)
        return LwwMap(d)


class CrdtMap(Crdt, Generic[K, V]):
    """Map of K -> V where V is itself merged on conflict (grow-only keys).

    ref: src/util/crdt/map.rs — used e.g. for Version.blocks.
    """

    __slots__ = ("_items",)

    def __init__(self, items: Optional[dict] = None):
        self._items: dict = dict(items) if items else {}

    def put(self, k: K, v: V) -> "CrdtMap":
        d = dict(self._items)
        mine = d.get(k)
        d[k] = v if mine is None else merge_auto(mine, v)
        return CrdtMap(d)

    def get(self, k: K) -> Optional[V]:
        return self._items.get(k)

    def items(self) -> Iterator[Tuple[K, V]]:
        for k in sorted(self._items):
            yield k, self._items[k]

    def clear(self) -> "CrdtMap":
        return CrdtMap()

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, k) -> bool:
        return k in self._items

    def __eq__(self, other) -> bool:
        return isinstance(other, CrdtMap) and self._items == other._items

    def merge(self, other: "CrdtMap") -> "CrdtMap":
        d = dict(self._items)
        for k, v in other._items.items():
            mine = d.get(k)
            d[k] = v if mine is None else merge_auto(mine, v)
        return CrdtMap(d)


@dataclass(frozen=True)
class Deletable(Crdt, Generic[T]):
    """Present(value) or Deleted; Deleted wins over Present on merge when
    timestamps are handled by an enclosing Lww. ref: src/util/crdt/deletable.rs
    (there, deletion wins; value merge otherwise).
    """

    value: Optional[T]  # None = deleted

    @staticmethod
    def present(v: T) -> "Deletable[T]":
        return Deletable(v)

    @staticmethod
    def deleted() -> "Deletable[T]":
        return Deletable(None)

    @property
    def is_deleted(self) -> bool:
        return self.value is None

    def merge(self, other: "Deletable[T]") -> "Deletable[T]":
        if self.value is None or other.value is None:
            return Deletable(None)
        return Deletable(merge_auto(self.value, other.value))
