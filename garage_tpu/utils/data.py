"""Core data types: 32-byte ids, hashes, hex codecs.

Ref parity: src/util/data.rs:9-177 (FixedBytes32 = Uuid = Hash, sha256sum,
blake2sum, fasthash, gen_uuid). Design difference: the block *content* hash in
this framework is a parallel tree hash (ops/treehash.py) so it can run batched
on TPU; blake2b-256 remains the metadata/item hash exactly like the reference.
"""

from __future__ import annotations

import hashlib
import os

# A FixedBytes32 is just `bytes` of length 32. We keep plain bytes (hashable,
# comparable, serializable) rather than a wrapper class; helpers below enforce
# the invariant where it matters.

Hash = bytes  # 32 bytes
Uuid = bytes  # 32 bytes

ZERO_HASH: Hash = b"\x00" * 32


def check32(b: bytes) -> bytes:
    if len(b) != 32:
        raise ValueError(f"expected 32 bytes, got {len(b)}")
    return b


def sha256sum(data: bytes) -> Hash:
    """ref: src/util/data.rs:114-122"""
    return hashlib.sha256(data).digest()


def blake2sum(data: bytes) -> Hash:
    """blake2b-256 — the metadata/item hash. ref: src/util/data.rs:124-132"""
    return hashlib.blake2b(data, digest_size=32).digest()


def fasthash(data: bytes) -> int:
    """Fast non-cryptographic 64-bit hash (ref xxh3: src/util/data.rs:134-143).

    xxhash is not available in this image; blake2b-8byte is the stand-in.
    Used only for in-memory sharding decisions, never persisted.
    """
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


def gen_uuid() -> Uuid:
    """Random 32-byte uuid. ref: src/util/data.rs:145-150"""
    return os.urandom(32)


def hex_of(h: bytes) -> str:
    return h.hex()


def hash_of_hex(s: str) -> Hash:
    return check32(bytes.fromhex(s))


def debug_short(h: bytes) -> str:
    """First 8 hex chars, for logs. ref: src/util/data.rs hexdump style."""
    return h[:4].hex()
