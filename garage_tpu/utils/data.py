"""Core data types: 32-byte ids, hashes, hex codecs.

Ref parity: src/util/data.rs:9-177 (FixedBytes32 = Uuid = Hash, sha256sum,
blake2sum, fasthash, gen_uuid). Design difference: the block *content* hash in
this framework is a parallel tree hash (ops/treehash.py) so it can run batched
on TPU; blake2b-256 remains the metadata/item hash exactly like the reference.
"""

from __future__ import annotations

import hashlib
import os

# A FixedBytes32 is just `bytes` of length 32. We keep plain bytes (hashable,
# comparable, serializable) rather than a wrapper class; helpers below enforce
# the invariant where it matters.

Hash = bytes  # 32 bytes
Uuid = bytes  # 32 bytes

ZERO_HASH: Hash = b"\x00" * 32


def check32(b: bytes) -> bytes:
    if len(b) != 32:
        raise ValueError(f"expected 32 bytes, got {len(b)}")
    return b


def sha256sum(data: bytes) -> Hash:
    """ref: src/util/data.rs:114-122"""
    return hashlib.sha256(data).digest()


def blake2sum(data: bytes) -> Hash:
    """blake2b-256 — the metadata/item hash. ref: src/util/data.rs:124-132"""
    return hashlib.blake2b(data, digest_size=32).digest()


def blake3sum(data: bytes) -> Hash:
    """BLAKE3-256 — the block *content* hash. Chosen over the
    reference's sequential blake2 (src/util/data.rs:124-132) because its
    chunk tree batches onto the TPU (ops/treehash.py); the native C
    kernel serves the host path, the pure-Python tree is the last-resort
    fallback. All three produce identical digests (tests/test_treehash)."""
    global _b3_impl
    if _b3_impl is None:
        try:
            from ..native import blake3 as impl

            impl(b"")  # force build/load now, not mid-request
        except Exception:
            from ..ops.treehash import blake3_py as impl
        _b3_impl = impl
    return _b3_impl(data)


_b3_impl = None

# The CLUSTER-WIDE content-hash algorithm (process-global by design:
# content addresses must agree across every node, so per-instance algos
# make no sense — multiple in-process Garage instances share it, and
# set_content_hash_algo warns if configs disagree). "blake3" is the
# native default; "blake2" mirrors the reference for stores migrated
# from it. Verification paths try the configured algo first, then the
# other, so mixed-algo stores stay readable during a migration.
_CONTENT_ALGOS = {"blake3": blake3sum, "blake2": blake2sum}
_content_algo = "blake3"
_content_algo_pinned = False


def set_content_hash_algo(algo: str) -> None:
    global _content_algo, _content_algo_pinned
    if algo not in _CONTENT_ALGOS:
        raise ValueError(f"unknown content hash algo {algo!r}")
    if _content_algo_pinned and algo != _content_algo:
        import logging

        logging.getLogger("garage_tpu.utils").warning(
            "content hash algo changed %s -> %s; in-process instances "
            "share one algorithm — mixed configs are a misconfiguration",
            _content_algo, algo)
    _content_algo = algo
    _content_algo_pinned = True


def content_hash(data: bytes) -> Hash:
    return _CONTENT_ALGOS[_content_algo](data)


def content_hash_matches(data: bytes, hash32: bytes) -> bool:
    """True if `data` hashes to `hash32` under the configured algo or,
    failing that, any other known algo (migration tolerance)."""
    if content_hash(data) == hash32:
        return True
    return any(fn(data) == hash32 for name, fn in _CONTENT_ALGOS.items()
               if name != _content_algo)


def fasthash(data: bytes) -> int:
    """Fast non-cryptographic 64-bit hash (ref xxh3: src/util/data.rs:134-143).

    xxhash is not available in this image; blake2b-8byte is the stand-in.
    Used only for in-memory sharding decisions, never persisted.
    """
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


def gen_uuid() -> Uuid:
    """Random 32-byte uuid. ref: src/util/data.rs:145-150"""
    return os.urandom(32)


def hex_of(h: bytes) -> str:
    return h.hex()


def hash_of_hex(s: str) -> Hash:
    return check32(bytes.fromhex(s))


def debug_short(h: bytes) -> str:
    """First 8 hex chars, for logs. ref: src/util/data.rs hexdump style."""
    return h[:4].hex()
