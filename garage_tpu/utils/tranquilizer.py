"""Tranquilizer: adaptive throttle for background workers.

Ref parity: src/util/tranquilizer.rs:21-78 — after each unit of work taking
`d` seconds, sleep `tranquility × avg(d)` so a worker with tranquility t uses
at most 1/(t+1) of a core/disk.
"""

from __future__ import annotations

import time
from collections import deque


class Tranquilizer:
    def __init__(self, max_observations: int = 10):
        self._obs: deque[float] = deque(maxlen=max_observations)
        self._last_start: float | None = None

    def reset(self) -> None:
        self._last_start = time.monotonic()

    def tranquilize_duration(self, tranquility: int) -> float:
        """Record the duration since reset(); return how long to sleep."""
        if self._last_start is None:
            return 0.0
        d = time.monotonic() - self._last_start
        self._obs.append(d)
        self._last_start = None
        if not self._obs or tranquility <= 0:
            return 0.0
        avg = sum(self._obs) / len(self._obs)
        return tranquility * avg
