"""Lightweight in-process metrics: labeled counters + duration stats.

Ref parity: src/util/metrics.rs + the per-subsystem metric modules
(rpc/metrics.rs, table/metrics.rs, block/metrics.rs,
api/common/generic_server.rs). The reference uses OpenTelemetry; this
build keeps a dependency-free registry that the admin /metrics endpoint
renders in Prometheus text format. Durations aggregate as
count / sum / max so rates and averages are derivable.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Iterator, Optional

# The metric naming contract: <subsystem>_<snake_case>. The static rule
# GL07 (garage_tpu/analysis/) enforces it at review time on literal
# names; this runtime check enforces the SAME regex at registration
# time so a dynamically formatted name (f"qos_{key}") that escapes the
# static net still fails fast in debug mode. Keep the two in lockstep:
# the analyzer imports this regex.
METRIC_NAME_RE = re.compile(
    r"^(api|qos|cache|chaos|rpc|block|table|resync|resize|scrub|s3|meta"
    r"|gateway|feeder)_[a-z0-9_]+$")

# Debug-mode strictness: on under GARAGE_METRICS_STRICT=1 (the test
# suite sets it), off in production — a bad metric name must never
# take down a serving node. "0"/"false"/"no" disable explicitly.
STRICT_METRIC_NAMES = os.environ.get(
    "GARAGE_METRICS_STRICT", "").lower() not in ("", "0", "false", "no")


class _Series:
    __slots__ = ("count", "total", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        # (name, labels-tuple) -> _Series
        self._series: dict[tuple, _Series] = {}

    def _get(self, name: str, labels: tuple) -> _Series:
        key = (name, labels)
        s = self._series.get(key)
        if s is None:
            if STRICT_METRIC_NAMES and not METRIC_NAME_RE.match(name):
                raise ValueError(
                    f"metric name {name!r} violates the naming scheme "
                    f"{METRIC_NAME_RE.pattern!r} (GL07); use a static "
                    "<subsystem>_<snake_case> name")
            with self._lock:
                s = self._series.setdefault(key, _Series())
        return s

    def inc(self, name: str, value: float = 1, **labels) -> None:
        s = self._get(name, tuple(sorted(labels.items())))
        s.count += 1
        s.total += value
        s.max = max(s.max, value)

    def observe(self, name: str, seconds: float, **labels) -> None:
        self.inc(name, seconds, **labels)

    def timer(self, name: str, **labels) -> "_Timer":
        return _Timer(self, name, labels)

    def totals(self, name: str, **match) -> tuple[int, float]:
        """Aggregate (count, sum) across every series of `name` whose
        labels include all of `match` (qos governor latency source)."""
        count, total = 0, 0.0
        want = set((k, str(v)) for k, v in match.items())
        for (n, labels), s in list(self._series.items()):
            if n != name:
                continue
            if want and not want.issubset(
                    (k, str(v)) for k, v in labels):
                continue
            count += s.count
            total += s.total
        return count, total

    def series(self, name: str) -> list[tuple[dict, int, float, float]]:
        """Every series of `name` as (labels, count, sum, max) — the
        admin API's per-label readouts (e.g. resize_phase_seconds by
        phase) without reaching into internals."""
        return [(dict(labels), s.count, s.total, s.max)
                for (n, labels), s in list(self._series.items())
                if n == name]

    def render(self) -> Iterator[str]:
        """Prometheus text lines: <name>_count, <name>_sum, <name>_max."""
        # snapshot under the lock: render runs in a scrape worker thread
        # while the loop (and the compaction thread) insert new series
        with self._lock:
            items = sorted(self._series.items())
        seen_help = set()
        for (name, labels), s in items:
            if name not in seen_help:
                seen_help.add(name)
                yield f"# TYPE {name}_count counter"
            lab = ",".join(f'{k}="{v}"' for k, v in labels)
            suffix = f"{{{lab}}}" if lab else ""
            yield f"{name}_count{suffix} {s.count}"
            yield f"{name}_sum{suffix} {s.total:.6f}"
            yield f"{name}_max{suffix} {s.max:.6f}"


class _Timer:
    __slots__ = ("reg", "name", "labels", "t0")

    def __init__(self, reg: MetricsRegistry, name: str, labels: dict):
        self.reg = reg
        self.name = name
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.reg.observe(self.name, time.perf_counter() - self.t0,
                         **self.labels)
        return False


_global: Optional[MetricsRegistry] = None


def registry() -> MetricsRegistry:
    """Process-wide registry (one server process = one node)."""
    global _global
    if _global is None:
        _global = MetricsRegistry()
    return _global
