"""K2V RPC: insert routing + poll subscriptions.

Ref parity: src/model/k2v/rpc.rs. Inserts are NOT applied by the API
gateway node: they are routed to one of the partition's storage nodes
(quorum 1) which applies the DVVS update under its *own* node id — this
keeps vector clocks bounded by the replication factor instead of growing
with every gateway that ever handled a write. The storage node then
propagates the merged item through the normal table quorum write.

PollItem long-polling (ref rpc.rs:206-260, sub.rs): the API node asks
every storage node to wake it when the item's causal context becomes
newer than the client's token; first non-empty response wins.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ...net.message import PRIO_NORMAL
from ...table.schema import partition_hash
from ...utils.crdt import now_msec
from .causality import CausalContext
from .item_table import K2VItem, partition_pk

log = logging.getLogger("garage_tpu.model.k2v")

_TIMESTAMP_KEY = b"timestamp"


class SubscriptionManager:
    """Wakes local pollers when an item changes (ref: k2v/sub.rs).

    notify() can fire from worker threads (table updates apply via
    asyncio.to_thread), so wakeups go through call_soon_threadsafe on
    the loop captured at subscribe time, and the registry is
    lock-protected."""

    def __init__(self):
        import threading

        self._events: dict[tuple, list] = {}  # key -> [(loop, Event)]
        self._lock = threading.Lock()

    def _key(self, item: K2VItem) -> tuple:
        return (item.bucket_id, item.partition_key_str, item.sort_key_str)

    def notify(self, item: K2VItem) -> None:
        with self._lock:
            waiters = self._events.pop(self._key(item), [])
        for loop, ev in waiters:
            loop.call_soon_threadsafe(ev.set)

    def subscribe(self, bucket_id: bytes, pk: str, sk: str) -> asyncio.Event:
        ev = asyncio.Event()
        loop = asyncio.get_running_loop()
        with self._lock:
            self._events.setdefault((bucket_id, pk, sk), []).append(
                (loop, ev))
        return ev

    def unsubscribe(self, bucket_id: bytes, pk: str, sk: str,
                    ev: asyncio.Event) -> None:
        with self._lock:
            lst = self._events.get((bucket_id, pk, sk))
            if not lst:
                return
            self._events[(bucket_id, pk, sk)] = [
                (lp, e) for lp, e in lst if e is not ev]
            if not self._events[(bucket_id, pk, sk)]:
                del self._events[(bucket_id, pk, sk)]


class K2VRpcHandler:
    def __init__(self, system, db, item_table, subscriptions):
        self.system = system
        self.item_table = item_table
        self.subscriptions = subscriptions
        self.local_timestamp = db.open_tree("k2v_local_timestamp")
        self.endpoint = system.netapp.endpoint("garage_tpu/k2v").set_handler(
            self._handle)

    # ---- public interface (API server calls these) ---------------------

    def _storage_nodes(self, bucket_id: bytes, partition_key: str
                       ) -> list[bytes]:
        ph = partition_hash(partition_pk(bucket_id, partition_key))
        return sorted(self.item_table.replication.storage_nodes(ph))

    async def insert(self, bucket_id: bytes, partition_key: str,
                     sort_key: str, causal_context: Optional[CausalContext],
                     value: Optional[bytes]) -> None:
        who = self._storage_nodes(bucket_id, partition_key)
        payload = {
            "op": "insert",
            "bucket": bucket_id,
            "pk": partition_key,
            "sk": sort_key,
            "ct": (causal_context.serialize()
                   if causal_context is not None else None),
            "value": value,
        }
        await self._call_any(who, payload)

    async def insert_batch(self, bucket_id: bytes,
                           items: list[tuple[str, str,
                                             Optional[CausalContext],
                                             Optional[bytes]]]) -> None:
        by_nodes: dict[tuple, list] = {}
        for pk, sk, ct, value in items:
            who = tuple(self._storage_nodes(bucket_id, pk))
            by_nodes.setdefault(who, []).append(
                [pk, sk, ct.serialize() if ct is not None else None, value])
        await asyncio.gather(*[
            self._call_any(list(who), {"op": "insert_many",
                                       "bucket": bucket_id,
                                       "items": batch})
            for who, batch in by_nodes.items()
        ])

    async def poll_item(self, bucket_id: bytes, partition_key: str,
                        sort_key: str, causal_context: CausalContext,
                        timeout: float) -> Optional[K2VItem]:
        """Wait until the item is newer than `causal_context`; None on
        timeout. First storage node to see a newer version answers."""
        who = self._storage_nodes(bucket_id, partition_key)
        payload = {"op": "poll_item", "bucket": bucket_id,
                   "pk": partition_key, "sk": sort_key,
                   "ct": causal_context.serialize(),
                   "timeout_ms": int(timeout * 1000)}

        async def one(node):
            resp, _ = await self.endpoint.call(node, payload, PRIO_NORMAL,
                                               timeout=timeout + 10.0)
            if resp.get("item") is None:
                raise TimeoutError("poll timed out on peer")
            return resp["item"]

        tasks = [asyncio.create_task(one(n)) for n in who]
        try:
            while tasks:
                done, tasks_set = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED)
                tasks = list(tasks_set)
                for t in done:
                    if t.exception() is None:
                        from ...utils import migrate

                        return migrate.decode(K2VItem, t.result())
            return None
        finally:
            for t in tasks:
                t.cancel()

    # ---- local application --------------------------------------------

    async def _call_any(self, who: list[bytes], payload) -> None:
        """try_call_many with quorum 1 (ref: rpc.rs insert)."""
        from ...rpc.rpc_helper import RequestStrategy

        await self.item_table.rpc.try_call_many(
            self.endpoint, who, payload,
            RequestStrategy(quorum=1, prio=PRIO_NORMAL, timeout=30.0),
        )

    def _local_insert(self, bucket_id: bytes, pk: str, sk: str,
                      ct_str: Optional[str],
                      value: Optional[bytes]) -> Optional[K2VItem]:
        """Apply the DVVS update locally under OUR node id, atomically
        with the monotonic local-timestamp bump, through the full
        trigger/merkle path (ref: rpc.rs local_insert)."""
        ct = CausalContext.parse(ct_str) if ct_str else None
        data = self.item_table.data

        def apply(tx, old):
            old_ts_raw = tx.get(self.local_timestamp, _TIMESTAMP_KEY)
            old_ts = (int.from_bytes(old_ts_raw, "big")
                      if old_ts_raw else 0)
            ent = old if old is not None else K2VItem(bucket_id, pk, sk)
            new_ts = ent.update(self.system.id, ct, value,
                                max(old_ts, now_msec()))
            tx.insert(self.local_timestamp, _TIMESTAMP_KEY,
                      new_ts.to_bytes(8, "big"))
            return ent

        return data.update_entry_with(partition_pk(bucket_id, pk),
                                      sk.encode(), apply)

    # ---- server side ---------------------------------------------------

    async def _handle(self, from_node, payload, stream):
        op = payload["op"]
        if op == "insert":
            item = self._local_insert(payload["bucket"], payload["pk"],
                                      payload["sk"], payload.get("ct"),
                                      payload.get("value"))
            if item is not None:
                await self.item_table.insert(item)
            return {"ok": True}
        if op == "insert_many":
            def apply_all():
                out = []
                for pk, sk, ct, value in payload["items"]:
                    item = self._local_insert(payload["bucket"], pk, sk,
                                              ct, value)
                    if item is not None:
                        out.append(item)
                return out

            # bulk transactions off the event loop (db.py convention)
            updated = await asyncio.to_thread(apply_all)
            for item in updated:
                await self.item_table.insert(item)
            return {"ok": True}
        if op == "poll_item":
            item = await self._handle_poll(
                payload["bucket"], payload["pk"], payload["sk"],
                payload["ct"], payload["timeout_ms"] / 1000.0)
            from ...utils import migrate

            return {"item": migrate.encode(item) if item else None}
        raise ValueError(f"unknown k2v op {op!r}")

    async def _handle_poll(self, bucket_id: bytes, pk: str, sk: str,
                           ct_str: str, timeout: float
                           ) -> Optional[K2VItem]:
        ct = CausalContext.parse(ct_str)
        if ct is None:
            raise ValueError("bad causality token")
        deadline = time.monotonic() + timeout
        while True:
            ev = self.subscriptions.subscribe(bucket_id, pk, sk)
            try:
                item = self._read_local(bucket_id, pk, sk)
                if item is not None and item.causal_context(
                        ).is_newer_than(ct):
                    return item
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    return None
            finally:
                self.subscriptions.unsubscribe(bucket_id, pk, sk, ev)

    def _read_local(self, bucket_id: bytes, pk: str,
                    sk: str) -> Optional[K2VItem]:
        raw = self.item_table.data.read_entry(
            partition_pk(bucket_id, pk), sk.encode())
        return (self.item_table.data.decode_stored(raw)
                if raw is not None else None)
