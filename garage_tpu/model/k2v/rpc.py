"""K2V RPC: insert routing + poll subscriptions.

Ref parity: src/model/k2v/rpc.rs. Inserts are NOT applied by the API
gateway node: they are routed to one of the partition's storage nodes
(quorum 1) which applies the DVVS update under its *own* node id — this
keeps vector clocks bounded by the replication factor instead of growing
with every gateway that ever handled a write. The storage node then
propagates the merged item through the normal table quorum write.

PollItem long-polling (ref rpc.rs:206-260, sub.rs): the API node asks
every storage node to wake it when the item's causal context becomes
newer than the client's token; first non-empty response wins.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ...net.message import PRIO_NORMAL
from ...table.schema import partition_hash
from ...utils.crdt import now_msec
from .causality import CausalContext
from .item_table import K2VItem, partition_pk

log = logging.getLogger("garage_tpu.model.k2v")

_TIMESTAMP_KEY = b"timestamp"


class PeerPollTimeout(Exception):
    """A storage node answered 'nothing changed within your window' —
    distinct from a transport timeout reaching that node."""


class SubscriptionManager:
    """Wakes local pollers when an item changes (ref: k2v/sub.rs).

    notify() can fire from worker threads (table updates apply via
    asyncio.to_thread), so wakeups go through call_soon_threadsafe on
    the loop captured at subscribe time, and the registry is
    lock-protected."""

    def __init__(self):
        import threading

        self._events: dict[tuple, list] = {}  # key -> [(loop, Event)]
        self._lock = threading.Lock()

    def _key(self, item: K2VItem) -> tuple:
        return (item.bucket_id, item.partition_key_str, item.sort_key_str)

    def notify(self, item: K2VItem) -> None:
        with self._lock:
            waiters = self._events.pop(self._key(item), [])
            # partition-level subscribers (PollRange) wake on ANY item
            # change in the partition
            waiters += self._events.pop(
                (item.bucket_id, item.partition_key_str, None), [])
        for loop, ev in waiters:
            loop.call_soon_threadsafe(ev.set)

    def subscribe(self, bucket_id: bytes, pk: str, sk: str) -> asyncio.Event:
        ev = asyncio.Event()
        loop = asyncio.get_running_loop()
        with self._lock:
            self._events.setdefault((bucket_id, pk, sk), []).append(
                (loop, ev))
        return ev

    def unsubscribe(self, bucket_id: bytes, pk: str, sk: str,
                    ev: asyncio.Event) -> None:
        with self._lock:
            lst = self._events.get((bucket_id, pk, sk))
            if not lst:
                return
            self._events[(bucket_id, pk, sk)] = [
                (lp, e) for lp, e in lst if e is not ev]
            if not self._events[(bucket_id, pk, sk)]:
                del self._events[(bucket_id, pk, sk)]


class K2VRpcHandler:
    def __init__(self, system, db, item_table, subscriptions):
        self.system = system
        self.item_table = item_table
        self.subscriptions = subscriptions
        self.local_timestamp = db.open_tree("k2v_local_timestamp")
        self.endpoint = system.netapp.endpoint("garage_tpu/k2v").set_handler(
            self._handle)

    # ---- public interface (API server calls these) ---------------------

    def _storage_nodes(self, bucket_id: bytes, partition_key: str
                       ) -> list[bytes]:
        ph = partition_hash(partition_pk(bucket_id, partition_key))
        return sorted(self.item_table.replication.storage_nodes(ph))

    async def insert(self, bucket_id: bytes, partition_key: str,
                     sort_key: str, causal_context: Optional[CausalContext],
                     value: Optional[bytes]) -> None:
        who = self._storage_nodes(bucket_id, partition_key)
        payload = {
            "op": "insert",
            "bucket": bucket_id,
            "pk": partition_key,
            "sk": sort_key,
            "ct": (causal_context.serialize()
                   if causal_context is not None else None),
            "value": value,
        }
        await self._call_any(who, payload)

    async def insert_batch(self, bucket_id: bytes,
                           items: list[tuple[str, str,
                                             Optional[CausalContext],
                                             Optional[bytes]]]) -> None:
        by_nodes: dict[tuple, list] = {}
        for pk, sk, ct, value in items:
            who = tuple(self._storage_nodes(bucket_id, pk))
            by_nodes.setdefault(who, []).append(
                [pk, sk, ct.serialize() if ct is not None else None, value])
        await asyncio.gather(*[
            self._call_any(list(who), {"op": "insert_many",
                                       "bucket": bucket_id,
                                       "items": batch})
            for who, batch in by_nodes.items()
        ])

    async def _poll_first_success(self, who: list[bytes], payload,
                                  timeout: float, empty_key: str):
        """Fan out a poll RPC; first non-empty response wins. Returns
        None only for genuine peer-side timeouts — when every peer
        failed HARD (unreachable etc.) this raises so the API answers
        an error instead of disguising an outage as 'no changes'."""
        async def one(node):
            resp, _ = await self.endpoint.call(node, payload, PRIO_NORMAL,
                                               timeout=timeout + 10.0)
            if resp.get(empty_key) is None:
                # dedicated sentinel: on py3.11+ asyncio.TimeoutError IS
                # TimeoutError, so a transport timeout to an unreachable
                # node must not masquerade as a peer-side "no changes"
                raise PeerPollTimeout("poll timed out on peer")
            return resp

        tasks = [asyncio.create_task(one(n)) for n in who]
        saw_timeout = False
        errors: list[Exception] = []
        try:
            while tasks:
                done, tasks_set = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED)
                tasks = list(tasks_set)
                for t in done:
                    e = t.exception()
                    if e is None:
                        return t.result()
                    if isinstance(e, PeerPollTimeout):
                        saw_timeout = True
                    else:
                        errors.append(e)
            if saw_timeout:
                return None
            raise RuntimeError(
                f"poll failed on all {len(who)} storage nodes: "
                f"{errors[:2]}")
        finally:
            for t in tasks:
                t.cancel()

    async def poll_item(self, bucket_id: bytes, partition_key: str,
                        sort_key: str, causal_context: CausalContext,
                        timeout: float) -> Optional[K2VItem]:
        """Wait until the item is newer than `causal_context`; None on
        timeout. First storage node to see a newer version answers."""
        who = self._storage_nodes(bucket_id, partition_key)
        payload = {"op": "poll_item", "bucket": bucket_id,
                   "pk": partition_key, "sk": sort_key,
                   "ct": causal_context.serialize(),
                   "timeout_ms": int(timeout * 1000)}
        resp = await self._poll_first_success(who, payload, timeout,
                                              "item")
        if resp is None:
            return None
        from ...utils import migrate

        return migrate.decode(K2VItem, resp["item"])

    async def poll_range(self, bucket_id: bytes, partition_key: str,
                         prefix: Optional[str], start: Optional[str],
                         end: Optional[str], seen_str: Optional[str],
                         timeout: float):
        """Wait until any item in the range changes vs the seen marker;
        -> (changed items, new marker string) or None on timeout
        (ref: rpc.rs poll_range + seen.rs RangeSeenMarker)."""
        from .seen import RangeSeenMarker

        if RangeSeenMarker.parse(seen_str or "") is None:
            raise ValueError("bad seen marker")
        who = self._storage_nodes(bucket_id, partition_key)
        payload = {"op": "poll_range", "bucket": bucket_id,
                   "pk": partition_key, "prefix": prefix, "start": start,
                   "end": end, "seen": seen_str or "",
                   "timeout_ms": int(timeout * 1000)}
        resp = await self._poll_first_success(who, payload, timeout,
                                              "items")
        if resp is None:
            return None
        from ...utils import migrate

        items = [migrate.decode(K2VItem, raw) for raw in resp["items"]]
        return items, resp["seen"]

    _POLL_PAGE = 500
    _POLL_MAX_CHANGED = 1000

    def _range_changed(self, bucket_id: bytes, pk: str,
                       prefix: Optional[str], start: Optional[str],
                       end: Optional[str], marker) -> list[K2VItem]:
        """Scan the WHOLE range in pages — a one-page horizon would make
        items past it permanently invisible to pollers. Output is capped
        (the marker only advances for returned items, so the remainder
        re-surfaces immediately on the next poll).

        Raw-cursor scan (ISSUE 9): pages come from read_range_raw, so
        the sort key for the resume cursor and the marker lookup is
        sliced off the engine key — pagination never decodes. Each
        ROW still decodes once (honestly: the marker comparison needs
        the stored vector clock, which only the decoded item carries)
        — the real win is that the whole scan+decode loop runs in a
        worker thread (see _handle_poll_range), not on the event
        loop, because at a million keys it is exactly the blocking
        helper GL10 exists to catch."""
        data = self.item_table.data
        out: list[K2VItem] = []
        cursor = start.encode() if start else None
        while True:
            rows, next_cursor = data.read_range_raw(
                partition_pk(bucket_id, pk), cursor,
                self._POLL_PAGE,
                prefix_sk=prefix.encode() if prefix else None,
                end_sk=end.encode() if end else None)
            for sk, raw in rows:
                item = data.decode_stored(raw)
                if marker.is_new(sk.decode("utf-8", "replace"),
                                 item.causal_context()):
                    out.append(item)
                    if len(out) >= self._POLL_MAX_CHANGED:
                        return out
            if next_cursor is None:
                return out
            cursor = next_cursor

    async def _handle_poll_range(self, bucket_id: bytes, pk: str,
                                 prefix, start, end, seen_str: str,
                                 timeout: float):
        from .seen import RangeSeenMarker

        marker = RangeSeenMarker.parse(seen_str)
        if marker is None:
            raise ValueError("bad seen marker")
        deadline = time.monotonic() + timeout
        while True:
            ev = self.subscriptions.subscribe(bucket_id, pk, None)
            try:
                # off-loop: the scan walks and decodes the whole range
                # — at scale that is a multi-ms sqlite/LSM read +
                # decode burst that must not stall the event loop
                changed = await asyncio.to_thread(
                    self._range_changed, bucket_id, pk, prefix,
                    start, end, marker)
                if changed:
                    for item in changed:
                        marker.update(item.sort_key_str,
                                      item.causal_context())
                    return changed, marker.serialize()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    return None
            finally:
                self.subscriptions.unsubscribe(bucket_id, pk, None, ev)

    # ---- local application --------------------------------------------

    async def _call_any(self, who: list[bytes], payload) -> None:
        """try_call_many with quorum 1 (ref: rpc.rs insert).

        hedge=False: this is a WRITE — a hedge against a slow-but-alive
        node would apply the insert under two node ids and surface
        duplicate DVVS siblings. Failover on error (at-least-once)
        stays, as in the reference."""
        from ...rpc.rpc_helper import RequestStrategy

        await self.item_table.rpc.try_call_many(
            self.endpoint, who, payload,
            RequestStrategy(quorum=1, prio=PRIO_NORMAL, timeout=30.0,
                            hedge=False),
        )

    def _local_insert(self, bucket_id: bytes, pk: str, sk: str,
                      ct_str: Optional[str],
                      value: Optional[bytes]) -> Optional[K2VItem]:
        """Apply the DVVS update locally under OUR node id, atomically
        with the monotonic local-timestamp bump, through the full
        trigger/merkle path (ref: rpc.rs local_insert)."""
        ct = CausalContext.parse(ct_str) if ct_str else None
        data = self.item_table.data

        def apply(tx, old):
            old_ts_raw = tx.get(self.local_timestamp, _TIMESTAMP_KEY)
            old_ts = (int.from_bytes(old_ts_raw, "big")
                      if old_ts_raw else 0)
            ent = old if old is not None else K2VItem(bucket_id, pk, sk)
            new_ts = ent.update(self.system.id, ct, value,
                                max(old_ts, now_msec()))
            tx.insert(self.local_timestamp, _TIMESTAMP_KEY,
                      new_ts.to_bytes(8, "big"))
            return ent

        return data.update_entry_with(partition_pk(bucket_id, pk),
                                      sk.encode(), apply)

    # ---- server side ---------------------------------------------------

    async def _handle(self, from_node, payload, stream):
        op = payload["op"]
        if op == "insert":
            # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
            item = self._local_insert(payload["bucket"], payload["pk"],
                                      payload["sk"], payload.get("ct"),
                                      payload.get("value"))
            if item is not None:
                await self.item_table.insert(item)
            return {"ok": True}
        if op == "insert_many":
            def apply_all():
                out = []
                for pk, sk, ct, value in payload["items"]:
                    item = self._local_insert(payload["bucket"], pk, sk,
                                              ct, value)
                    if item is not None:
                        out.append(item)
                return out

            # bulk transactions off the event loop (db.py convention)
            updated = await asyncio.to_thread(apply_all)
            for item in updated:
                await self.item_table.insert(item)
            return {"ok": True}
        if op == "poll_item":
            item = await self._handle_poll(
                payload["bucket"], payload["pk"], payload["sk"],
                payload["ct"], payload["timeout_ms"] / 1000.0)
            from ...utils import migrate

            return {"item": migrate.encode(item) if item else None}
        if op == "poll_range":
            res = await self._handle_poll_range(
                payload["bucket"], payload["pk"], payload.get("prefix"),
                payload.get("start"), payload.get("end"),
                payload.get("seen", ""), payload["timeout_ms"] / 1000.0)
            if res is None:
                return {"items": None, "seen": None}
            from ...utils import migrate

            items, seen = res
            return {"items": [migrate.encode(i) for i in items],
                    "seen": seen}
        raise ValueError(f"unknown k2v op {op!r}")

    async def _handle_poll(self, bucket_id: bytes, pk: str, sk: str,
                           ct_str: str, timeout: float
                           ) -> Optional[K2VItem]:
        ct = CausalContext.parse(ct_str)
        if ct is None:
            raise ValueError("bad causality token")
        deadline = time.monotonic() + timeout
        while True:
            ev = self.subscriptions.subscribe(bucket_id, pk, sk)
            try:
                # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
                item = self._read_local(bucket_id, pk, sk)
                if item is not None and item.causal_context(
                        ).is_newer_than(ct):
                    return item
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    return None
            finally:
                self.subscriptions.unsubscribe(bucket_id, pk, sk, ev)

    def _read_local(self, bucket_id: bytes, pk: str,
                    sk: str) -> Optional[K2VItem]:
        raw = self.item_table.data.read_entry(
            partition_pk(bucket_id, pk), sk.encode())
        return (self.item_table.data.decode_stored(raw)
                if raw is not None else None)
