"""K2V causality: vector clocks and causality tokens.

Ref parity: src/model/k2v/causality.rs:21-120. A CausalContext is a
vector clock over abbreviated 64-bit node ids; its base64url (no pad)
encoding — checksum u64 followed by (node, time) u64 pairs, all
big-endian — is the "causality token" clients echo back on writes to
declare which versions they have seen.
"""

from __future__ import annotations

import base64
from typing import Optional

# node ids in K2V are the first 8 bytes of the 32-byte node uuid
# (ref: causality.rs make_node_id)


def make_node_id(node_uuid: bytes) -> int:
    return int.from_bytes(node_uuid[:8], "big")


VectorClock = dict  # int node id -> int time


def vclock_gt(a: VectorClock, b: VectorClock) -> bool:
    return any(ts > b.get(n, 0) for n, ts in a.items())


def vclock_max(a: VectorClock, b: VectorClock) -> VectorClock:
    out = dict(a)
    for n, ts in b.items():
        out[n] = max(out.get(n, 0), ts)
    return out


class CausalContext:
    __slots__ = ("vector_clock",)

    def __init__(self, vector_clock: Optional[VectorClock] = None):
        self.vector_clock: VectorClock = vector_clock or {}

    def serialize(self) -> str:
        ints = []
        for node, t in sorted(self.vector_clock.items()):
            ints.append(node)
            ints.append(t)
        checksum = 0
        for v in ints:
            checksum ^= v
        raw = checksum.to_bytes(8, "big") + b"".join(
            v.to_bytes(8, "big") for v in ints)
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    @classmethod
    def parse(cls, s: str) -> Optional["CausalContext"]:
        try:
            raw = base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
        except Exception:
            # lint: ignore[GL05] malformed client token -> None is the parse contract (400 upstream)
            return None
        if len(raw) < 8 or len(raw) % 16 != 8:
            return None
        checksum = int.from_bytes(raw[:8], "big")
        vc: VectorClock = {}
        for i in range((len(raw) - 8) // 16):
            node = int.from_bytes(raw[8 + 16 * i:16 + 16 * i], "big")
            t = int.from_bytes(raw[16 + 16 * i:24 + 16 * i], "big")
            vc[node] = t
        check = 0
        for n, t in vc.items():
            check ^= n ^ t
        if check != checksum:
            return None
        return cls(vc)

    def is_newer_than(self, other: "CausalContext") -> bool:
        return vclock_gt(self.vector_clock, other.vector_clock)

    def __eq__(self, other):
        return (isinstance(other, CausalContext)
                and self.vector_clock == other.vector_clock)

    def __repr__(self):
        return f"CausalContext({self.vector_clock})"
