"""RangeSeenMarker: resumable cursor for K2V PollRange.

Ref parity: src/model/k2v/seen.rs. The marker records, per sort key,
the vector clock the client has already seen; a poll returns items
whose causal context carries something newer. Encoding is
base64url(msgpack) with a checksum-free structure (the marker is
client-opaque but server-validated by shape).
"""

from __future__ import annotations

import base64
from typing import Optional

import msgpack

from .causality import CausalContext, vclock_gt, vclock_max


class RangeSeenMarker:
    __slots__ = ("seen",)

    def __init__(self, seen: Optional[dict] = None):
        # sort_key (str) -> vector clock (dict int->int)
        self.seen: dict[str, dict] = seen or {}

    def update(self, sort_key: str, cc: CausalContext) -> None:
        # merge, never overwrite: answers from divergent replicas must
        # only advance the marker or redeliveries ping-pong until the
        # replicas converge
        self.seen[sort_key] = vclock_max(
            self.seen.get(sort_key, {}), cc.vector_clock)

    def is_new(self, sort_key: str, cc: CausalContext) -> bool:
        prev = self.seen.get(sort_key)
        if prev is None:
            return True
        return vclock_gt(cc.vector_clock, prev)

    def serialize(self) -> str:
        raw = msgpack.packb(
            [[sk, sorted(vc.items())] for sk, vc in sorted(self.seen.items())],
            use_bin_type=True)
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    @classmethod
    def parse(cls, s: str) -> Optional["RangeSeenMarker"]:
        if not s:
            return cls()
        try:
            raw = base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
            items = msgpack.unpackb(raw, raw=False)
            return cls({sk: {int(n): int(t) for n, t in vc}
                        for sk, vc in items})
        except Exception:
            # lint: ignore[GL05] malformed client token -> None is the parse contract (400 upstream)
            return None
