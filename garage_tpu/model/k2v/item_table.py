"""K2V item table: DVVS (dotted version vector set) entries.

Ref parity: src/model/k2v/item_table.rs. An item is addressed by
(bucket, partition_key, sort_key) and holds, per writer node, a
DvvsEntry {t_discard, values: [(t, value-or-None)]}. Writes discard
every version covered by the supplied causality token and append a new
timestamped value; concurrent writes on different nodes coexist as
conflicting values until a later write with a merged token discards
them. `None` is the Deleted marker (ref DvvsValue::Deleted).

Table partition key bytes = bucket_id ++ partition_key (utf-8) — blake2
of that matches the reference's K2VItemPartition::hash (blake2 over the
same concatenation).
"""

from __future__ import annotations

from typing import Optional

from ...table.schema import Entry, TableSchema
from .causality import CausalContext, make_node_id

ENTRIES = "entries"
CONFLICTS = "conflicts"
VALUES = "values"
BYTES = "bytes"


def partition_pk(bucket_id: bytes, partition_key: str) -> bytes:
    return bucket_id + partition_key.encode()


class DvvsEntry:
    __slots__ = ("t_discard", "values")

    def __init__(self, t_discard: int = 0,
                 values: Optional[list] = None):
        self.t_discard = t_discard
        self.values: list[tuple[int, Optional[bytes]]] = values or []

    def max_time(self) -> int:
        return max([self.t_discard] + [t for t, _ in self.values])

    def discard(self) -> None:
        self.values = [(t, v) for t, v in self.values if t > self.t_discard]

    def merge(self, other: "DvvsEntry") -> "DvvsEntry":
        out = DvvsEntry(max(self.t_discard, other.t_discard),
                        list(self.values))
        out.discard()
        t_max = out.max_time()
        for t, v in other.values:
            if t > t_max:
                out.values.append((t, v))
        return out

    def pack(self):
        return [self.t_discard, [[t, v] for t, v in self.values]]

    @classmethod
    def unpack(cls, o):
        return cls(o[0], [(t, bytes(v) if v is not None else None)
                          for t, v in o[1]])


class K2VItem(Entry):
    VERSION_MARKER = b"GTk2v01"

    def __init__(self, bucket_id: bytes, partition_key: str, sort_key: str,
                 items: Optional[dict[int, DvvsEntry]] = None):
        self.bucket_id = bucket_id
        self.partition_key_str = partition_key
        self.sort_key_str = sort_key
        self.items: dict[int, DvvsEntry] = items or {}

    # ---- DVVS ops (ref: item_table.rs:71-133) --------------------------

    def update(self, this_node: bytes, context: Optional[CausalContext],
               new_value: Optional[bytes], node_ts: int) -> int:
        """Apply one write; returns the new local timestamp."""
        if context is not None:
            for node, t_discard in context.vector_clock.items():
                e = self.items.get(node)
                if e is not None:
                    e.t_discard = max(e.t_discard, t_discard)
                else:
                    self.items[node] = DvvsEntry(t_discard)
        for e in self.items.values():
            e.discard()
        node_id = make_node_id(this_node)
        e = self.items.setdefault(node_id, DvvsEntry())
        t_new = max(e.max_time() + 1, node_ts + 1)
        e.values.append((t_new, new_value))
        return t_new

    def causal_context(self) -> CausalContext:
        return CausalContext({n: e.max_time()
                              for n, e in self.items.items()})

    def values(self) -> list[Optional[bytes]]:
        out: list[Optional[bytes]] = []
        for _, e in sorted(self.items.items()):
            for _, v in e.values:
                if v not in out:
                    out.append(v)
        return out

    def live_values(self) -> list[bytes]:
        return [v for v in self.values() if v is not None]

    # ---- Entry interface ----------------------------------------------

    def partition_key(self) -> bytes:
        return partition_pk(self.bucket_id, self.partition_key_str)

    def sort_key(self) -> bytes:
        return self.sort_key_str.encode()

    def is_tombstone(self) -> bool:
        vals = self.values()
        return all(v is None for v in vals)

    def merge(self, other: "K2VItem") -> "K2VItem":
        items = dict(self.items)
        for node, e2 in other.items.items():
            e1 = items.get(node)
            items[node] = e1.merge(e2) if e1 is not None else \
                DvvsEntry(e2.t_discard, list(e2.values))
        return K2VItem(self.bucket_id, self.partition_key_str,
                       self.sort_key_str, items)

    def pack(self):
        return [self.bucket_id, self.partition_key_str, self.sort_key_str,
                [[n, e.pack()] for n, e in sorted(self.items.items())]]

    @classmethod
    def unpack(cls, o):
        return cls(bytes(o[0]), o[1], o[2],
                   {n: DvvsEntry.unpack(e) for n, e in o[3]})

    # ---- counted item (ref: item_table.rs counts) ----------------------

    def counter_partition_key(self) -> bytes:
        return self.bucket_id

    def counter_sort_key(self) -> bytes:
        return self.partition_key_str.encode()

    def counts(self) -> list[tuple[str, int]]:
        vals = self.values()
        n_values = sum(1 for v in vals if v is not None)
        return [
            (ENTRIES, 0 if self.is_tombstone() else 1),
            (CONFLICTS, 1 if n_values > 1 else 0),
            (VALUES, n_values),
            (BYTES, sum(len(v) for v in vals if v is not None)),
        ]


class K2VItemTable(TableSchema):
    TABLE_NAME = "k2v_item"
    ENTRY = K2VItem

    def __init__(self, counter: Optional[object] = None,
                 subscriptions: Optional[object] = None):
        self.counter = counter
        self.subscriptions = subscriptions

    def updated(self, tx, old: Optional[K2VItem],
                new: Optional[K2VItem]) -> None:
        if self.counter is not None:
            self.counter.count(tx, old, new)
        if self.subscriptions is not None and new is not None:
            item = new
            tx.on_commit(lambda: self.subscriptions.notify(item))

    def matches_filter(self, entry: K2VItem, flt) -> bool:
        if flt is None:
            return True
        kind = flt.get("type") if isinstance(flt, dict) else None
        if kind == "item":
            if flt.get("conflicts_only") and len(entry.live_values()) < 2:
                return False
            if not flt.get("tombstones") and entry.is_tombstone():
                return False
            return True
        return True
