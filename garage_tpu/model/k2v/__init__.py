"""K2V: causally-consistent key-key-value store (model layer).

Ref parity: src/model/k2v/ — DVVS item table (item_table.py), vector
clocks / causality tokens (causality.py), insert-routing RPC + poll
subscriptions (rpc.py).
"""

from .causality import CausalContext, make_node_id, vclock_gt, vclock_max
from .item_table import DvvsEntry, K2VItem, K2VItemTable, partition_pk
from .rpc import K2VRpcHandler, SubscriptionManager

__all__ = [
    "CausalContext", "DvvsEntry", "K2VItem", "K2VItemTable",
    "K2VRpcHandler", "SubscriptionManager", "make_node_id",
    "partition_pk", "vclock_gt", "vclock_max",
]
