"""Online repair procedures.

Ref parity: src/garage/repair/online.rs:29-390. Each procedure is a
background worker that walks one local table store with a cursor and
fixes dangling references left by crashes or missed trigger runs:

- RepairVersions: a live Version whose backing object version no longer
  exists (or is Aborted) is tombstoned, which cascades to block refs.
- RepairBlockRefs: a live BlockRef whose Version is gone/deleted is
  tombstoned, releasing the block's refcount.
- RepairMpu: a live MultipartUpload whose object no longer shows the
  upload is tombstoned (parts cleared).
- BlockRcRepair: recomputes every block's refcount from the block_ref
  store (ref: repair/online.rs BlockRcRepair + block/rc.rs:83-130).
- RepairTables: queues a full anti-entropy pass on every table.

Launchable from the CLI (`repair <what>`) through the admin RPC.
"""

from __future__ import annotations

import logging

from ..utils.background import Worker, WorkerInfo, WState
from .s3.mpu_table import MultipartUpload
from .s3.object_table import ST_ABORTED
from .s3.version_table import BACKLINK_OBJECT, Version

log = logging.getLogger("garage_tpu.model.repair")

BATCH = 64


class _TableRepairWorker(Worker):
    """Cursor walk over one table's local store; `process(entry)` returns
    True when it repaired something (ref: online.rs TableRepairWorker)."""

    def __init__(self, garage, table):
        self.garage = garage
        self.table = table
        self.name = f"{table.name} repair"
        self._pos = b""
        self.counter = 0
        self.repairs = 0
        self.done = False

    async def work(self):
        import asyncio

        store = self.table.data.store
        batch = await asyncio.to_thread(
            lambda: list(store.iter(
                start=self._pos + b"\x00" if self._pos else None,
                limit=BATCH)))
        if not batch:
            log.info("%s: finished, examined %d, fixed %d", self.name,
                     self.counter, self.repairs)
            self.done = True
            return WState.DONE
        for key, raw in batch:
            entry = self.table.data.decode_stored(raw)
            if await self.process(entry):
                self.repairs += 1
            self.counter += 1
            self._pos = key
        return WState.BUSY

    async def process(self, entry) -> bool:
        raise NotImplementedError

    def info(self):
        return WorkerInfo(name=self.name,
                          progress=f"{self.counter} ({self.repairs})")


class RepairVersions(_TableRepairWorker):
    def __init__(self, garage):
        super().__init__(garage, garage.version_table)

    async def process(self, version: Version) -> bool:
        if version.deleted.value:
            return False
        if version.backlink[0] == BACKLINK_OBJECT:
            _, bucket_id, key = version.backlink
            obj = await self.garage.object_table.get(
                bucket_id, key.encode() if isinstance(key, str) else key)
            exists = obj is not None and any(
                v.uuid == version.uuid and v.state.kind != ST_ABORTED
                for v in obj.versions)
        else:
            upload_id = version.backlink[1]
            mpu = await self.garage.mpu_table.get(upload_id, b"")
            exists = mpu is not None and not mpu.deleted.value
        if exists:
            return False
        log.info("repair versions: tombstoning %s", version.uuid.hex()[:8])
        await self.garage.version_table.insert(
            Version.new(version.uuid, version.backlink, deleted=True))
        return True


class RepairBlockRefs(_TableRepairWorker):
    def __init__(self, garage):
        super().__init__(garage, garage.block_ref_table)

    async def process(self, block_ref) -> bool:
        if block_ref.deleted.value:
            return False
        v = await self.garage.version_table.get(block_ref.version, b"")
        if v is not None and not v.deleted.value:
            return False
        from .s3.block_ref_table import BlockRef

        log.info("repair block refs: tombstoning ref %s -> %s",
                 block_ref.block.hex()[:8], block_ref.version.hex()[:8])
        await self.garage.block_ref_table.insert(
            BlockRef.new(block_ref.block, block_ref.version, deleted=True))
        return True


class RepairMpu(_TableRepairWorker):
    def __init__(self, garage):
        super().__init__(garage, garage.mpu_table)

    async def process(self, mpu: MultipartUpload) -> bool:
        if mpu.deleted.value:
            return False
        obj = await self.garage.object_table.get(
            mpu.bucket_id,
            mpu.key.encode() if isinstance(mpu.key, str) else mpu.key)
        exists = obj is not None and any(
            v.uuid == mpu.upload_id and v.is_uploading(check_multipart=True)
            for v in obj.versions)
        if exists:
            return False
        log.info("repair mpu: tombstoning upload %s",
                 mpu.upload_id.hex()[:8])
        tomb = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                                   mpu.bucket_id, mpu.key, deleted=True)
        await self.garage.mpu_table.insert(tomb)
        return True


class BlockRcRepair(Worker):
    """Recalculate every block's refcount from the block_ref store
    (ref: online.rs BlockRcRepair)."""

    def __init__(self, garage):
        self.garage = garage
        self.name = "block rc repair"
        self._cursor = b""
        self.counter = 0
        self.done = False

    async def work(self):
        import asyncio

        rc = self.garage.block_manager.rc
        hashes = await asyncio.to_thread(
            lambda: [h[0] for h in rc.tree.iter(
                start=self._cursor + b"\x00" if self._cursor else None,
                limit=BATCH)])
        if not hashes:
            log.info("block rc repair: finished, %d recalculated",
                     self.counter)
            self.done = True
            return WState.DONE
        for h in hashes:
            await asyncio.to_thread(rc.recalculate, h)
            self.counter += 1
            self._cursor = h
        return WState.BUSY

    def info(self):
        return WorkerInfo(name=self.name, progress=str(self.counter))


def launch_repair(garage, what: str):
    """Spawn the requested repair worker (ref: online.rs
    launch_online_repair). Returns a short description."""
    runner = garage.runner
    if what == "tables":
        for t in garage.all_tables():
            t.syncer.add_full_sync()
        return "full table sync queued on all tables"
    if what == "versions":
        runner.spawn_worker(RepairVersions(garage))
    elif what == "block-refs":
        runner.spawn_worker(RepairBlockRefs(garage))
    elif what == "mpu":
        runner.spawn_worker(RepairMpu(garage))
    elif what == "block-rc":
        runner.spawn_worker(BlockRcRepair(garage))
    elif what == "blocks":
        from ..block.repair import RepairWorker

        runner.spawn_worker(RepairWorker(garage.block_manager))
    elif what == "rebalance":
        from ..block.repair import RebalanceWorker

        runner.spawn_worker(RebalanceWorker(garage.block_manager))
    else:
        raise ValueError(f"unknown repair procedure {what!r}")
    return f"{what} repair worker launched"
