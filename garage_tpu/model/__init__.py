"""Application model layer: schemas + the Garage composition root.

Ref parity: src/model/ (garage.rs, s3/*, bucket_table.rs, key_table.rs,
index_counter.rs, permission.rs).
"""

from .bucket_alias_table import BucketAlias, BucketAliasTable
from .bucket_table import Bucket, BucketParams, BucketTable, is_valid_bucket_name
from .garage import Garage, parse_addr, parse_peer
from .index_counter import CounterEntry, IndexCounter
from .key_table import Key, KeyParams, KeyTable
from .permission import BucketKeyPerm

__all__ = [
    "Bucket", "BucketAlias", "BucketAliasTable", "BucketKeyPerm",
    "BucketParams", "BucketTable", "CounterEntry", "Garage", "IndexCounter",
    "Key", "KeyParams", "KeyTable", "is_valid_bucket_name", "parse_addr",
    "parse_peer",
]
