"""Garage: the composition root that turns the libraries into a node.

Ref parity: src/model/garage.rs:37-334. Opens the db, builds the
System/NetApp, the BlockManager, and all tables with their replication
parameters (data: read quorum 1; metadata: full quorums; control:
full-copy), wires the block_ref -> block rc trigger chain and the rc
recalculator, and spawns every background worker.

Replication parameter table (ref: garage.rs:154-170):
  data (block refs)   sharded, R=1-ish .. erasure-widened placement
  meta (obj/ver/mpu)  sharded, R/W from replication mode
  control (bucket/key/alias)  full-copy
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from ..block.layout import DataDir as LayoutDataDir
from ..block.layout import DataLayout
from ..block.manager import BlockManager
from ..db import open_db
from ..net import NetApp
from ..rpc.layout.manager import LayoutManager  # noqa: F401 (re-export)
from ..rpc.replication_mode import ReplicationMode
from ..rpc.rpc_helper import RpcHelper
from ..rpc.system import System, load_or_gen_node_key
from ..table.replication import (TableFullReplication,
                                 TableShardedReplication)
from ..table.table import Table
from ..utils.background import BackgroundRunner, BgVars
from ..utils.config import Config
from ..utils.persister import Persister
from .bucket_alias_table import BucketAliasTable
from .bucket_table import BucketTable
from .index_counter import IndexCounter
from .key_table import KeyTable
from .s3.block_ref_table import (BlockRefReplication, BlockRefTable,
                                 block_ref_recount_fn)
from .s3.mpu_table import MultipartUploadTable
from .s3.object_table import ObjectTable
from .s3.version_table import VersionTable

log = logging.getLogger("garage_tpu.model")


def parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host.strip("[]"), int(port))


def parse_peer(s: str) -> tuple[tuple[str, int], Optional[bytes]]:
    """"<hex node id>@host:port" or "host:port" -> (addr, id|None)."""
    if "@" in s:
        nid, _, addr = s.partition("@")
        return parse_addr(addr), bytes.fromhex(nid)
    return parse_addr(s), None


class Garage:
    def __init__(self, config: Config, local_net=None,
                 status_interval: Optional[float] = None,
                 ping_interval: Optional[float] = None):
        self.config = config
        self.bg_vars = BgVars()
        from ..utils.data import set_content_hash_algo

        set_content_hash_algo(config.block_hash_algo)
        from .. import native

        native.warm_async()  # build the C kernels off the event loop
        os.makedirs(config.metadata_dir, exist_ok=True)
        for d in config.data_dirs:
            os.makedirs(d.path, exist_ok=True)

        # ---- db (ref: garage.rs:95-116) --------------------------------
        db_path = os.path.join(config.metadata_dir, "db")
        self.db = open_db(db_path, engine=config.db_engine,
                          fsync=config.metadata_fsync)

        # ---- identity / net (ref: garage.rs:118-130, system.rs) --------
        netid = (bytes.fromhex(config.rpc_secret) if config.rpc_secret
                 else b"garage-tpu-insecure-dev")
        privkey = load_or_gen_node_key(config.metadata_dir)
        bind = parse_addr(config.rpc_bind_addr)
        public = (parse_addr(config.rpc_public_addr)
                  if config.rpc_public_addr else bind)
        self.netapp = NetApp(netid, privkey, bind_addr=bind, public_addr=public)
        if local_net is not None:
            local_net.register(self.netapp)

        self.replication = ReplicationMode.parse(
            config.replication_factor, config.consistency_mode,
            config.erasure_coding,
        )
        bootstrap = [(a, i) for a, i in map(parse_peer, config.bootstrap_peers)]
        kwargs = {}
        if status_interval is not None:
            kwargs["status_interval"] = status_interval
        if ping_interval is not None:
            kwargs["ping_interval"] = ping_interval
        from ..rpc.discovery import providers_from_config

        self.system = System(
            self.netapp, self.replication, config.metadata_dir,
            data_dirs=[d.path for d in config.data_dirs],
            bootstrap_peers=bootstrap,
            discovery=providers_from_config(config), **kwargs,
        )
        self.system.layout_manager.set_broadcast_debounce(
            config.rpc_layout_debounce_ms / 1000.0)
        rpc = RpcHelper(self.system)
        self.rpc = rpc
        rm = self.replication

        # ---- replication parameters (ref: garage.rs:154-170) -----------
        meta_rep = TableShardedReplication(
            self.system, rm.read_quorum, rm.write_quorum
        )
        control_rep = TableFullReplication(self.system)
        # block_ref rows must reach every shard holder (erasure widens
        # the placement beyond rf; see BlockRefReplication docstring)
        block_ref_rep = BlockRefReplication(
            self.system, rm.read_quorum, rm.write_quorum, rm.storage_width
        )

        # ---- block manager (ref: garage.rs:172-176) --------------------
        self.data_layout = self._load_data_layout(config)
        self.block_manager = BlockManager(
            self.system, self.db, self.data_layout,
            compression=config.compression_level is not None,
            fsync=config.data_fsync,
            device_mode="auto" if config.tpu.enable else "off",
            device_batch_blocks=config.tpu.batch_blocks,
            tpu_cfg=config.tpu,
            ram_buffer_max=config.block_ram_buffer_max,
            read_cache_max_bytes=config.block_read_cache_max_bytes,
            resync_breaker_aware=config.block_resync_breaker_aware,
            cache_tier=config.block_cache_tier,
            cache_tier_hint_top_n=config.block_cache_tier_hint_top_n,
            cache_lease_wait_ms=config.block_cache_lease_wait_ms,
            cache_prefetch_inflight=config.block_cache_prefetch_inflight,
            cache_packed_max_bytes=config.block_cache_packed_max_bytes,
        )

        # ---- tables (ref: garage.rs:178-248) ---------------------------
        self.bucket_table = Table(BucketTable(), control_rep, rpc, self.db)
        self.bucket_alias_table = Table(BucketAliasTable(), control_rep, rpc,
                                        self.db)
        self.key_table = Table(KeyTable(), control_rep, rpc, self.db)

        self.block_ref_table = Table(
            BlockRefTable(self.block_manager), block_ref_rep, rpc, self.db
        )
        self.version_table = Table(
            VersionTable(self.block_ref_table), meta_rep, rpc, self.db
        )
        self.mpu_counter = IndexCounter(self.system, meta_rep, rpc, self.db,
                                        "bucket_mpu_counter")
        self.mpu_table = Table(
            MultipartUploadTable(self.version_table, self.mpu_counter),
            meta_rep, rpc, self.db,
        )
        self.object_counter = IndexCounter(self.system, meta_rep, rpc, self.db,
                                           "bucket_object_counter")
        self.object_table = Table(
            ObjectTable(self.version_table, self.mpu_table,
                        self.object_counter),
            meta_rep, rpc, self.db,
        )

        # ---- K2V (ref: garage.rs:206-248 + model/k2v/) -----------------
        from .k2v.item_table import K2VItemTable
        from .k2v.rpc import K2VRpcHandler, SubscriptionManager

        self.k2v_subscriptions = SubscriptionManager()
        self.k2v_counter = IndexCounter(self.system, meta_rep, rpc, self.db,
                                        "k2v_index_counter")
        self.k2v_item_table = Table(
            K2VItemTable(self.k2v_counter, self.k2v_subscriptions),
            meta_rep, rpc, self.db,
        )
        self.k2v_rpc = K2VRpcHandler(self.system, self.db,
                                     self.k2v_item_table,
                                     self.k2v_subscriptions)

        # rc recalculation from the block_ref store (ref: garage.rs:252-256)
        self.block_manager.rc.register_calculator(
            block_ref_recount_fn(self.block_ref_table)
        )

        # ---- qos admission control (garage_tpu/qos/) -------------------
        from ..qos import QosEngine
        from ..qos.limiter import QosLimits

        qc = config.qos
        self.qos = QosEngine(QosLimits(
            global_rps=qc.global_rps, global_burst=qc.global_burst,
            global_bytes_per_s=qc.global_bytes_per_s,
            global_bytes_burst=qc.global_bytes_burst,
            per_key_rps=qc.per_key_rps,
            per_bucket_rps=qc.per_bucket_rps,
            max_concurrent=qc.max_concurrent, max_queue=qc.max_queue,
            max_wait_s=qc.max_wait_s, fair_keys=qc.fair_keys,
        ))
        # foreground block-read bytes (cache hit AND store miss alike)
        # consume the qos bytes budget (shape_bytes never sheds, it
        # just paces): GET/copy traffic is priced evenly wherever it is
        # served from, and a hot set cannot ride the cache past the
        # configured byte rate
        self.block_manager.read_qos_charge = self.qos.shape_bytes
        self.qos_governor = None  # spawned in spawn_workers
        self.lsm_maintenance = None  # spawned in spawn_workers (lsm only)

        # ---- self-healing rpc knobs ([rpc] section) --------------------
        self.system.peering.health.configure(
            hedging=config.rpc_hedging,
            hedge_rate=config.rpc_hedge_rate,
            adaptive_timeout=config.rpc_adaptive_timeout,
            write_hedging=config.rpc_hedge_writes,
        )

        # ---- fault injection ([chaos] section) -------------------------
        # boot-time arming for chaos experiments / CI; runtime control
        # stays available through admin GET/POST /v1/chaos either way.
        # The zone resolver is installed unconditionally (cheap: one
        # attribute write) so a partition_zone fault armed later via
        # admin POST /v1/chaos can resolve frame endpoints to zones —
        # every node converges on the same layout, so any node's view
        # serves the process-global controller.
        from ..chaos import controller as chaos_controller
        from ..zones import layout_zone_resolver

        chaos_controller().zone_resolver = layout_zone_resolver(
            self.system.layout_manager)
        if config.chaos.enable:
            from ..chaos import FaultSpec, arm

            chaos = arm(seed=config.chaos.seed)
            for spec in config.chaos.faults:
                chaos.add(FaultSpec(**dict(spec)))

        # one global lock serializing bucket/key/alias mutations
        # (ref: garage.rs:61 bucket_lock + helper/locked.rs)
        self.bucket_lock = asyncio.Lock()

        self.runner = BackgroundRunner()
        self._run_task: Optional[asyncio.Task] = None

    def _load_data_layout(self, config: Config) -> DataLayout:
        multi = len(config.data_dirs) > 1
        dirs = []
        for d in config.data_dirs:
            if d.read_only or (multi and d.capacity is None):
                # multi-HDD entries without a declared capacity are
                # read-only (utils/config.py DataDir semantics; the
                # reference rejects them at config parse)
                cap = 0
            else:
                cap = d.capacity or 1  # single dir: proportion is moot
            dirs.append(LayoutDataDir(d.path, cap))
        if not dirs:
            dirs = [LayoutDataDir(os.path.join(config.metadata_dir, "data"), 1)]
        persister = Persister(config.metadata_dir, "data_layout", DataLayout)
        self._data_layout_persister = persister
        prev = persister.load()
        if prev is None:
            lay = DataLayout.initialize(dirs)
        elif ([d.path for d in prev.dirs] != [d.path for d in dirs]
              or [d.capacity for d in prev.dirs] != [d.capacity for d in dirs]):
            lay = prev.update_dirs(dirs)  # rebalance worker migrates files
        else:
            return prev
        persister.save(lay)
        return lay

    # ---- lifecycle (ref: garage/server.rs:30-120) ----------------------

    def all_tables(self) -> list[Table]:
        return [
            self.bucket_table, self.bucket_alias_table, self.key_table,
            self.object_table, self.version_table, self.block_ref_table,
            self.mpu_table, self.object_counter.table, self.mpu_counter.table,
            self.k2v_item_table, self.k2v_counter.table,
        ]

    def spawn_workers(self, scrub: bool = True) -> None:
        """ref: model/garage.rs:282-334 spawn_workers."""
        for t in self.all_tables():
            t.spawn_workers(self.runner)
        self.block_manager.spawn_workers(self.runner, scrub=scrub)
        self.block_manager.register_bg_vars(self.bg_vars)
        if self.db.engine_name == "lsm":
            # background size-tiered compaction, paced by the governor
            # exactly like resync/scrub (README "Metadata at scale")
            from ..db.lsm import LsmMaintenanceWorker

            self.lsm_maintenance = LsmMaintenanceWorker(self.db)
            self.runner.spawn_worker(self.lsm_maintenance)
        qc = self.config.qos
        if qc.governor:
            from ..qos import GovernorWorker

            self.qos_governor = GovernorWorker(
                self, interval=qc.governor_interval,
                target_latency=qc.governor_target_latency,
                scrub_range=(qc.scrub_tranquility_min,
                             qc.scrub_tranquility_max),
                resync_range=(qc.resync_tranquility_min,
                              qc.resync_tranquility_max),
                resync_backlog_ref=qc.resync_backlog_ref,
                table_sync_tranq_max=self.config.table_sync_tranquility_max,
            )
            self.runner.spawn_worker(self.qos_governor)
            gov = self.qos_governor

            bm = self.block_manager

            def set_gov(v):
                gov.enabled = v.lower() in ("1", "true", "yes")
                if gov.enabled:
                    # re-enabling hands the tranquility knobs back from
                    # any manual `worker set` override
                    bm.resync.tranquility_manual = False
                    sw = getattr(bm, "scrub_worker", None)
                    if sw is not None:
                        sw.state.tranquility_manual = False
                        sw.persister.save(sw.state)

            self.bg_vars.register_rw("qos-governor",
                                     lambda: int(gov.enabled), set_gov)
        from .s3.lifecycle_worker import LifecycleWorker

        self.runner.spawn_worker(LifecycleWorker(self))
        if self.config.metadata_auto_snapshot_interval:
            from .snapshot import AutoSnapshotWorker

            self.runner.spawn_worker(AutoSnapshotWorker(
                self, self.config.metadata_auto_snapshot_interval))

    async def run(self, spawn_workers: bool = True) -> None:
        """Start listening + gossip + workers; returns when stop() is
        called."""
        if spawn_workers:
            self.spawn_workers()
        await self.system.run()

    async def stop(self) -> None:
        await self.runner.shutdown()
        await self.block_manager.stop()
        await self.system.stop()
        self.db.close()
