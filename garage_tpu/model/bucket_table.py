"""Bucket table (full-copy control table).

Ref parity: src/model/bucket_table.rs. A bucket is identified by a
random uuid; its params are a CRDT aggregate of authorized keys, global
and key-local aliases, and Lww'd website / CORS / lifecycle / quota
configs. Deletion is a Deletable tombstone (a deleted bucket id is
never reused).

Plain-structure config payloads (travel inside Lww registers):
  website:   {"index_document": str, "error_document": str|None}
  cors:      [{"id","max_age_seconds","allow_origins","allow_methods",
               "allow_headers","expose_headers"}]
  lifecycle: [{"id","enabled","filter":{"prefix","size_gt","size_lt"},
               "abort_incomplete_mpu_days","expiration"}]
  quotas:    {"max_size": int|None, "max_objects": int|None}
"""

from __future__ import annotations

from typing import Optional

from ..table.schema import Entry, TableSchema
from ..utils.crdt import Crdt, CrdtMap, Deletable, Lww, LwwMap, now_msec
from ..utils.data import gen_uuid
from .permission import BucketKeyPerm


class BucketParams(Crdt):
    def __init__(self, creation_date: Optional[int] = None,
                 authorized_keys: Optional[CrdtMap] = None,
                 aliases: Optional[LwwMap] = None,
                 local_aliases: Optional[LwwMap] = None,
                 website_config: Optional[Lww] = None,
                 cors_config: Optional[Lww] = None,
                 lifecycle_config: Optional[Lww] = None,
                 quotas: Optional[Lww] = None):
        self.creation_date = creation_date if creation_date is not None else now_msec()
        self.authorized_keys = authorized_keys or CrdtMap()  # key_id -> perm
        self.aliases = aliases or LwwMap()  # alias -> bool
        self.local_aliases = local_aliases or LwwMap()  # (key_id, alias) -> bool
        self.website_config = website_config or Lww.new(None)
        self.cors_config = cors_config or Lww.new(None)
        self.lifecycle_config = lifecycle_config or Lww.new(None)
        self.quotas = quotas or Lww.new({"max_size": None, "max_objects": None})

    def __eq__(self, other):
        return isinstance(other, BucketParams) and self.pack() == other.pack()

    def merge(self, o: "BucketParams") -> "BucketParams":
        return BucketParams(
            min(self.creation_date, o.creation_date),
            self.authorized_keys.merge(o.authorized_keys),
            self.aliases.merge(o.aliases),
            self.local_aliases.merge(o.local_aliases),
            self.website_config.merge(o.website_config),
            self.cors_config.merge(o.cors_config),
            self.lifecycle_config.merge(o.lifecycle_config),
            self.quotas.merge(o.quotas),
        )

    def pack(self):
        return [
            self.creation_date,
            [[k, p.pack()] for k, p in self.authorized_keys.items()],
            [[k, lww.ts, lww.value] for k, lww in self.aliases.items_lww()],
            [[list(k), lww.ts, lww.value]
             for k, lww in self.local_aliases.items_lww()],
            self.website_config.pack(),
            self.cors_config.pack(),
            self.lifecycle_config.pack(),
            self.quotas.pack(),
        ]

    @classmethod
    def unpack(cls, o) -> "BucketParams":
        return cls(
            o[0],
            CrdtMap({k: BucketKeyPerm.unpack(p) for k, p in o[1]}),
            LwwMap({k: Lww(ts, v) for k, ts, v in o[2]}),
            LwwMap({tuple(k): Lww(ts, v) for k, ts, v in o[3]}),
            Lww.unpack(o[4]),
            Lww.unpack(o[5]),
            Lww.unpack(o[6]),
            Lww.unpack(o[7]),
        )


class Bucket(Entry):
    VERSION_MARKER = b"GTbkt01"

    def __init__(self, id: bytes, state: Deletable):
        self.id = id
        self.state = state  # Deletable[BucketParams]

    @staticmethod
    def new() -> "Bucket":
        return Bucket(gen_uuid(), Deletable.present(BucketParams()))

    @property
    def is_deleted(self) -> bool:
        return self.state.is_deleted

    @property
    def params(self) -> Optional[BucketParams]:
        return self.state.value

    def partition_key(self) -> bytes:
        return self.id

    def sort_key(self) -> bytes:
        return b""

    def merge(self, other: "Bucket") -> "Bucket":
        return Bucket(self.id, self.state.merge(other.state))

    def pack(self):
        return [self.id,
                self.params.pack() if self.params is not None else None]

    @classmethod
    def unpack(cls, o) -> "Bucket":
        params = BucketParams.unpack(o[1]) if o[1] is not None else None
        return cls(
            bytes(o[0]),
            Deletable.present(params) if params is not None
            else Deletable.deleted(),
        )

    # ---- convenience for API/CLI layers --------------------------------

    def with_params(self, params: BucketParams) -> "Bucket":
        return Bucket(self.id, Deletable.present(params))

    def authorized(self, key_id: str) -> BucketKeyPerm:
        if self.params is None:
            return BucketKeyPerm.no_permissions()
        return (self.params.authorized_keys.get(key_id)
                or BucketKeyPerm.no_permissions())


class BucketTable(TableSchema):
    TABLE_NAME = "bucket"
    ENTRY = Bucket

    def matches_filter(self, entry: Bucket, flt) -> bool:
        if flt is None or flt.get("deleted", "any") == "any":
            return True
        want_deleted = flt["deleted"] == "deleted"
        return entry.is_deleted == want_deleted


def is_valid_bucket_name(name: str) -> bool:
    """AWS bucket-name rules (ref: bucket_alias_table.rs:83-98).
    ASCII-only: lowercase letters, digits, dots, hyphens."""
    if not (3 <= len(name) <= 63):
        return False
    if not all(("a" <= c <= "z") or ("0" <= c <= "9") or c in ".-"
               for c in name):
        return False
    first, last = name[0], name[-1]
    if not (("a" <= first <= "z") or ("0" <= first <= "9")):
        return False
    if not (("a" <= last <= "z") or ("0" <= last <= "9")):
        return False
    if all(("0" <= c <= "9") or c == "." for c in name):  # looks like an IP
        return False
    if name.startswith("xn--") or name.endswith("-s3alias"):
        return False
    return ".." not in name
