"""Global bucket aliases (full-copy control table).

Ref parity: src/model/bucket_alias_table.rs. An alias is a human name
pointing (Lww) at a bucket id or None (deleted).
"""

from __future__ import annotations

from typing import Optional

from ..table.schema import Entry, TableSchema
from ..utils.crdt import Lww
from .bucket_table import is_valid_bucket_name


class BucketAlias(Entry):
    VERSION_MARKER = b"GTals01"

    def __init__(self, name: str, state: Lww):
        self.name = name
        self.state = state  # Lww[Optional[bucket_id bytes]]

    @staticmethod
    def new(name: str, bucket_id: Optional[bytes],
            ts: Optional[int] = None) -> Optional["BucketAlias"]:
        if not is_valid_bucket_name(name):
            return None
        return BucketAlias(name, Lww.new(bucket_id, ts))

    @property
    def is_deleted(self) -> bool:
        return self.state.value is None

    @property
    def bucket_id(self) -> Optional[bytes]:
        return self.state.value

    def partition_key(self) -> bytes:
        return b""

    def sort_key(self) -> bytes:
        return self.name.encode()

    def merge(self, other: "BucketAlias") -> "BucketAlias":
        return BucketAlias(self.name, self.state.merge(other.state))

    def pack(self):
        return [self.name, self.state.ts, self.state.value]

    @classmethod
    def unpack(cls, o) -> "BucketAlias":
        v = bytes(o[2]) if o[2] is not None else None
        return cls(o[0], Lww(o[1], v))


class BucketAliasTable(TableSchema):
    TABLE_NAME = "bucket_alias"
    ENTRY = BucketAlias

    def matches_filter(self, entry: BucketAlias, flt) -> bool:
        if flt is None or flt.get("deleted", "any") == "any":
            return True
        return entry.is_deleted == (flt["deleted"] == "deleted")
