"""API key table (full-copy control table).

Ref parity: src/model/key_table.rs. A key is "GK" + 12 hex bytes with a
32-hex-byte secret; params hold the name, create-bucket permission,
per-bucket grants, and key-local bucket aliases.
"""

from __future__ import annotations

import os
from typing import Optional

from ..table.schema import Entry, TableSchema
from ..utils.crdt import Crdt, CrdtMap, Deletable, Lww, LwwMap
from .permission import BucketKeyPerm


class KeyParams(Crdt):
    def __init__(self, secret_key: str, name: Optional[Lww] = None,
                 allow_create_bucket: Optional[Lww] = None,
                 authorized_buckets: Optional[CrdtMap] = None,
                 local_aliases: Optional[LwwMap] = None):
        self.secret_key = secret_key
        self.name = name or Lww.new("")
        self.allow_create_bucket = allow_create_bucket or Lww.new(False)
        self.authorized_buckets = authorized_buckets or CrdtMap()  # bucket_id -> perm
        self.local_aliases = local_aliases or LwwMap()  # alias -> bucket_id|None

    def __eq__(self, other):
        return isinstance(other, KeyParams) and self.pack() == other.pack()

    def merge(self, o: "KeyParams") -> "KeyParams":
        return KeyParams(
            self.secret_key,
            self.name.merge(o.name),
            self.allow_create_bucket.merge(o.allow_create_bucket),
            self.authorized_buckets.merge(o.authorized_buckets),
            self.local_aliases.merge(o.local_aliases),
        )

    def pack(self):
        return [
            self.secret_key,
            self.name.pack(),
            self.allow_create_bucket.pack(),
            [[k, p.pack()] for k, p in self.authorized_buckets.items()],
            [[k, lww.ts, lww.value] for k, lww in self.local_aliases.items_lww()],
        ]

    @classmethod
    def unpack(cls, o) -> "KeyParams":
        return cls(
            o[0],
            Lww.unpack(o[1]),
            Lww.unpack(o[2]),
            CrdtMap({bytes(k): BucketKeyPerm.unpack(p) for k, p in o[3]}),
            LwwMap({k: Lww(ts, bytes(v) if v is not None else None)
                    for k, ts, v in o[4]}),
        )


class Key(Entry):
    VERSION_MARKER = b"GTkey01"

    def __init__(self, key_id: str, state: Deletable):
        self.key_id = key_id
        self.state = state  # Deletable[KeyParams]

    @staticmethod
    def new(name: str = "") -> "Key":
        key_id = "GK" + os.urandom(12).hex()
        secret = os.urandom(32).hex()
        params = KeyParams(secret)
        params.name = Lww.new(name)
        return Key(key_id, Deletable.present(params))

    @staticmethod
    def import_key(key_id: str, secret_key: str, name: str = "") -> "Key":
        if len(key_id) != 26 or not key_id.startswith("GK"):
            raise ValueError("invalid key id (GK + 24 hex chars)")
        bytes.fromhex(key_id[2:])
        if len(secret_key) != 64:
            raise ValueError("invalid secret key (64 hex chars)")
        bytes.fromhex(secret_key)
        params = KeyParams(secret_key)
        params.name = Lww.new(name)
        return Key(key_id, Deletable.present(params))

    @staticmethod
    def deleted(key_id: str) -> "Key":
        return Key(key_id, Deletable.deleted())

    @property
    def is_deleted(self) -> bool:
        return self.state.is_deleted

    @property
    def params(self) -> Optional[KeyParams]:
        return self.state.value

    def bucket_permissions(self, bucket_id: bytes) -> BucketKeyPerm:
        if self.params is None:
            return BucketKeyPerm.no_permissions()
        return (self.params.authorized_buckets.get(bucket_id)
                or BucketKeyPerm.no_permissions())

    def allow_read(self, bucket_id: bytes) -> bool:
        return self.bucket_permissions(bucket_id).allow_read

    def allow_write(self, bucket_id: bytes) -> bool:
        return self.bucket_permissions(bucket_id).allow_write

    def allow_owner(self, bucket_id: bytes) -> bool:
        return self.bucket_permissions(bucket_id).allow_owner

    def partition_key(self) -> bytes:
        return b""

    def sort_key(self) -> bytes:
        return self.key_id.encode()

    def merge(self, other: "Key") -> "Key":
        return Key(self.key_id, self.state.merge(other.state))

    def pack(self):
        return [self.key_id,
                self.params.pack() if self.params is not None else None]

    @classmethod
    def unpack(cls, o) -> "Key":
        params = KeyParams.unpack(o[1]) if o[1] is not None else None
        return cls(o[0], Deletable.present(params) if params is not None
                   else Deletable.deleted())


class KeyTable(TableSchema):
    TABLE_NAME = "key"
    ENTRY = Key

    def matches_filter(self, entry: Key, flt) -> bool:
        if flt is None:
            return True
        if "matches" in flt:
            pat = flt["matches"].lower()
            if entry.is_deleted:
                return False
            return (entry.key_id.lower().startswith(pat)
                    or (entry.params is not None
                        and entry.params.name.value.lower() == pat))
        want = flt.get("deleted", "any")
        if want == "any":
            return True
        return entry.is_deleted == (want == "deleted")
