"""Distributed index counters (objects / bytes / uploads per bucket).

Ref parity: src/model/index_counter.rs. Each counted table's `updated()`
trigger calls `IndexCounter.count(tx, old, new)` inside the same
transaction: the delta is applied to a node-local counter tree, and the
node's new totals are queued for insertion into a sharded counter table
whose entries CRDT-merge per (counter name, node id) with a timestamp —
so every node's contribution converges independently and the global
value is the sum of per-node values.

A counted entry implements:
    counter_partition_key() -> bytes
    counter_sort_key() -> bytes
    counts() -> list[(name, int)]
"""

from __future__ import annotations

from typing import Optional

import msgpack

from ..table.schema import Entry, TableSchema, tree_key
from ..table.table import Table
from ..utils.crdt import now_msec


class CounterEntry(Entry):
    """values: {name: {node_id(bytes): (ts, value)}}."""

    VERSION_MARKER = b"GTcnt01"

    def __init__(self, pk: bytes, sk: bytes, values: Optional[dict] = None):
        self.pk = pk
        self.sk = sk
        self.values: dict = values or {}

    def partition_key(self) -> bytes:
        return self.pk

    def sort_key(self) -> bytes:
        return self.sk

    def is_tombstone(self) -> bool:
        return all(
            v == 0
            for per_node in self.values.values()
            for _, v in per_node.values()
        )

    def merge(self, other: "CounterEntry") -> "CounterEntry":
        out = {n: dict(per) for n, per in self.values.items()}
        for name, per_node in other.values.items():
            mine = out.setdefault(name, {})
            for node, (ts, v) in per_node.items():
                if node not in mine or ts > mine[node][0]:
                    mine[node] = (ts, v)
        return CounterEntry(self.pk, self.sk, out)

    def filtered_values(self, nodes: list[bytes]) -> dict[str, int]:
        """Aggregate over storage nodes. Every replica of a partition
        counts the same rows, so the aggregate is max, not sum
        (ref: index_counter.rs:84-107)."""
        out: dict[str, int] = {}
        nodeset = set(nodes)
        for name, per_node in self.values.items():
            vals = [v for n, (_, v) in per_node.items() if n in nodeset]
            if vals:
                out[name] = max(vals)
        return out

    def pack(self):
        return [
            self.pk,
            self.sk,
            [
                [name, [[n, ts, v] for n, (ts, v) in sorted(per.items())]]
                for name, per in sorted(self.values.items())
            ],
        ]

    @classmethod
    def unpack(cls, o) -> "CounterEntry":
        values = {
            name: {bytes(n): (ts, v) for n, ts, v in per}
            for name, per in o[2]
        }
        return cls(bytes(o[0]), bytes(o[1]), values)


class CounterTable(TableSchema):
    ENTRY = CounterEntry

    def __init__(self, name: str):
        self.TABLE_NAME = name

    def matches_filter(self, entry, flt) -> bool:
        if flt is None:
            return True
        nodes = [bytes(n) for n in flt.get("nodes", [])]
        tomb = all(v == 0 for v in entry.filtered_values(nodes).values())
        want = flt.get("deleted", "any")
        if want == "deleted":
            return tomb
        if want == "not_deleted":
            return not tomb
        return True


class IndexCounter:
    """ref: index_counter.rs:165-252."""

    def __init__(self, system, replication, rpc_helper, db, name: str):
        self.this_node = system.id
        self.local_counter = db.open_tree(f"local_counter:{name}")
        self.table = Table(CounterTable(name), replication, rpc_helper, db)

    def spawn_workers(self, runner) -> None:
        self.table.spawn_workers(runner)

    def count(self, tx, old, new) -> None:
        """Apply the old→new delta inside the caller's transaction."""
        src = old if old is not None else new
        pk, sk = src.counter_partition_key(), src.counter_sort_key()
        deltas: dict[str, int] = {}
        for k, v in (old.counts() if old is not None else []):
            deltas[k] = deltas.get(k, 0) - v
        for k, v in (new.counts() if new is not None else []):
            deltas[k] = deltas.get(k, 0) + v

        k = tree_key(pk, sk)
        raw = tx.get(self.local_counter, k)
        local: dict[str, tuple[int, int]] = {}
        if raw is not None:
            local = {name: (ts, v) for name, ts, v in msgpack.unpackb(raw)}
        now = now_msec()
        for name, inc in deltas.items():
            ts, v = local.get(name, (0, 0))
            local[name] = (max(ts + 1, now), v + inc)
        tx.insert(
            self.local_counter, k,
            msgpack.packb([[n, ts, v] for n, (ts, v) in sorted(local.items())]),
        )
        entry = CounterEntry(
            pk, sk,
            {name: {self.this_node: tv} for name, tv in local.items()},
        )
        self.table.queue_insert(tx, entry)

    def recount(self, data) -> int:
        """OFFLINE repair: rebuild this node's local counters from a
        full scan of the counted table, replacing whatever incremental
        state drifted (ref: src/garage/repair/offline.rs:11 +
        index_counter.rs recalculation). Returns the number of counter
        rows rewritten. MUST run with the server stopped — a concurrent
        live count() landing between the scan and the rewrite would be
        overwritten by stale totals whose fresher timestamp then wins
        the CRDT merge cluster-wide. The repair-offline CLI enforces
        this with the meta-dir flock (utils/lockfile.py) that a running
        server holds for its lifetime. The rewritten counter-table
        entries gossip out through normal anti-entropy at next boot."""
        agg: dict[tuple[bytes, bytes], dict[str, int]] = {}
        key_of: dict[bytes, tuple[bytes, bytes]] = {}
        for _k, raw in data.iter_all():
            e = data.decode_stored(raw)
            pksk = (e.counter_partition_key(), e.counter_sort_key())
            key_of[tree_key(*pksk)] = pksk
            d = agg.setdefault(pksk, {})
            for name, v in e.counts():
                d[name] = d.get(name, 0) + v
        # stale local-counter rows (counted rows all gone) get zeroed;
        # tree keys are invertible, so no table row is needed
        from ..table.schema import split_tree_key

        stale: list[tuple[bytes, tuple[bytes, bytes]]] = [
            (k, split_tree_key(k))
            for k, _ in self.local_counter.iter() if k not in key_of
        ]
        now = now_msec()
        n = 0
        todo = [(tree_key(*pksk), pksk) for pksk in agg] + stale
        for key, pksk in todo:
            counts = agg.get(pksk, {})

            def body(tx, key=key, counts=counts):
                raw = tx.get(self.local_counter, key)
                local = {}
                if raw is not None:
                    local = {name: (ts, v)
                             for name, ts, v in msgpack.unpackb(raw)}
                names = set(local) | set(counts)
                for name in names:
                    ts, _old = local.get(name, (0, 0))
                    local[name] = (max(ts + 1, now), counts.get(name, 0))
                tx.insert(self.local_counter, key, msgpack.packb(
                    [[nm, ts, v] for nm, (ts, v) in sorted(local.items())]))
                return local

            local = self.table.data.db.transaction(body)
            self.table.data.update_entry_decoded(CounterEntry(
                pksk[0], pksk[1],
                {name: {self.this_node: tv}
                 for name, tv in local.items()}))
            n += 1
        return n

    async def read(self, pk: bytes, sk: bytes,
                   nodes: list[bytes]) -> dict[str, int]:
        e = await self.table.get(pk, sk)
        return e.filtered_values(nodes) if e is not None else {}
