"""Bucket-key permission CRDT.

Ref parity: src/model/permission.rs. A timestamped permission triple;
newer timestamp wins, equal timestamps merge to the most restricted set
(so a concurrent grant+revoke resolves to revoke).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.crdt import Crdt


@dataclass(frozen=True)
class BucketKeyPerm(Crdt):
    ts: int = 0
    allow_read: bool = False
    allow_write: bool = False
    allow_owner: bool = False

    @staticmethod
    def no_permissions() -> "BucketKeyPerm":
        return BucketKeyPerm(0, False, False, False)

    @staticmethod
    def all_permissions(ts: int = 0) -> "BucketKeyPerm":
        return BucketKeyPerm(ts, True, True, True)

    @property
    def is_any(self) -> bool:
        return self.allow_read or self.allow_write or self.allow_owner

    def merge(self, other: "BucketKeyPerm") -> "BucketKeyPerm":
        if other.ts > self.ts:
            return other
        if other.ts == self.ts and other != self:
            # most-restricted wins on timestamp tie (ref: permission.rs)
            return BucketKeyPerm(
                self.ts,
                self.allow_read and other.allow_read,
                self.allow_write and other.allow_write,
                self.allow_owner and other.allow_owner,
            )
        return self

    def pack(self) -> list:
        return [self.ts, self.allow_read, self.allow_write, self.allow_owner]

    @classmethod
    def unpack(cls, o) -> "BucketKeyPerm":
        return cls(o[0], bool(o[1]), bool(o[2]), bool(o[3]))
