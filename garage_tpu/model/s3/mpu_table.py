"""Multipart upload table.

Ref parity: src/model/s3/mpu_table.rs. One row per upload id; parts are
a CRDT map keyed by (part_number, timestamp) so a re-uploaded part gets
a newer timestamp and both records coexist until Complete picks the
newest. The `updated()` trigger propagates deletion to the version
table; the counter tracks uploads/parts/bytes per bucket.
"""

from __future__ import annotations

from typing import Optional

from ...table.schema import Entry, TableSchema
from ...utils.crdt import Bool, Crdt, CrdtMap, now_msec
from .version_table import BACKLINK_MPU, Version

UPLOADS = "uploads"
PARTS = "parts"
BYTES = "bytes"


class MpuPart(Crdt):
    """ref: MpuPart {version, etag, size} (checksum folded into etag
    handling at the API layer)."""

    __slots__ = ("version", "etag", "size")

    def __init__(self, version: bytes, etag: Optional[str] = None,
                 size: Optional[int] = None):
        self.version = version
        self.etag = etag
        self.size = size

    def pack(self):
        return [self.version, self.etag, self.size]

    @classmethod
    def unpack(cls, o):
        return cls(bytes(o[0]), o[1], o[2])

    def merge(self, other: "MpuPart") -> "MpuPart":
        # commutative max-merge of every field (ref mpu_table.rs:150-167
        # max-merges etag/size; version is included here so two gateways
        # colliding on the same (part, ts) key still converge)
        def mx(a, b):
            if a is None:
                return b
            if b is None:
                return a
            return max(a, b)

        return MpuPart(max(self.version, other.version),
                       mx(self.etag, other.etag), mx(self.size, other.size))

    def __eq__(self, other):
        return isinstance(other, MpuPart) and self.pack() == other.pack()


class MultipartUpload(Entry):
    VERSION_MARKER = b"GTmpu01"

    def __init__(self, upload_id: bytes, timestamp: int, deleted: Bool,
                 parts: CrdtMap, bucket_id: bytes, key: str):
        self.upload_id = upload_id
        self.timestamp = timestamp
        self.deleted = deleted
        self.parts = parts  # (part_number, ts) -> MpuPart
        self.bucket_id = bucket_id
        self.key = key

    @staticmethod
    def new(upload_id: bytes, timestamp: int, bucket_id: bytes, key: str,
            deleted: bool = False) -> "MultipartUpload":
        return MultipartUpload(upload_id, timestamp, Bool(deleted),
                               CrdtMap(), bucket_id, key)

    def next_timestamp(self, part_number: int) -> int:
        """ref: mpu_table.rs:92-103."""
        prev = [k[1] for k, _ in self.parts.items() if k[0] == part_number]
        return max(now_msec(), (max(prev) + 1) if prev else 0)

    def partition_key(self) -> bytes:
        return self.upload_id

    def sort_key(self) -> bytes:
        return b""

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def merge(self, other: "MultipartUpload") -> "MultipartUpload":
        deleted = self.deleted.merge(other.deleted)
        parts = CrdtMap() if deleted.value else self.parts.merge(other.parts)
        return MultipartUpload(self.upload_id, self.timestamp, deleted,
                               parts, self.bucket_id, self.key)

    def pack(self):
        return [
            self.upload_id, self.timestamp, self.deleted.value,
            [[k[0], k[1], p.pack()] for k, p in self.parts.items()],
            self.bucket_id, self.key,
        ]

    @classmethod
    def unpack(cls, o):
        parts = CrdtMap({(pn, ts): MpuPart.unpack(p) for pn, ts, p in o[3]})
        return cls(bytes(o[0]), o[1], Bool(bool(o[2])), parts,
                   bytes(o[4]), o[5])

    # ---- counted item (ref: mpu_table.rs:227-260) ----------------------

    def counter_partition_key(self) -> bytes:
        return self.bucket_id

    def counter_sort_key(self) -> bytes:
        return b""

    def counts(self) -> list[tuple[str, int]]:
        uploads = 0 if self.deleted.value else 1
        part_numbers = {k[0] for k, _ in self.parts.items()}
        bytes_ = sum(p.size or 0 for _, p in self.parts.items())
        return [(UPLOADS, uploads), (PARTS, len(part_numbers)),
                (BYTES, bytes_)]


class MultipartUploadTable(TableSchema):
    TABLE_NAME = "multipart_upload"
    ENTRY = MultipartUpload

    def __init__(self, version_table, mpu_counter):
        self.version_table = version_table
        self.mpu_counter = mpu_counter

    def updated(self, tx, old: Optional[MultipartUpload],
                new: Optional[MultipartUpload]) -> None:
        """Deletion propagates to the part versions
        (ref: mpu_table.rs updated)."""
        self.mpu_counter.count(tx, old, new)
        if old is None or new is None:
            return
        if new.deleted.value and not old.deleted.value:
            for _, part in old.parts.items():
                self.version_table.queue_insert(
                    tx,
                    Version.new(part.version, (BACKLINK_MPU, old.upload_id),
                                deleted=True),
                )

    def matches_filter(self, entry: MultipartUpload, flt) -> bool:
        if flt is None or flt.get("deleted", "any") == "any":
            return True
        return entry.is_tombstone() == (flt["deleted"] == "deleted")
