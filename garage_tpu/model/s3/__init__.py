"""S3 object-model tables (object/version/block_ref/multipart)."""

from .block_ref_table import (BlockRef, BlockRefReplication, BlockRefTable,
                              block_ref_recount_fn)
from .mpu_table import MpuPart, MultipartUpload, MultipartUploadTable
from .object_table import (Object, ObjectTable, ObjectVersion,
                           ObjectVersionData, ObjectVersionMeta,
                           ObjectVersionState, object_upload_version)
from .version_table import Version, VersionTable

__all__ = [
    "BlockRef", "BlockRefReplication", "BlockRefTable", "MpuPart",
    "MultipartUpload", "MultipartUploadTable", "Object", "ObjectTable",
    "ObjectVersion", "ObjectVersionData", "ObjectVersionMeta",
    "ObjectVersionState", "Version", "VersionTable",
    "block_ref_recount_fn", "object_upload_version",
]
