"""Lifecycle worker: daily pass applying bucket lifecycle rules.

Ref parity: src/model/s3/lifecycle_worker.rs:36-380. Once per UTC day
the worker walks the local object table in key order (cursor-batched so
a batch never scans the whole tail), and for every object applies the
owning bucket's enabled rules:

- Expiration (AfterDays n / AtDate d): the current data version is
  replaced by a delete marker when old enough and the size filter
  matches.
- AbortIncompleteMultipartUpload (DaysAfterInitiation n): uploading
  versions older than n days flip to Aborted; the object-table trigger
  chain then tombstones their version rows and drops block refs.

Only `last_completed` (an ISO date) persists across restarts — a crash
mid-pass restarts the day's walk from the front, which is idempotent.
Buckets with no enabled rules are skipped wholesale by jumping the
cursor past the bucket's key range.
"""

from __future__ import annotations

import datetime
import logging

from ...table.data import _prefix_upper_bound
from ...table.schema import tree_key
from ...utils import migrate
from ...utils.background import Throttled, Worker, WorkerInfo, WState
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ...utils.persister import Persister
from .object_table import (Object, ObjectVersion, ObjectVersionData,
                           ObjectVersionState, ST_COMPLETE, ST_UPLOADING)

log = logging.getLogger("garage_tpu.model.lifecycle")

BATCH = 100


def _date_of_msec(ts: int) -> datetime.date:
    return datetime.datetime.fromtimestamp(
        ts / 1000, datetime.timezone.utc).date()


def next_date(ts: int) -> datetime.date:
    """The day after the version's timestamp — a version expires N days
    after the *end* of its creation day (ref: lifecycle_worker.rs
    next_date)."""
    return _date_of_msec(ts) + datetime.timedelta(days=1)


def today() -> datetime.date:
    return datetime.datetime.now(datetime.timezone.utc).date()


class LifecycleState(migrate.Migratable):
    VERSION_MARKER = b"GTlfc01"

    def __init__(self, last_completed: str = ""):
        self.last_completed = last_completed  # ISO date or ""

    def pack(self):
        return [self.last_completed]

    @classmethod
    def unpack(cls, o):
        return cls(*o)


class LifecycleWorker(Worker):
    def __init__(self, garage):
        self.garage = garage
        self.name = "object lifecycle"
        self.persister = Persister(garage.config.metadata_dir,
                                   "lifecycle_state", LifecycleState)
        st = self.persister.load() or LifecycleState()
        self._running_date = None  # date of the in-progress pass
        self._next_start = b""  # next tree key to scan from (inclusive)
        self._last_completed = (
            datetime.date.fromisoformat(st.last_completed)
            if st.last_completed else None)
        self._expired = 0
        self._aborted = 0
        self._bucket_cache: tuple[bytes, object] | None = None

    def _due(self) -> bool:
        return self._last_completed is None or self._last_completed < today()

    async def work(self):
        if self._running_date is None:
            if not self._due():
                return WState.IDLE
            self._running_date = today()
            self._next_start = b""
            self._expired = self._aborted = 0
            log.info("lifecycle pass starting for %s", self._running_date)

        import asyncio

        store = self.garage.object_table.data.store
        batch = await asyncio.to_thread(
            lambda: list(store.iter(start=self._next_start or None,
                                    limit=BATCH)))
        if not batch:
            log.info("lifecycle pass for %s done: %d expired, %d mpu "
                     "aborted", self._running_date, self._expired,
                     self._aborted)
            self._last_completed = self._running_date
            self._running_date = None
            self.persister.save(LifecycleState(
                self._last_completed.isoformat()))
            return WState.IDLE
        for key, raw in batch:
            obj = self.garage.object_table.data.decode_stored(raw)
            skip_bucket = await self._process(obj)
            self._next_start = key + b"\x00"
            if skip_bucket:
                # rows group by hash(bucket) ++ bucket ++ key, so jumping
                # to the bucket's tree-key prefix upper bound skips the
                # whole bucket (ref: lifecycle_worker.rs Skip::SkipBucket)
                bound = _prefix_upper_bound(tree_key(obj.bucket_id, b""))
                if bound is not None:
                    self._next_start = max(self._next_start, bound)
                break
        return Throttled(0.01)

    async def _process(self, obj: Object) -> bool:
        """Apply rules to one object; True => skip rest of the bucket."""
        if not any(v.is_data or v.state.kind == ST_UPLOADING
                   for v in obj.versions):
            return False
        bucket = await self._get_bucket(obj.bucket_id)
        if bucket is None or bucket.params is None:
            return True
        rules = bucket.params.lifecycle_config.value or []
        if not any(r.get("enabled", True) for r in rules):
            return True
        now_date = self._running_date
        for rule in rules:
            if not rule.get("enabled", True):
                continue
            flt = rule.get("filter") or {}
            pfx = flt.get("prefix")
            if pfx and not obj.key.startswith(pfx):
                continue
            exp = rule.get("expiration")
            if exp is not None:
                cur = obj.last_data()
                if cur is not None and self._size_ok(cur, flt):
                    if isinstance(exp, int):
                        due = (now_date - next_date(cur.timestamp)
                               ).days >= exp
                    else:
                        try:
                            due = now_date >= datetime.date.fromisoformat(exp)
                        except ValueError:
                            log.warning("bad lifecycle date %r in bucket "
                                        "%s", exp, obj.bucket_id.hex()[:8])
                            due = False
                    if due:
                        marker = Object(obj.bucket_id, obj.key, [
                            ObjectVersion(
                                gen_uuid(),
                                max(now_msec(), cur.timestamp + 1),
                                ObjectVersionState.complete(
                                    ObjectVersionData.delete_marker()))])
                        await self.garage.object_table.insert(marker)
                        self._expired += 1
            abort_days = rule.get("abort_incomplete_mpu_days")
            if abort_days is not None:
                aborted = [
                    ObjectVersion(v.uuid, v.timestamp,
                                  ObjectVersionState.aborted())
                    for v in obj.versions
                    if v.state.kind == ST_UPLOADING
                    and (now_date - next_date(v.timestamp)).days
                    >= abort_days
                ]
                if aborted:
                    await self.garage.object_table.insert(
                        Object(obj.bucket_id, obj.key, aborted))
                    self._aborted += len(aborted)
        return False

    @staticmethod
    def _size_ok(version, flt: dict) -> bool:
        if version.state.kind != ST_COMPLETE:
            return False
        size = version.state.data.meta.size \
            if version.state.data.meta is not None else 0
        if flt.get("size_gt") is not None and not size > flt["size_gt"]:
            return False
        if flt.get("size_lt") is not None and not size < flt["size_lt"]:
            return False
        return True

    async def _get_bucket(self, bucket_id: bytes):
        if self._bucket_cache is not None \
                and self._bucket_cache[0] == bucket_id:
            return self._bucket_cache[1]
        b = await self.garage.bucket_table.get(bucket_id, b"")
        # lint: ignore[GL12] single lifecycle worker task owns this 1-entry cache; a racing fill would only re-cache the other bucket and the id check above re-fetches on mismatch
        self._bucket_cache = (bucket_id, b)
        return b

    async def wait_for_work(self):
        import asyncio

        await asyncio.sleep(60.0)

    def info(self):
        return WorkerInfo(
            name=self.name,
            progress=(self._next_start[:4].hex() if self._running_date
                      else (self._last_completed.isoformat()
                            if self._last_completed else "never")),
        )
