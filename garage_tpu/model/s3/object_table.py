"""Object table: the S3 namespace (bucket_id, key) -> versions.

Ref parity: src/model/s3/object_table.rs. An Object is the list of its
versions ordered by (timestamp, uuid); each version is Uploading /
Complete / Aborted; complete data is a DeleteMarker, Inline bytes
(< inline threshold) or FirstBlock (block list in the version table).
Merge keeps CRDT semantics: Aborted dominates a version's state,
Complete dominates Uploading, and versions older than the newest
Complete one are dropped.

The `updated()` trigger (ref: object_table.rs:547-645):
  1. updates the bucket's object counters,
  2. propagates dropped/aborted versions to the version table
     (tombstones, which cascade to block_refs),
  3. deletes MPU entries for finished multipart uploads.
"""

from __future__ import annotations

from typing import Optional

from ...table.schema import Entry, TableSchema
from ...utils.crdt import now_msec
from .mpu_table import MultipartUpload
from .version_table import BACKLINK_OBJECT, Version

# ObjectVersionState kinds
ST_UPLOADING = "uploading"
ST_COMPLETE = "complete"
ST_ABORTED = "aborted"

# ObjectVersionData kinds
DATA_DELETE_MARKER = "delete_marker"
DATA_INLINE = "inline"
DATA_FIRST_BLOCK = "first_block"

# counter names (ref: object_table.rs:16-18)
OBJECTS = "objects"
UNFINISHED_UPLOADS = "unfinished_uploads"
BYTES = "bytes"


class ObjectVersionMeta:
    """ref: ObjectVersionMeta {headers, size, etag}."""

    __slots__ = ("headers", "size", "etag")

    def __init__(self, headers: dict, size: int, etag: str):
        self.headers = dict(headers)  # content-type + user meta
        self.size = size
        self.etag = etag

    def pack(self):
        return [sorted(self.headers.items()), self.size, self.etag]

    @classmethod
    def unpack(cls, o):
        return cls(dict(o[0]), o[1], o[2])


class ObjectVersionData:
    """DeleteMarker | Inline(meta, bytes) | FirstBlock(meta, hash)."""

    __slots__ = ("kind", "meta", "blob")

    def __init__(self, kind: str, meta: Optional[ObjectVersionMeta] = None,
                 blob: bytes = b""):
        self.kind = kind
        self.meta = meta  # None only for delete markers
        self.blob = blob  # inline bytes, or 32-byte first-block hash

    @staticmethod
    def delete_marker() -> "ObjectVersionData":
        return ObjectVersionData(DATA_DELETE_MARKER)

    @staticmethod
    def inline(meta: ObjectVersionMeta, data: bytes) -> "ObjectVersionData":
        return ObjectVersionData(DATA_INLINE, meta, data)

    @staticmethod
    def first_block(meta: ObjectVersionMeta, hash32: bytes) -> "ObjectVersionData":
        return ObjectVersionData(DATA_FIRST_BLOCK, meta, hash32)

    def pack(self):
        return [self.kind, self.meta.pack() if self.meta else None, self.blob]

    @classmethod
    def unpack(cls, o):
        meta = ObjectVersionMeta.unpack(o[1]) if o[1] is not None else None
        return cls(o[0], meta, bytes(o[2]))

    def merge(self, other: "ObjectVersionData") -> "ObjectVersionData":
        # honest writers never produce different Complete data for one
        # version uuid; break ties deterministically (ref: AutoCrdt max)
        import msgpack

        return self if msgpack.packb(self.pack()) >= msgpack.packb(other.pack()) \
            else other


class ObjectVersionState:
    """Uploading{multipart, headers} | Complete(data) | Aborted."""

    __slots__ = ("kind", "multipart", "headers", "data")

    def __init__(self, kind: str, multipart: bool = False,
                 headers: Optional[dict] = None,
                 data: Optional[ObjectVersionData] = None):
        self.kind = kind
        self.multipart = multipart
        self.headers = dict(headers) if headers else {}
        self.data = data

    @staticmethod
    def uploading(headers: dict, multipart: bool = False) -> "ObjectVersionState":
        return ObjectVersionState(ST_UPLOADING, multipart, headers)

    @staticmethod
    def complete(data: ObjectVersionData) -> "ObjectVersionState":
        return ObjectVersionState(ST_COMPLETE, data=data)

    @staticmethod
    def aborted() -> "ObjectVersionState":
        return ObjectVersionState(ST_ABORTED)

    def merge(self, other: "ObjectVersionState") -> "ObjectVersionState":
        """ref: object_table.rs ObjectVersionState::merge — Aborted wins;
        Complete beats Uploading; two Completes merge data."""
        if self.kind == ST_ABORTED or other.kind == ST_ABORTED:
            return ObjectVersionState.aborted()
        if self.kind == ST_COMPLETE and other.kind == ST_COMPLETE:
            return ObjectVersionState.complete(self.data.merge(other.data))
        if self.kind == ST_COMPLETE:
            return self
        if other.kind == ST_COMPLETE:
            return other
        return self  # both uploading

    def pack(self):
        return [self.kind, self.multipart, sorted(self.headers.items()),
                self.data.pack() if self.data else None]

    @classmethod
    def unpack(cls, o):
        data = ObjectVersionData.unpack(o[3]) if o[3] is not None else None
        return cls(o[0], bool(o[1]), dict(o[2]), data)


class ObjectVersion:
    __slots__ = ("uuid", "timestamp", "state")

    def __init__(self, uuid: bytes, timestamp: int, state: ObjectVersionState):
        self.uuid = uuid
        self.timestamp = timestamp
        self.state = state

    def cmp_key(self) -> tuple:
        return (self.timestamp, self.uuid)

    @property
    def is_complete(self) -> bool:
        return self.state.kind == ST_COMPLETE

    @property
    def is_data(self) -> bool:
        """Complete and not a delete marker."""
        return (self.state.kind == ST_COMPLETE
                and self.state.data.kind != DATA_DELETE_MARKER)

    def is_uploading(self, check_multipart: Optional[bool] = None) -> bool:
        if self.state.kind != ST_UPLOADING:
            return False
        return check_multipart is None or self.state.multipart == check_multipart

    def pack(self):
        return [self.uuid, self.timestamp, self.state.pack()]

    @classmethod
    def unpack(cls, o):
        return cls(bytes(o[0]), o[1], ObjectVersionState.unpack(o[2]))


class Object(Entry):
    VERSION_MARKER = b"GTobj01"

    def __init__(self, bucket_id: bytes, key: str,
                 versions: Optional[list[ObjectVersion]] = None):
        self.bucket_id = bucket_id
        self.key = key
        self.versions = sorted(versions or [], key=ObjectVersion.cmp_key)

    def partition_key(self) -> bytes:
        return self.bucket_id

    def sort_key(self) -> bytes:
        return self.key.encode()

    def merge(self, other: "Object") -> "Object":
        """ref: object_table.rs Crdt for Object."""
        by_key = {v.cmp_key(): ObjectVersion(v.uuid, v.timestamp, v.state)
                  for v in self.versions}
        for ov in other.versions:
            k = ov.cmp_key()
            if k in by_key:
                by_key[k] = ObjectVersion(
                    ov.uuid, ov.timestamp, by_key[k].state.merge(ov.state)
                )
            else:
                by_key[k] = ov
        versions = [by_key[k] for k in sorted(by_key)]
        # drop versions older than the last complete one
        last_complete = None
        for i, v in enumerate(versions):
            if v.is_complete:
                last_complete = i
        if last_complete is not None:
            versions = versions[last_complete:]
        return Object(self.bucket_id, self.key, versions)

    def last_complete(self) -> Optional[ObjectVersion]:
        for v in reversed(self.versions):
            if v.is_complete:
                return v
        return None

    def last_data(self) -> Optional[ObjectVersion]:
        """Newest complete non-delete-marker version (what GET serves)."""
        v = self.last_complete()
        return v if v is not None and v.is_data else None

    def version(self, uuid: bytes) -> Optional[ObjectVersion]:
        for v in self.versions:
            if v.uuid == uuid:
                return v
        return None

    def pack(self):
        return [self.bucket_id, self.key, [v.pack() for v in self.versions]]

    @classmethod
    def unpack(cls, o):
        return cls(bytes(o[0]), o[1], [ObjectVersion.unpack(v) for v in o[2]])

    # ---- counted item (ref: object_table.rs:652-688) -------------------

    def counter_partition_key(self) -> bytes:
        return self.bucket_id

    def counter_sort_key(self) -> bytes:
        return b""

    def counts(self) -> list[tuple[str, int]]:
        n_objects = 1 if any(v.is_data for v in self.versions) else 0
        n_uploading = sum(1 for v in self.versions if v.is_uploading())
        n_bytes = sum(
            v.state.data.meta.size
            for v in self.versions
            if v.is_complete and v.state.data.meta is not None
        )
        return [(OBJECTS, n_objects), (UNFINISHED_UPLOADS, n_uploading),
                (BYTES, n_bytes)]


class ObjectTable(TableSchema):
    TABLE_NAME = "object"
    ENTRY = Object

    def __init__(self, version_table, mpu_table, object_counter):
        self.version_table = version_table
        self.mpu_table = mpu_table
        self.object_counter = object_counter

    def updated(self, tx, old: Optional[Object], new: Optional[Object]) -> None:
        """ref: object_table.rs:547-645."""
        self.object_counter.count(tx, old, new)
        if old is None or new is None:
            return
        new_by_key = {v.cmp_key(): v for v in new.versions}
        for v in old.versions:
            nv = new_by_key.get(v.cmp_key())
            # dropped or newly-aborted versions delete their block list
            delete_version = nv is None or (
                nv.state.kind == ST_ABORTED and v.state.kind != ST_ABORTED
            )
            if delete_version:
                self.version_table.queue_insert(
                    tx,
                    Version.new(v.uuid,
                                (BACKLINK_OBJECT, old.bucket_id, old.key),
                                deleted=True),
                )
            # finished/aborted multipart uploads delete their MPU entry
            if v.is_uploading(check_multipart=True):
                delete_mpu = nv is None or nv.state.kind != ST_UPLOADING
                if delete_mpu:
                    self.mpu_table.queue_insert(
                        tx,
                        MultipartUpload.new(v.uuid, v.timestamp,
                                            old.bucket_id, old.key,
                                            deleted=True),
                    )

    def matches_filter(self, entry: Object, flt) -> bool:
        if flt is None:
            return True
        t = flt.get("type")
        if t == "data":
            return any(v.is_data for v in entry.versions)
        if t == "uploading":
            cm = flt.get("multipart")
            return any(v.is_uploading(cm) for v in entry.versions)
        return True


def object_upload_version(bucket_id: bytes, key: str, uuid: bytes,
                          headers: dict, multipart: bool = False,
                          timestamp: Optional[int] = None) -> Object:
    """A fresh single-version Object in Uploading state (PUT step 1)."""
    ts = timestamp if timestamp is not None else now_msec()
    return Object(bucket_id, key, [
        ObjectVersion(uuid, ts, ObjectVersionState.uploading(headers, multipart))
    ])
