"""Object version table: the block list of one upload.

Ref parity: src/model/s3/version_table.rs. A Version is keyed by its
uuid; `blocks` maps (part_number, offset) -> (block hash, size). The
`updated()` trigger propagates deletion to the block_ref table (one
tombstone per referenced block) via the async insert queue.
"""

from __future__ import annotations

from typing import Optional

from ...table.schema import Entry, TableSchema
from ...utils.crdt import Bool, CrdtMap
from .block_ref_table import BlockRef

# backlink kinds
BACKLINK_OBJECT = "object"
BACKLINK_MPU = "mpu"


class Version(Entry):
    VERSION_MARKER = b"GTver01"

    def __init__(self, uuid: bytes, deleted: Bool, blocks: CrdtMap,
                 backlink: tuple):
        self.uuid = uuid
        self.deleted = deleted
        # (part_number, offset) -> (hash, size); values max-merge, which
        # is a no-op for honest writers (same block content)
        self.blocks = blocks
        # ("object", bucket_id, key) | ("mpu", upload_id)
        self.backlink = backlink

    @staticmethod
    def new(uuid: bytes, backlink: tuple, deleted: bool = False) -> "Version":
        return Version(uuid, Bool(deleted), CrdtMap(), backlink)

    def with_block(self, part_number: int, offset: int, hash32: bytes,
                   size: int) -> "Version":
        return Version(self.uuid, self.deleted,
                       self.blocks.put((part_number, offset), (hash32, size)),
                       self.backlink)

    def partition_key(self) -> bytes:
        return self.uuid

    def sort_key(self) -> bytes:
        return b""

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def merge(self, other: "Version") -> "Version":
        deleted = self.deleted.merge(other.deleted)
        if deleted.value:
            blocks = CrdtMap()
        else:
            blocks = self.blocks.merge(other.blocks)
        return Version(self.uuid, deleted, blocks, self.backlink)

    # ---- helpers (ref: version_table.rs:97-123) ------------------------

    def has_part_number(self, pn: int) -> bool:
        return any(k[0] == pn for k, _ in self.blocks.items())

    def n_parts(self) -> int:
        pns = {k[0] for k, _ in self.blocks.items()}
        return max(pns) if pns else 0

    def total_size(self) -> int:
        return sum(size for _, (_, size) in self.blocks.items())

    def pack(self):
        bl = list(self.backlink)
        return [
            self.uuid,
            self.deleted.value,
            [[k[0], k[1], h, s] for k, (h, s) in self.blocks.items()],
            bl,
        ]

    @classmethod
    def unpack(cls, o) -> "Version":
        blocks = CrdtMap({(pn, off): (bytes(h), s) for pn, off, h, s in o[2]})
        bl = o[3]
        backlink = ((BACKLINK_OBJECT, bytes(bl[1]), bl[2])
                    if bl[0] == BACKLINK_OBJECT
                    else (BACKLINK_MPU, bytes(bl[1])))
        return cls(bytes(o[0]), Bool(bool(o[1])), blocks, backlink)


class VersionTable(TableSchema):
    TABLE_NAME = "version"
    ENTRY = Version

    def __init__(self, block_ref_table):
        self.block_ref_table = block_ref_table

    def updated(self, tx, old: Optional[Version],
                new: Optional[Version]) -> None:
        """Deletion propagates to block_ref tombstones
        (ref: version_table.rs:178-201)."""
        if old is None or new is None:
            return
        if new.deleted.value and not old.deleted.value:
            for _, (h, _size) in old.blocks.items():
                self.block_ref_table.queue_insert(
                    tx, BlockRef.new(h, old.uuid, deleted=True)
                )

    def matches_filter(self, entry: Version, flt) -> bool:
        if flt is None or flt.get("deleted", "any") == "any":
            return True
        return entry.is_tombstone() == (flt["deleted"] == "deleted")
