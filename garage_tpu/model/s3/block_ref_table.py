"""Block reference table — the joint between metadata and block store.

Ref parity: src/model/s3/block_ref_table.rs. One row per (block hash,
version uuid); the `updated()` trigger calls block_incref/block_decref
on the local BlockManager inside the same transaction, so a block's
local refcount exactly tracks the non-deleted refs stored on this node.

Erasure divergence (no reference analogue): when block data is striped
over k+m shard holders, block_ref rows must reach ALL holders — each
holder's local rc drives fetch/rebuild/delete of its shard. So
BlockRefReplication widens the storage set to the shard placement
(shard_nodes_of), which is exactly aligned with the ring position of the
partition key because 32-byte keys index the ring identically
(table/schema.py partition_hash).
"""

from __future__ import annotations

from typing import Optional

from ...table.replication import (SyncPartition, TableShardedReplication,
                                  partition_first_hash)
from ...table.schema import Entry, TableSchema, tree_key
from ...utils.crdt import Bool


class BlockRef(Entry):
    VERSION_MARKER = b"GTbrf01"

    def __init__(self, block: bytes, version: bytes, deleted: Bool):
        self.block = block  # hash of the referenced data block
        self.version = version  # uuid of the object version holding it
        self.deleted = deleted

    @staticmethod
    def new(block: bytes, version: bytes, deleted: bool = False) -> "BlockRef":
        return BlockRef(block, version, Bool(deleted))

    def partition_key(self) -> bytes:
        return self.block

    def sort_key(self) -> bytes:
        return self.version

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def merge(self, other: "BlockRef") -> "BlockRef":
        return BlockRef(self.block, self.version,
                        self.deleted.merge(other.deleted))

    def pack(self):
        return [self.block, self.version, self.deleted.value]

    @classmethod
    def unpack(cls, o) -> "BlockRef":
        return cls(bytes(o[0]), bytes(o[1]), Bool(bool(o[2])))


class BlockRefTable(TableSchema):
    TABLE_NAME = "block_ref"
    ENTRY = BlockRef

    def __init__(self, block_manager):
        self.block_manager = block_manager

    def updated(self, tx, old: Optional[BlockRef],
                new: Optional[BlockRef]) -> None:
        """ref: block_ref_table.rs:63-83."""
        block = (old or new).block
        was = old is not None and not old.deleted.value
        is_now = new is not None and not new.deleted.value
        if is_now and not was:
            self.block_manager.block_incref(tx, block)
        if was and not is_now:
            self.block_manager.block_decref(tx, block)

    def matches_filter(self, entry: BlockRef, flt) -> bool:
        if flt is None or flt.get("deleted", "any") == "any":
            return True
        return entry.is_tombstone() == (flt["deleted"] == "deleted")


def block_ref_recount_fn(block_ref_table):
    """CalculateRefcount for BlockRc.recalculate: count non-deleted refs
    of a block in the local store (ref: block_ref_table.rs:88-125)."""

    def count(hash32: bytes) -> int:
        from ...table.data import _prefix_upper_bound

        data = block_ref_table.data
        prefix = tree_key(hash32, b"")
        n = 0
        # end-bounded: an unbounded iter materializes the whole tail of
        # the block_ref tree per call, turning a full rc repair O(N^2)
        for _k, raw in data.store.iter(start=prefix,
                                       end=_prefix_upper_bound(prefix)):
            if not data.decode_stored(raw).is_tombstone():
                n += 1
        return n

    return count


class BlockRefReplication(TableShardedReplication):
    """Widens block_ref replication to every erasure shard holder.

    With replicate-N codecs (width == metadata rf) this degenerates to
    plain sharded replication, so it is safe to use unconditionally for
    the block_ref table."""

    def __init__(self, system, read_quorum: int, write_quorum: int,
                 width: int):
        super().__init__(system, read_quorum, write_quorum)
        self.width = width

    def _placement(self, version, hash32: bytes) -> list[bytes]:
        from ...block.codec import shard_nodes_of

        return shard_nodes_of(version, hash32, self.width)

    def storage_nodes(self, hash32):
        return self._placement(self._helper.current(), hash32)

    def read_nodes(self, hash32):
        return self._placement(self._helper.read_version(), hash32)

    def write_sets(self, hash32):
        sets = []
        for v in self._helper.versions_for_writes():
            s = self._placement(v, hash32)
            if s and s not in sets:
                sets.append(s)
        return sets

    def sync_partitions(self):
        # shard placement is constant across one ring partition (it only
        # depends on partition_of(hash)), so the per-partition storage
        # sets are the placements of the partition's first hash
        out = []
        for p in range(256):
            fh = partition_first_hash(p)
            sets = []
            for v in self._helper.versions_for_writes():
                s = self._placement(v, fh)
                if s and s not in sets:
                    sets.append(s)
            out.append(SyncPartition(p, fh, sets))
        return out
