"""Metadata DB snapshots + auto-snapshot worker.

Ref parity: src/model/snapshot.rs:17-140. `snapshot_metadata` hot-copies
the metadata database (engine-level Db.snapshot) into a timestamped
directory under `metadata_snapshots_dir` (default
`{metadata_dir}/snapshots`), keeping the 2 most recent. The
AutoSnapshotWorker runs it on `metadata_auto_snapshot_interval` with the
reference's first-run-at-half-interval and ±20% jitter.
"""

from __future__ import annotations

import asyncio
import datetime
import logging
import os
import random
import shutil
import threading
import time

from ..utils.background import Worker, WorkerInfo, WState

log = logging.getLogger("garage_tpu.model.snapshot")

KEEP_SNAPSHOTS = 2

_snapshot_lock = threading.Lock()


def snapshots_dir(config) -> str:
    d = getattr(config, "metadata_snapshots_dir", None)
    return d or os.path.join(config.metadata_dir, "snapshots")


def snapshot_metadata(garage) -> str:
    """Take one snapshot; returns its path. Blocking — run in a thread.
    Raises if another snapshot is in progress (ref: snapshot.rs
    SNAPSHOT_MUTEX try_lock)."""
    if not _snapshot_lock.acquire(blocking=False):
        raise RuntimeError("another snapshot is already in progress")
    try:
        base = snapshots_dir(garage.config)
        os.makedirs(base, exist_ok=True)
        stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H-%M-%SZ")
        path = os.path.join(base, stamp)
        log.info("snapshotting metadata db to %s", path)
        garage.db.snapshot(path)
        _cleanup(base)
        return path
    finally:
        _snapshot_lock.release()


def _cleanup(base: str) -> None:
    try:
        entries = sorted(e for e in os.listdir(base) if len(e) > 8)
    except OSError:
        return
    for name in entries[:-KEEP_SNAPSHOTS] if len(entries) > KEEP_SNAPSHOTS \
            else []:
        p = os.path.join(base, name)
        try:
            if os.path.isdir(p):
                shutil.rmtree(p)
            else:
                os.remove(p)
        except OSError as e:
            log.error("failed to clean old snapshot %s: %s", p, e)


class AutoSnapshotWorker(Worker):
    def __init__(self, garage, interval: float):
        self.garage = garage
        self.name = "metadata auto-snapshot"
        self.interval = interval
        # first snapshot at half the interval (ref: snapshot.rs:103)
        self._next = time.monotonic() + interval / 2

    async def work(self):
        if time.monotonic() < self._next:
            return WState.IDLE
        await asyncio.to_thread(snapshot_metadata, self.garage)
        # lint: ignore[GL12] single snapshot worker task owns _next; BackgroundRunner never runs two work() frames of one worker concurrently
        self._next = time.monotonic() + self.interval * (
            1.0 + random.random() / 5.0)
        return WState.IDLE

    async def wait_for_work(self):
        await asyncio.sleep(max(1.0, self._next - time.monotonic()))

    def info(self):
        return WorkerInfo(
            name=self.name,
            progress=f"next in {max(0, self._next - time.monotonic()):.0f}s",
        )
