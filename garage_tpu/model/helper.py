"""Bucket/key helpers and locked control-plane mutations.

Ref parity: src/model/helper/{bucket,key,locked}.rs. Reads resolve
aliases and check liveness; mutations that touch the bucket/key/alias
triangle are serialized under `garage.bucket_lock` (the reference's
single global lock, garage.rs:61) so alias updates never race.
"""

from __future__ import annotations

from typing import Optional

from ..utils.crdt import Deletable, Lww, now_msec
from ..utils.error import BadRequest, NoSuchBucket, NoSuchKey
from .bucket_alias_table import BucketAlias
from .bucket_table import Bucket, is_valid_bucket_name
from .key_table import Key
from .permission import BucketKeyPerm


class GarageHelper:
    def __init__(self, garage):
        self.g = garage

    # ---- reads ---------------------------------------------------------

    async def resolve_global_bucket_name(self, name: str) -> Optional[bytes]:
        """Alias or 64-hex bucket id -> bucket id
        (ref: helper/bucket.rs resolve_global_bucket_name)."""
        if len(name) == 64:
            try:
                return bytes.fromhex(name)
            except ValueError:
                pass
        alias = await self.g.bucket_alias_table.get(b"", name.encode())
        if alias is not None and alias.bucket_id is not None:
            return alias.bucket_id
        return None

    async def get_existing_bucket(self, bucket_id: bytes) -> Bucket:
        b = await self.g.bucket_table.get(bucket_id, b"")
        if b is None or b.is_deleted:
            raise NoSuchBucket(bucket_id.hex())
        return b

    async def get_existing_key(self, key_id: str) -> Key:
        k = await self.g.key_table.get(b"", key_id.encode())
        if k is None or k.is_deleted:
            raise NoSuchKey(key_id)
        return k

    async def key_secret(self, key_id: str) -> Optional[str]:
        """SigV4 secret lookup."""
        k = await self.g.key_table.get(b"", key_id.encode())
        if k is None or k.is_deleted or k.params is None:
            return None
        return k.params.secret_key

    async def list_buckets(self, limit: int = 1000) -> list[BucketAlias]:
        return [
            a for a in await self.g.bucket_alias_table.get_range(
                b"", limit=limit)
            if not a.is_deleted
        ]

    async def list_keys(self, limit: int = 1000) -> list[Key]:
        return [
            k for k in await self.g.key_table.get_range(b"", limit=limit)
            if not k.is_deleted
        ]

    # ---- locked mutations (ref: helper/locked.rs) ----------------------

    async def create_bucket(self, name: str) -> Bucket:
        if not is_valid_bucket_name(name):
            raise BadRequest(f"invalid bucket name {name!r}")
        async with self.g.bucket_lock:
            existing = await self.resolve_global_bucket_name(name)
            if existing is not None:
                raise BadRequest(f"bucket {name!r} already exists")
            bucket = Bucket.new()
            params = bucket.params
            params.aliases = params.aliases.insert(name, True)
            bucket = bucket.with_params(params)
            await self.g.bucket_table.insert(bucket)
            await self.g.bucket_alias_table.insert(
                BucketAlias(name, Lww.new(bucket.id)))
            return bucket

    async def delete_bucket(self, bucket_id: bytes) -> None:
        """Only empty buckets can go (ref: helper/bucket.rs
        delete_bucket)."""
        async with self.g.bucket_lock:
            bucket = await self.get_existing_bucket(bucket_id)
            objs = await self.g.object_table.get_range(
                bucket_id, flt={"type": "data"}, limit=1)
            if objs:
                raise BadRequest("bucket is not empty")
            params = bucket.params
            # drop aliases, then tombstone the bucket
            for alias, held in list(params.aliases.items()):
                if held:
                    await self.g.bucket_alias_table.insert(
                        BucketAlias(alias, Lww.new(None)))
            await self.g.bucket_table.insert(
                Bucket(bucket.id, Deletable.deleted()))

    async def create_key(self, name: str = "") -> Key:
        k = Key.new(name)
        await self.g.key_table.insert(k)
        return k

    async def delete_key(self, key_id: str) -> None:
        async with self.g.bucket_lock:
            key = await self.get_existing_key(key_id)
            # revoke from all buckets it was authorized on
            for bid, perm in list(key.params.authorized_buckets.items()):
                if perm.is_any:
                    await self._set_perm_unlocked(bid, key_id,
                                                  BucketKeyPerm(now_msec()))
            await self.g.key_table.insert(Key.deleted(key_id))

    async def set_key_create_bucket(self, key_id: str, allow: bool) -> None:
        """Grant/revoke the global create-bucket permission
        (ref: helper/key.rs set_allow_create_bucket)."""
        async with self.g.bucket_lock:
            key = await self.get_existing_key(key_id)
            kp = key.params
            kp.allow_create_bucket = kp.allow_create_bucket.update(allow)
            await self.g.key_table.insert(
                Key(key_id, Deletable.present(kp)))

    async def set_bucket_key_permissions(self, bucket_id: bytes,
                                         key_id: str,
                                         perm: BucketKeyPerm) -> None:
        async with self.g.bucket_lock:
            await self._set_perm_unlocked(bucket_id, key_id, perm)

    async def global_alias_bucket(self, bucket_id: bytes,
                                  alias: str) -> None:
        """Point a global alias at a bucket (ref: helper/bucket.rs
        set_global_bucket_alias)."""
        if not is_valid_bucket_name(alias):
            raise BadRequest(f"invalid alias name {alias!r}")
        async with self.g.bucket_lock:
            existing = await self.resolve_global_bucket_name(alias)
            if existing is not None and existing != bucket_id:
                raise BadRequest(f"alias {alias!r} already in use")
            bucket = await self.get_existing_bucket(bucket_id)
            params = bucket.params
            params.aliases = params.aliases.insert(alias, True)
            await self.g.bucket_table.insert(bucket.with_params(params))
            await self.g.bucket_alias_table.insert(
                BucketAlias(alias, Lww.new(bucket_id)))

    async def global_unalias_bucket(self, bucket_id: bytes,
                                    alias: str) -> None:
        async with self.g.bucket_lock:
            cur = await self.resolve_global_bucket_name(alias)
            if cur != bucket_id:
                raise BadRequest(
                    f"alias {alias!r} does not point to this bucket")
            bucket = await self.get_existing_bucket(bucket_id)
            params = bucket.params
            live = [a for a, v in params.aliases.items() if v and a != alias]
            has_local = any(v for _, v in params.local_aliases.items())
            if not live and not has_local:
                raise BadRequest(
                    "cannot remove the bucket's last alias")
            params.aliases = params.aliases.insert(alias, False)
            await self.g.bucket_table.insert(bucket.with_params(params))
            await self.g.bucket_alias_table.insert(
                BucketAlias(alias, Lww.new(None)))

    async def local_alias_bucket(self, bucket_id: bytes, key_id: str,
                                 alias: str) -> None:
        """Key-local bucket alias (ref: helper/bucket.rs
        set_local_bucket_alias)."""
        if not is_valid_bucket_name(alias):
            raise BadRequest(f"invalid alias name {alias!r}")
        async with self.g.bucket_lock:
            bucket = await self.get_existing_bucket(bucket_id)
            key = await self.get_existing_key(key_id)
            kp = key.params
            cur = kp.local_aliases.get(alias)
            if cur is not None and cur != bucket_id:
                raise BadRequest(f"local alias {alias!r} already in use")
            kp.local_aliases = kp.local_aliases.insert(alias, bucket_id)
            await self.g.key_table.insert(
                Key(key_id, Deletable.present(kp)))
            params = bucket.params
            params.local_aliases = params.local_aliases.insert(
                (key_id, alias), True)
            await self.g.bucket_table.insert(bucket.with_params(params))

    async def local_unalias_bucket(self, bucket_id: bytes, key_id: str,
                                   alias: str) -> None:
        async with self.g.bucket_lock:
            bucket = await self.get_existing_bucket(bucket_id)
            key = await self.get_existing_key(key_id)
            kp = key.params
            if kp.local_aliases.get(alias) != bucket_id:
                raise BadRequest(
                    f"local alias {alias!r} does not point to this bucket")
            params = bucket.params
            live = [a for a, v in params.aliases.items() if v]
            others = [k for k, v in params.local_aliases.items()
                      if v and k != (key_id, alias)]
            if not live and not others:
                raise BadRequest("cannot remove the bucket's last alias")
            kp.local_aliases = kp.local_aliases.insert(alias, None)
            await self.g.key_table.insert(
                Key(key_id, Deletable.present(kp)))
            params.local_aliases = params.local_aliases.insert(
                (key_id, alias), False)
            await self.g.bucket_table.insert(bucket.with_params(params))

    async def update_bucket_config(self, bucket_id: bytes, field: str,
                                   value) -> None:
        """Read-modify-write one Lww config register (website_config /
        cors_config / lifecycle_config / quotas) under the bucket lock
        (ref: api/s3/website.rs + cors.rs update paths through
        helper/locked.rs)."""
        await self.update_bucket_configs(bucket_id, {field: value})

    async def update_bucket_configs(self, bucket_id: bytes,
                                    updates: dict) -> None:
        """Atomically update several Lww config registers in ONE locked
        read-modify-write (admin UpdateBucket sets website + quotas
        together; two separate inserts could persist half on error)."""
        async with self.g.bucket_lock:
            bucket = await self.get_existing_bucket(bucket_id)
            params = bucket.params
            for field, value in updates.items():
                setattr(params, field, getattr(params, field).update(value))
            await self.g.bucket_table.insert(bucket.with_params(params))

    async def _set_perm_unlocked(self, bucket_id: bytes, key_id: str,
                                 perm: BucketKeyPerm) -> None:
        bucket = await self.get_existing_bucket(bucket_id)
        key = await self.get_existing_key(key_id)
        params = bucket.params
        params.authorized_keys = params.authorized_keys.put(key_id, perm)
        await self.g.bucket_table.insert(bucket.with_params(params))
        kp = key.params
        kp.authorized_buckets = kp.authorized_buckets.put(bucket_id, perm)
        await self.g.key_table.insert(Key(key_id, Deletable.present(kp)))


def allow_all(ts: Optional[int] = None) -> BucketKeyPerm:
    return BucketKeyPerm(ts if ts is not None else now_msec(),
                         True, True, True)
