"""Block resync: the self-healing queue of the block store.

Ref parity: src/block/resync.rs. A persistent queue (db tree keyed by
due-time ++ hash) drives re-examination of blocks: a block this node
needs but lacks is fetched from a holder (or, in erasure mode, its shard
is rebuilt from any k others — TPU repair matmul); a block held but no
longer needed is offered to nodes that still need it, then deleted.
Failures back off exponentially 1 min -> 64 min in a persistent error
tree, so a dead peer doesn't melt the queue.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Optional

from ..net.message import PRIO_BACKGROUND
from ..utils.background import Worker, WState
from ..utils.error import MissingBlock
from .codec import shard_nodes_of
from .manager import pack_shard, unpack_shard

log = logging.getLogger("garage_tpu.block.resync")

RESYNC_RETRY_DELAY = 60.0  # doubles up to 64x (ref: resync.rs:37-40)
MAX_RESYNC_WORKERS = 8


class BlockResyncManager:
    def __init__(self, manager, db):
        self.manager = manager
        self.db = db
        self.queue = db.open_tree("block_resync_queue")  # due_ms ++ hash -> b""
        self.errors = db.open_tree("block_resync_errors")  # hash -> (count, next_ms)
        self.n_workers = 1
        self.tranquility = 0.0
        # True after an operator `worker set resync-tranquility`: the
        # qos governor leaves the knob alone until re-enabled
        self.tranquility_manual = False

    # ---- queue ---------------------------------------------------------

    @staticmethod
    def _qkey(at: float, hash32: bytes) -> bytes:
        return int(at * 1000).to_bytes(8, "big") + hash32

    def push_now(self, hash32: bytes) -> None:
        self.queue.insert(self._qkey(time.time(), hash32), b"")

    def push_at(self, hash32: bytes, at: float) -> None:
        self.queue.insert(self._qkey(at, hash32), b"")

    def queue_len(self) -> int:
        return len(self.queue)

    def errors_len(self) -> int:
        return len(self.errors)

    def _pop_due(self) -> Optional[bytes]:
        now = time.time()
        for k, _ in self.queue.iter():
            if int.from_bytes(k[:8], "big") > now * 1000:
                return None
            self.queue.remove(k)
            h = k[8:]
            # skip if errored and not yet due for retry
            e = self.errors.get(h)
            if e is not None:
                _, next_ms = self._parse_err(e)
                if next_ms > now * 1000:
                    self.queue.insert(self._qkey(next_ms / 1000, h), b"")
                    continue
            return h
        return None

    @staticmethod
    def _parse_err(raw: bytes) -> tuple[int, int]:
        return int.from_bytes(raw[:4], "big"), int.from_bytes(raw[4:], "big")

    def _record_error(self, hash32: bytes) -> None:
        e = self.errors.get(hash32)
        count = self._parse_err(e)[0] + 1 if e else 1
        delay = RESYNC_RETRY_DELAY * (2 ** min(count - 1, 6))
        # ±25% jitter: one node outage queues thousands of blocks in
        # the same second, and deterministic doubling would march them
        # all into synchronized retry storms against the recovering peer
        delay *= 1.0 + random.uniform(-0.25, 0.25)
        next_ms = int((time.time() + delay) * 1000)
        self.errors.insert(
            hash32, count.to_bytes(4, "big") + next_ms.to_bytes(8, "big")
        )
        self.queue.insert(self._qkey(next_ms / 1000, hash32), b"")

    def _clear_error(self, hash32: bytes) -> None:
        self.errors.remove(hash32)

    def iter_errors(self, limit: int = 1000):
        """[(hash32, failures, next_try_ms)] — `block list-errors`."""
        out = []
        for h, raw in self.errors.iter(limit=limit):
            count, next_ms = self._parse_err(raw)
            out.append((h, count, next_ms))
        return out

    def retry_now(self, hashes=None, all_errors: bool = False) -> int:
        """Clear backoff + requeue (`block retry-now`)."""
        if all_errors:
            hashes = [h for h, _ in self.errors.iter(limit=1 << 20)]
        hashes = hashes or []
        for h in hashes:
            self._clear_error(h)
            self.push_now(h)
        return len(hashes)

    def spawn_workers(self, runner) -> None:
        for i in range(self.n_workers):
            runner.spawn_worker(ResyncWorker(self, i))

    # ---- the resync decision (ref: resync.rs:354-505) ------------------

    async def resync_block(self, hash32: bytes) -> None:
        m = self.manager
        needed = m.rc.is_needed(hash32)
        have = m.has_local(hash32)

        if have and not needed and m.rc.is_deletable_now(hash32):
            await self._offload(hash32)
            return
        if needed and not have:
            await self._fetch(hash32)
            return
        if needed and have and m.erasure:
            # do we hold the RIGHT shard for the current layout?
            await self._fix_shard_placement(hash32)

    async def _offload(self, hash32: bytes) -> None:
        """Not needed here: give our copy/shard to nodes that need it,
        then delete (ref: resync.rs:404-460)."""
        m = self.manager
        me = m.system.id
        if m.erasure:
            placement = shard_nodes_of(m.system.layout_helper.current(),
                                       hash32, m.codec.width)
        else:
            placement = m.system.layout_helper.current_storage_nodes_of(hash32)
        for node in placement:
            if node == me:
                continue
            try:
                resp, _ = await m.endpoint.call(
                    node, {"op": "need", "hash": hash32}, PRIO_BACKGROUND
                )
                if not resp.get("needed"):
                    continue
                if m.erasure:
                    want = placement.index(node)
                    raw = m.read_local_shard(hash32, want)
                    if raw is None:
                        # rebuild their shard from what we can gather
                        raw = await self._rebuild_shard(hash32, want)
                    if raw is not None:
                        await m.endpoint.call(
                            node, {"op": "put", "hash": hash32,
                                   "part": want, "data": raw},
                            PRIO_BACKGROUND,
                        )
                else:
                    packed = m.read_local(hash32)
                    if packed is not None:
                        await m.endpoint.call(
                            node, {"op": "put", "hash": hash32,
                                   "part": None, "data": packed},
                            PRIO_BACKGROUND,
                        )
                m.metrics["resync_sent"] += 1
            except Exception as e:
                log.info("offload %s to %s failed: %s",
                         hash32[:4].hex(), node[:4].hex(), e)
                raise
        m.delete_local(hash32)
        m.rc.clear_deletable(hash32)

    async def _fetch(self, hash32: bytes) -> None:
        """Needed but absent: get it (ref: resync.rs:462-505)."""
        m = self.manager
        if not m.erasure:
            packed, _verified = await m._get_replicate(hash32)
            m.write_local(hash32, packed)
            m.metrics["resync_recv"] += 1
            return
        # erasure: our assigned shard, fetched or rebuilt
        placement = shard_nodes_of(m.system.layout_helper.current(),
                                   hash32, m.codec.width)
        me = m.system.id
        if me not in placement:
            return  # not a holder anymore; nothing to fetch
        want = placement.index(me)
        raw = await self._fetch_shard(hash32, placement, want)
        if raw is None:
            raw = await self._rebuild_shard(hash32, want)
        if raw is None:
            raise MissingBlock(hash32)
        m.write_local_shard(hash32, want, raw)
        m.metrics["resync_recv"] += 1

    async def _fix_shard_placement(self, hash32: bytes) -> None:
        """After a layout change we may hold shard j but be assigned
        shard i: fetch/rebuild i; the stale j is dropped once rc says
        deletable (or by offload on the next pass)."""
        m = self.manager
        placement = shard_nodes_of(m.system.layout_helper.current(),
                                   hash32, m.codec.width)
        me = m.system.id
        if me not in placement:
            return
        want = placement.index(me)
        if want in m.local_parts(hash32):
            return
        raw = await self._fetch_shard(hash32, placement, want)
        if raw is None:
            raw = await self._rebuild_shard(hash32, want)
        if raw is not None:
            m.write_local_shard(hash32, want, raw)

    async def _fetch_shard(self, hash32: bytes, placement: list[bytes],
                           idx: int) -> Optional[bytes]:
        """Ask everyone for shard idx (an old holder may have it)."""
        m = self.manager
        for node in placement:
            if node == m.system.id:
                continue
            try:
                resp, _ = await m.endpoint.call(
                    node, {"op": "get", "hash": hash32, "part": idx},
                    PRIO_BACKGROUND,
                )
                if resp.get("data") is not None:
                    return resp["data"]
            except Exception as e:
                log.debug("resync shard fetch part=%d from %s "
                          "failed: %s", idx, node[:4].hex(), e)
                continue
        return None

    async def _rebuild_shard(self, hash32: bytes, idx: int) -> Optional[bytes]:
        """RS repair: gather any k parts, recompute shard idx (the TPU
        repair matmul, ops/rs.py repair)."""
        m = self.manager
        placement = shard_nodes_of(m.system.layout_helper.current(),
                                   hash32, m.codec.width)
        got = await m._gather_parts(hash32, placement, m.codec.read_need)
        if got is None:
            return None
        parts, len_candidates, _lens = got
        packed_len = len_candidates[0]  # majority vote
        if idx in parts:
            return pack_shard(parts[idx], packed_len)
        rebuilt = m.codec.repair_parts(parts, (idx,))
        return pack_shard(rebuilt[idx], packed_len)


class ResyncWorker(Worker):
    def __init__(self, resync: BlockResyncManager, i: int):
        self.resync = resync
        self.name = f"block resync {i}"

    async def work(self):
        h = self.resync._pop_due()
        if h is None:
            return WState.IDLE
        try:
            await self.resync.resync_block(h)
            self.resync._clear_error(h)
        except Exception as e:
            log.info("resync %s failed: %s", h[:4].hex(), e)
            self.resync._record_error(h)
        if self.resync.tranquility > 0:
            from ..utils.background import Throttled

            return Throttled(self.resync.tranquility)
        return WState.BUSY

    async def wait_for_work(self):
        await asyncio.sleep(1.0)

    def info(self):
        from ..utils.background import WorkerInfo

        return WorkerInfo(
            name=self.name,
            queue_length=self.resync.queue_len(),
            persistent_errors=self.resync.errors_len(),
        )
