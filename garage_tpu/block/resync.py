"""Block resync: the self-healing queue of the block store.

Ref parity: src/block/resync.rs. A persistent queue (db tree keyed by
due-time ++ hash) drives re-examination of blocks: a block this node
needs but lacks is fetched from a holder (or, in erasure mode, its shard
is rebuilt from any k others — TPU repair matmul); a block held but no
longer needed is offered to nodes that still need it, then deleted.
Failures back off exponentially 1 min -> 64 min in a persistent error
tree, so a dead peer doesn't melt the queue.

Resize participation (ISSUE 6): a layout version bump enumerates every
block this node holds or references into the queue (the rebalance
backlog); draining it IS the data migration, and an empty queue after
a rebalance lets the block layer report its layout-sync position so
old versions can be GC'd. Placement decisions consult the shared
PeerHealthTracker: rebalance traffic never re-queues at a peer whose
circuit breaker is open — it spreads across healthy holders and lets
the backoff retry the broken one after its breaker closes.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Optional

from ..net.message import PRIO_BACKGROUND
from ..utils.background import Worker, WState, spawn
from ..utils.error import MissingBlock
from ..utils.metrics import registry
from .codec import shard_nodes_of
from .manager import pack_shard, unpack_shard

log = logging.getLogger("garage_tpu.block.resync")

RESYNC_RETRY_DELAY = 60.0  # doubles up to 64x (ref: resync.rs:37-40)
MAX_RESYNC_WORKERS = 8


class BlockResyncManager:
    def __init__(self, manager, db, breaker_aware: bool = True):
        self.manager = manager
        self.db = db
        self.queue = db.open_tree("block_resync_queue")  # due_ms ++ hash -> b""
        self.errors = db.open_tree("block_resync_errors")  # hash -> (count, next_ms)
        self.meta = db.open_tree("block_resync_meta")  # rebalance marker
        self.n_workers = 1
        self.tranquility = 0.0
        # True after an operator `worker set resync-tranquility`: the
        # qos governor leaves the knob alone until re-enabled
        self.tranquility_manual = False
        # `[block] resync_breaker_aware`: skip open-breaker peers when
        # placing rebalance pushes/fetches
        self.breaker_aware = breaker_aware
        # shard rebuilds served from the packed-bytes tier segment
        # (ISSUE 18): each one skipped a whole k-shard gather
        self.rebuild_tier_hits = 0
        # error backoff base — tests/benches shrink it so chaos-induced
        # failures retry within the harness window instead of in a
        # minute
        self.retry_delay = RESYNC_RETRY_DELAY
        # layout version whose rebalance enumeration has COMPLETED
        # (None until bootstrap_layout_marker or an enumeration runs)
        self._enumerated_version: Optional[int] = None
        self._enumerating = 0
        # blocks popped from the queue but still being resynced; an idle
        # worker must not report "backlog drained" while a sibling
        # worker holds the last block in flight (it may fail + re-queue,
        # and the sync tracker is monotonic — a premature report can't
        # be retracted)
        self._in_flight = 0
        # (version, retry-not-before) of a rebalance enumeration that
        # FAILED: the marker is persisted before the scan runs, so
        # note_layout_change won't re-fire for this version — the
        # worker idle path retries from here instead
        self._enumerate_retry: Optional[tuple[int, float]] = None
        # consecutive breaker deferrals per block (cleared on success):
        # past DEFER_CAP the block falls back to the exponential error
        # backoff — a PERMANENTLY dead holder must not be probed every
        # breaker cooldown forever
        self._defer_counts: dict[bytes, int] = {}

    # ---- layout rebalance (ISSUE 6) ------------------------------------

    def _marker(self) -> Optional[int]:
        raw = self.meta.get(b"rebalance_version")
        return int.from_bytes(raw, "big") if raw else None

    def _set_marker(self, version: int) -> None:
        self.meta.insert(b"rebalance_version", version.to_bytes(8, "big"))

    def _current_version(self) -> int:
        s = getattr(self.manager, "system", None)
        return (s.layout_helper.current().version
                if s is not None else 0)

    def bootstrap_layout_marker(self) -> None:
        """Boot-time resume: a fresh store adopts the current layout
        version vacuously (nothing to move); a store whose persisted
        marker — or whose own persisted sync tracker — is behind the
        current version crashed or was offline during a transition and
        re-enumerates, so a kill-and-restart resumes the migration
        instead of silently forgetting it."""
        v = self._current_version()
        marker = self._marker()
        if marker is None:
            self._set_marker(v)
            self._enumerated_version = v
            return
        s = self.manager.system
        synced = s.layout_manager.history.update_trackers.sync.get(
            s.id, 0)
        if marker < v or synced < marker:
            self.enqueue_rebalance(v)
        else:
            self._enumerated_version = marker

    def note_layout_change(self) -> None:
        """LayoutManager.on_change hook — cheap no-op until the current
        version actually moves past the last enumerated one (tracker
        gossip fires this constantly during a transition)."""
        v = self._current_version()
        marker = self._marker()
        if marker is not None and v <= marker:
            return
        self.enqueue_rebalance(v)

    def enqueue_rebalance(self, version: int) -> None:
        """Queue every block this node references or stores in a
        partition whose placement changed between the last enumerated
        layout and `version` (fetch what moved in, offload what moved
        away). Unchanged partitions are skipped — a resize that moves
        1/N of the ring re-examines ~1/N of the store, not all of it."""
        prev = self._marker()
        self._set_marker(version)
        self._enumerating += 1
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # no running loop (boot-time resume before asyncio.run):
            # enumerate synchronously — it is a startup cost either way
            loop = asyncio.new_event_loop()
            try:
                loop.run_until_complete(self._enumerate(version, prev))
            finally:
                loop.close()
            return
        spawn(self._enumerate(version, prev), "resync-rebalance")

    def _moved_partitions(self, version: int,
                          prev: Optional[int]) -> Optional[set]:
        """Partitions whose full placement tuple differs between layout
        `prev` and `version`, or None when only a full scan is sound
        (no prior marker, either version already GC'd from history).
        Placement is a pure function of the partition — replicate reads
        the ring row, erasure walks successive partitions for width
        distinct nodes (codec.shard_nodes_of) — so comparing one
        synthetic hash per partition covers every block in it."""
        if prev is None or prev == version:
            return None
        m = self.manager
        s = getattr(m, "system", None)
        history = getattr(getattr(s, "layout_manager", None), "history",
                          None)
        if history is None:
            return None
        old = history.get_version(prev)
        new = history.get_version(version)
        if old is None or new is None:
            return None

        from ..rpc.layout.version import N_PARTITIONS

        def placement(lv, p: int) -> tuple:
            if m.erasure:
                synth = bytes([p]) + bytes(31)
                return tuple(shard_nodes_of(lv, synth, m.codec.width))
            return tuple(lv.nodes_of(p))

        return {p for p in range(N_PARTITIONS)
                if placement(old, p) != placement(new, p)}

    async def _enumerate(self, version: int,
                         prev: Optional[int] = None) -> None:
        moved = self._moved_partitions(version, prev)

        def scan() -> int:
            seen: set[bytes] = set()
            for h in self.manager.rc.all_hashes():
                if moved is None or h[0] in moved:
                    seen.add(bytes(h))
            for h, _ in self.manager.iter_local_blocks(parts=moved):
                seen.add(h)
            for h in seen:
                self.push_now(h)
            return len(seen)

        try:
            n = await asyncio.to_thread(scan)
            registry().inc("resync_rebalance_enqueued", n)
            if moved is None:
                registry().inc("resync_rebalance_full_scans")
            else:
                from ..rpc.layout.version import N_PARTITIONS

                registry().inc("resync_rebalance_partitions_scanned",
                               len(moved))
                registry().inc("resync_rebalance_partitions_skipped",
                               N_PARTITIONS - len(moved))
            log.info("layout v%d: %d blocks queued for rebalance (%s)",
                     version, n,
                     "full scan" if moved is None
                     else f"{len(moved)}/256 partitions")
            if self._enumerated_version is None \
                    or version > self._enumerated_version:
                self._enumerated_version = version
            self._enumerate_retry = None
        except Exception as e:
            # without this, a transient scan failure wedges the
            # transition until restart: the marker already says v, so
            # no layout-change hook will ever re-enumerate
            log.warning("layout v%d rebalance enumeration failed, "
                        "will retry: %s", version, e)
            self._enumerate_retry = (version, time.monotonic() + 5.0)
        finally:
            self._enumerating -= 1

    def maybe_report_synced(self) -> bool:
        """Once the rebalance backlog (queue AND error tree) is fully
        drained, report the block layer's sync position to the layout
        manager so the node's sync tracker — and with it old-version
        GC — can advance. Idempotent and cheap; called from the resync
        worker's idle path and the resize harness."""
        retry = self._enumerate_retry
        if retry is not None and not self._enumerating:
            rv, not_before = retry
            if time.monotonic() >= not_before:
                self._enumerate_retry = None
                self.enqueue_rebalance(rv)
            return False
        v = self._enumerated_version
        if v is None or self._enumerating or self._in_flight:
            return False
        if self.queue_len() or self.errors_len():
            return False
        s = getattr(self.manager, "system", None)
        lm = getattr(s, "layout_manager", None)
        if lm is None:
            return False
        # pessimistic tracker (ISSUE 16 residual): hold the report until
        # every OTHER sync source (the table syncers) has reported v.
        # block_ref rows land — and enqueue their fetches via the ref
        # trigger — strictly BEFORE their table source reports, so once
        # the tables are through and our queue/error/in-flight state is
        # empty, every row-triggered fetch has genuinely drained. Until
        # then an empty queue may only mean the rows haven't arrived
        # yet, and reporting would let the cluster GC a layout version
        # this node still needs to source those blocks from.
        if not lm.sources_synced_through(v, exclude="blocks"):
            return False
        lm.sync_until_from("blocks", v)
        return True

    # ---- breaker-aware placement ---------------------------------------

    def _placement_order(self, nodes) -> tuple[list[bytes], int]:
        """(candidates to try now, count skipped): peers ranked by
        breaker state (healthy first), with open-breaker peers dropped
        from this attempt entirely — pushing at a known-broken peer
        just burns its timeout and re-queues the block."""
        nodes = list(nodes)
        if not self.breaker_aware:
            return nodes, 0
        health = self.manager.rpc.health()
        if health is None:
            return nodes, 0
        now = time.monotonic()
        ranked = sorted(nodes,
                        key=lambda n: health.breaker_rank(n, now))
        keep = [n for n in ranked
                if health.breaker_state(n, now) != "open"]
        skipped = len(nodes) - len(keep)
        if skipped:
            registry().inc("resync_breaker_skip", skipped)
        return keep, skipped

    # ---- queue ---------------------------------------------------------

    @staticmethod
    def _qkey(at: float, hash32: bytes) -> bytes:
        return int(at * 1000).to_bytes(8, "big") + hash32

    def push_now(self, hash32: bytes) -> None:
        self.queue.insert(self._qkey(time.time(), hash32), b"")

    def push_at(self, hash32: bytes, at: float) -> None:
        self.queue.insert(self._qkey(at, hash32), b"")

    def queue_len(self) -> int:
        return len(self.queue)

    def due_len(self, cap: int = 4096) -> int:
        """Entries due NOW — excludes error-backoff and breaker-deferred
        requeues parked in the future, which sit in the queue without
        competing for anything. The governor reads this, not
        queue_len(): a peer outage parks thousands of blocks on 60 s+
        backoffs, and counting them as live pressure would throttle
        idle background work for minutes. Capped: the pressure signal
        saturates at resync_backlog_ref anyway."""
        now_ms = int(time.time() * 1000)
        n = 0
        # limit= keeps the tree from materializing the whole queue
        # under the db lock when an outage parks 100k+ future entries
        for k, _ in self.queue.iter(limit=cap):
            if int.from_bytes(k[:8], "big") > now_ms:
                break
            n += 1
        return n

    def errors_len(self) -> int:
        return len(self.errors)

    def _pop_due(self) -> Optional[bytes]:
        now = time.time()
        for k, _ in self.queue.iter():
            if int.from_bytes(k[:8], "big") > now * 1000:
                return None
            self.queue.remove(k)
            h = k[8:]
            # skip if errored and not yet due for retry
            e = self.errors.get(h)
            if e is not None:
                _, next_ms = self._parse_err(e)
                if next_ms > now * 1000:
                    self.queue.insert(self._qkey(next_ms / 1000, h), b"")
                    continue
            return h
        return None

    @staticmethod
    def _parse_err(raw: bytes) -> tuple[int, int]:
        return int.from_bytes(raw[:4], "big"), int.from_bytes(raw[4:], "big")

    def _record_error(self, hash32: bytes) -> None:
        e = self.errors.get(hash32)
        count = self._parse_err(e)[0] + 1 if e else 1
        delay = self.retry_delay * (2 ** min(count - 1, 6))
        # ±25% jitter: one node outage queues thousands of blocks in
        # the same second, and deterministic doubling would march them
        # all into synchronized retry storms against the recovering peer
        delay *= 1.0 + random.uniform(-0.25, 0.25)
        next_ms = int((time.time() + delay) * 1000)
        self.errors.insert(
            hash32, count.to_bytes(4, "big") + next_ms.to_bytes(8, "big")
        )
        self.queue.insert(self._qkey(next_ms / 1000, hash32), b"")

    def _clear_error(self, hash32: bytes) -> None:
        # NB: deliberately does NOT reset _defer_counts — a deferral
        # returns normally through the worker's success path, and
        # resetting there would defeat the DEFER_CAP escalation; the
        # count clears where the block's move actually completes
        self.errors.remove(hash32)

    def iter_errors(self, limit: int = 1000):
        """[(hash32, failures, next_try_ms)] — `block list-errors`."""
        out = []
        for h, raw in self.errors.iter(limit=limit):
            count, next_ms = self._parse_err(raw)
            out.append((h, count, next_ms))
        return out

    def retry_now(self, hashes=None, all_errors: bool = False) -> int:
        """Clear backoff + requeue (`block retry-now`)."""
        if all_errors:
            hashes = [h for h, _ in self.errors.iter(limit=1 << 20)]
        hashes = hashes or []
        for h in hashes:
            self._clear_error(h)
            self.push_now(h)
        return len(hashes)

    def spawn_workers(self, runner) -> None:
        for i in range(self.n_workers):
            runner.spawn_worker(ResyncWorker(self, i))

    # ---- the resync decision (ref: resync.rs:354-505) ------------------

    async def resync_block(self, hash32: bytes) -> None:
        m = self.manager
        needed = m.rc.is_needed(hash32)
        have = m.has_local(hash32)

        # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
        if have and not needed and m.rc.is_deletable_now(hash32):
            await self._offload(hash32)
            return
        if needed and not have:
            await self._fetch(hash32)
            return
        if needed and have and m.erasure:
            # do we hold the RIGHT shard for the current layout?
            await self._fix_shard_placement(hash32)

    async def _offload(self, hash32: bytes) -> None:
        """Not needed here: give our copy/shard to nodes that need it,
        then delete (ref: resync.rs:404-460). Breaker-aware: an
        open-breaker recipient defers the offload (backoff retry)
        instead of burning a timeout against a known-dead peer — and
        the local copy is NEVER deleted while a recipient was
        skipped."""
        m = self.manager
        me = m.system.id
        if m.erasure:
            placement = shard_nodes_of(m.system.layout_helper.current(),
                                       hash32, m.codec.width)
        else:
            placement = m.system.layout_helper.current_storage_nodes_of(hash32)
        candidates, skipped = self._placement_order(
            n for n in placement if n != me)
        for node in candidates:
            try:
                resp, _ = await m.endpoint.call(
                    node, {"op": "need", "hash": hash32}, PRIO_BACKGROUND
                )
                if not resp.get("needed"):
                    continue
                if m.erasure:
                    want = placement.index(node)
                    raw = await asyncio.to_thread(
                        m.read_local_shard, hash32, want)
                    if raw is None:
                        # rebuild their shard from what we can gather
                        raw = await self._rebuild_shard(hash32, want)
                    if raw is not None:
                        await m.endpoint.call(
                            node, {"op": "put", "hash": hash32,
                                   "part": want, "data": raw},
                            PRIO_BACKGROUND,
                        )
                        m.metrics["resync_bytes"] += len(raw)
                else:
                    packed = await asyncio.to_thread(m.read_local,
                                                     hash32)
                    if packed is not None:
                        await m.endpoint.call(
                            node, {"op": "put", "hash": hash32,
                                   "part": None, "data": packed},
                            PRIO_BACKGROUND,
                        )
                        m.metrics["resync_bytes"] += len(packed)
                m.metrics["resync_sent"] += 1
            except Exception as e:
                log.info("offload %s to %s failed: %s",
                         hash32[:4].hex(), node[:4].hex(), e)
                raise
        if skipped:
            # a recipient with an open breaker never got its copy: keep
            # ours and retry on the BREAKER's timescale (~cooldown, or
            # the error backoff once the deferral cap is hit) — either
            # way the pending queue/error entry keeps the node
            # correctly un-synced until the offload completes
            # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
            if not self._defer(hash32):
                raise RuntimeError(
                    f"offload deferred > {self.DEFER_CAP}× on "
                    f"breaker-open recipients ({skipped} skipped)")
            registry().inc("resync_offload_deferred", skipped)
            return
        await asyncio.to_thread(m.delete_local, hash32)
        await asyncio.to_thread(m.rc.clear_deletable, hash32)
        self._defer_counts.pop(hash32, None)

    # consecutive breaker deferrals before a block escalates to the
    # exponential error backoff (~cap × BREAKER_COOLDOWN of fast
    # retries buys a briefly-down peer its recovery window)
    DEFER_CAP = 6

    def _defer(self, hash32: bytes) -> bool:
        """An op that failed while peers sat behind an open breaker is
        a deferral, not a failure: requeue on the breaker's timescale
        instead of landing in the error tree, whose 60 s-doubling
        backoff would block the layout sync report for minutes on a
        peer that recovers in seconds. Returns False once the block
        has deferred DEFER_CAP times in a row — the caller must then
        treat it as a real failure so a permanently dead peer gets the
        exponential backoff, not a probe every cooldown forever.
        (Callers count the deferral under their own literal metric
        name — GL07.)"""
        n = self._defer_counts.get(hash32, 0) + 1
        if n > self.DEFER_CAP:
            return False
        self._defer_counts[hash32] = n
        from ..net.peering import BREAKER_COOLDOWN

        self.push_at(hash32, time.time() + BREAKER_COOLDOWN)
        return True

    def _open_breaker_holders(self, hash32: bytes) -> int:
        """Holders of hash32 (any readable layout version, excluding
        us) whose breaker is currently open."""
        if not self.breaker_aware:
            return 0
        m = self.manager
        health = m.rpc.health()
        if health is None:
            return 0
        me = m.system.id
        now = time.monotonic()
        return sum(1 for n in m.system.layout_helper
                   .block_read_nodes_of(hash32)
                   if n != me
                   and health.breaker_state(n, now) == "open")

    async def _fetch(self, hash32: bytes) -> None:
        """Needed but absent: get it (ref: resync.rs:462-505).

        Replicate fetches of HINTED-HOT blocks route through the
        cluster cache tier first (ISSUE 15): if a peer's gossiped
        hot-hash hints say the block is hot, one probe to its cache
        owner replaces the remote packed read, and the payload is
        re-packed locally (any compression variant of the right plain
        bytes is a valid replica — the content address covers the
        plain bytes). Cold blocks never probe: a rebalance enumeration
        of the whole store must not spray one wasted RPC per block.
        Erasure SHARD fetches ride the PACKED tier (ISSUE 18) via
        _rebuild_shard: the cached bytes are the exact packed block the
        stripe was cut from, so the deterministic re-encode reproduces
        byte-identical shards — the old recompression restriction only
        applied to the DECODED segment."""
        m = self.manager
        if not m.erasure:
            if await self._fetch_via_tier(hash32):
                return
            try:
                packed, _verified = await m._get_replicate(hash32)
            except Exception:
                skipped = self._open_breaker_holders(hash32)
                # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
                if skipped and self._defer(hash32):
                    registry().inc("resync_fetch_deferred", skipped)
                    return
                raise
            await asyncio.to_thread(m.write_local, hash32, packed)
            self._defer_counts.pop(hash32, None)
            m.metrics["resync_recv"] += 1
            m.metrics["resync_bytes"] += len(packed)
            return
        # erasure: our assigned shard, fetched or rebuilt
        placement = shard_nodes_of(m.system.layout_helper.current(),
                                   hash32, m.codec.width)
        me = m.system.id
        if me not in placement:
            return  # not a holder anymore; nothing to fetch
        want = placement.index(me)
        raw, skipped = await self._fetch_shard(hash32, placement, want)
        if raw is None:
            raw = await self._rebuild_shard(hash32, want)
        if raw is None:
            if skipped and self._defer(hash32):
                registry().inc("resync_fetch_deferred", skipped)
                return
            raise MissingBlock(hash32)
        await asyncio.to_thread(m.write_local_shard, hash32, want, raw)
        self._defer_counts.pop(hash32, None)
        m.metrics["resync_recv"] += 1
        m.metrics["resync_bytes"] += len(raw)

    async def _fetch_via_tier(self, hash32: bytes) -> bool:
        """Hint-gated tier read for a replicate fetch: True when the
        block landed locally via the cache tier (probe hit at the
        owner, content-verified there, re-packed and stored here)."""
        m = self.manager
        tier = getattr(m, "cache_tier", None)
        if tier is None or not tier.is_hot(hash32):
            return False
        owner = tier.owner_of(hash32)
        if owner is None:
            return False
        data = await tier.probe(owner, hash32)
        if data is None:
            return False
        from .block import DataBlock

        blk = (await asyncio.to_thread(DataBlock.compress, data)
               if m.compression else DataBlock.plain(data))
        await asyncio.to_thread(m.write_local_payload, hash32,
                                blk.compression, blk.bytes)
        registry().inc("cache_tier_resync_hits")
        self._defer_counts.pop(hash32, None)
        m.metrics["resync_recv"] += 1
        m.metrics["resync_bytes"] += len(data)
        return True

    async def _fix_shard_placement(self, hash32: bytes) -> None:
        """After a layout change we may hold shard j but be assigned
        shard i: fetch/rebuild i; the stale j is dropped once rc says
        deletable (or by offload on the next pass)."""
        m = self.manager
        placement = shard_nodes_of(m.system.layout_helper.current(),
                                   hash32, m.codec.width)
        me = m.system.id
        if me not in placement:
            return
        want = placement.index(me)
        if want in m.local_parts(hash32):
            return
        raw, skipped = await self._fetch_shard(hash32, placement, want)
        if raw is None:
            raw = await self._rebuild_shard(hash32, want)
        if raw is None:
            # don't swallow: draining the queue without our assigned
            # shard would let maybe_report_synced declare the layer
            # synced — and old-version GC proceed — while this node is
            # below the erasure tolerance the layout claims
            # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
            if skipped and self._defer(hash32):
                registry().inc("resync_fetch_deferred", skipped)
                return
            raise MissingBlock(hash32)
        await asyncio.to_thread(m.write_local_shard, hash32, want, raw)
        self._defer_counts.pop(hash32, None)

    async def _fetch_shard(self, hash32: bytes, placement: list[bytes],
                           idx: int) -> tuple[Optional[bytes], int]:
        """Ask everyone for shard idx (an old holder may have it) —
        healthy holders first, open-breaker ones not at all (the
        backoff retry returns once their breaker closes). Returns
        (data, holders skipped for an open breaker) so the caller can
        tell a deferral from a real miss."""
        m = self.manager
        candidates, skipped = self._placement_order(
            n for n in placement if n != m.system.id)
        for node in candidates:
            try:
                resp, _ = await m.endpoint.call(
                    node, {"op": "get", "hash": hash32, "part": idx},
                    PRIO_BACKGROUND,
                )
                if resp.get("data") is not None:
                    return resp["data"], skipped
            except Exception as e:
                log.debug("resync shard fetch part=%d from %s "
                          "failed: %s", idx, node[:4].hex(), e)
                continue
        return None, skipped

    async def _rebuild_shard(self, hash32: bytes, idx: int) -> Optional[bytes]:
        """RS repair: gather any k parts, recompute shard idx through
        the feeder's batched `repair` op — concurrent resync workers'
        rebuilds (a repair/rebalance wave) coalesce into one
        pattern-as-data device launch instead of one host matmul per
        stripe on the event loop.

        Packed-tier fast path (ISSUE 18): when the packed block bytes
        are in the tier (local segment, or a hint-gated owner probe),
        the deterministic RS encode regenerates ALL shards byte-
        identically from them — zero gather RPCs, zero repair matmul.
        encode_put's framing is pack_shard(crc32c) like the original
        PUT, so the rebuilt file is the file that was lost."""
        m = self.manager
        packed = await m.packed_from_tier(hash32)
        if packed is not None:
            try:
                framed = await m.feeder.encode_put(bytes(packed))
                if idx < len(framed):
                    self.rebuild_tier_hits += 1
                    registry().inc("cache_packed_rebuild_hit")
                    return bytes(framed[idx])
            except Exception as e:
                log.debug("packed-tier rebuild of %s.s%d failed: %s "
                          "(falling back to gather)",
                          hash32[:4].hex(), idx, e)
        placement = shard_nodes_of(m.system.layout_helper.current(),
                                   hash32, m.codec.width)
        got = await m._gather_parts(hash32, placement, m.codec.read_need)
        if got is None:
            return None
        parts, len_candidates, _lens = got
        packed_len = len_candidates[0]  # majority vote
        if idx in parts:
            # lint: ignore[GL10] pack_shard's crc is native-C microseconds; the flagged open/cc chain is the one-time kernel build, cached for the process lifetime
            return pack_shard(parts[idx], packed_len)
        present = tuple(sorted(parts.keys())[: m.codec.read_need])
        rebuilt = await m.feeder.repair(present, (idx,),
                                        [parts[i] for i in present])
        return pack_shard(rebuilt[idx], packed_len)


class ResyncWorker(Worker):
    def __init__(self, resync: BlockResyncManager, i: int):
        self.resync = resync
        self.name = f"block resync {i}"

    async def work(self):
        # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
        h = self.resync._pop_due()
        if h is None:
            # backlog drained: report the block layer's layout-sync
            # position so old layout versions can be GC'd (no-op
            # unless a rebalance actually completed)
            self.resync.maybe_report_synced()
            return WState.IDLE
        self.resync._in_flight += 1
        try:
            await self.resync.resync_block(h)
            self.resync._clear_error(h)
        except Exception as e:
            log.info("resync %s failed: %s", h[:4].hex(), e)
            # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
            self.resync._record_error(h)
        finally:
            self.resync._in_flight -= 1
        if self.resync.tranquility > 0:
            from ..utils.background import Throttled

            return Throttled(self.resync.tranquility)
        return WState.BUSY

    async def wait_for_work(self):
        await asyncio.sleep(1.0)

    def info(self):
        from ..utils.background import WorkerInfo

        return WorkerInfo(
            name=self.name,
            queue_length=self.resync.queue_len(),
            persistent_errors=self.resync.errors_len(),
        )
