"""Multi-drive data layout: where a block lives on this node's disks.

Ref parity: src/block/layout.rs. 1024 sub-partitions (top 10 bits of the
block hash) map to data directories proportionally to their capacity;
each sub-partition has a primary and (during rebalances) secondary dirs.
On-disk path: {dir}/{hex(hash[0])}/{hex(hash[1])}/{full hex}[suffix]
(ref: layout.rs:262-291, HASH_DRIVE_BYTES).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..utils import migrate

DRIVE_NPART = 1024  # ref: layout.rs:13


@dataclass
class DataDir:
    path: str
    capacity: int  # bytes; 0 = read-only (drain)


class DataLayout(migrate.Migratable):
    """ref: layout.rs DataLayout."""

    VERSION_MARKER = b"GTdlay01"

    def __init__(self, dirs: list[DataDir], part_prim: list[int],
                 part_sec: list[list[int]]):
        self.dirs = dirs
        self.part_prim = part_prim  # sub-partition -> dir index
        self.part_sec = part_sec  # sub-partition -> old dir indices

    @classmethod
    def initialize(cls, dirs: list[DataDir]) -> "DataLayout":
        lay = cls(dirs, [], [[] for _ in range(DRIVE_NPART)])
        lay.part_prim = cls._assign(dirs)
        return lay

    @classmethod
    def single(cls, path: str) -> "DataLayout":
        return cls.initialize([DataDir(path, 1)])

    @staticmethod
    def _assign(dirs: list[DataDir]) -> list[int]:
        """Capacity-proportional striped assignment (deterministic)."""
        writable = [(i, d.capacity) for i, d in enumerate(dirs) if d.capacity > 0]
        if not writable:
            raise ValueError("no writable data dir")
        total = sum(c for _, c in writable)
        out, acc = [], [0.0] * len(writable)
        for _ in range(DRIVE_NPART):
            for j, (_, c) in enumerate(writable):
                acc[j] += c / total
            j = max(range(len(writable)), key=lambda j: acc[j])
            acc[j] -= 1.0
            out.append(writable[j][0])
        return out

    def update_dirs(self, dirs: list[DataDir]) -> "DataLayout":
        """New drive set: recompute primaries, remember old location as
        secondary so reads keep working until rebalance moves the files
        (ref: layout.rs update)."""
        new_prim = self._assign(dirs)
        old_paths = [d.path for d in self.dirs]
        path_to_new = {d.path: i for i, d in enumerate(dirs)}
        sec = []
        for p in range(DRIVE_NPART):
            s = set()
            old_i = self.part_prim[p] if p < len(self.part_prim) else None
            if old_i is not None and old_i < len(old_paths):
                ni = path_to_new.get(old_paths[old_i])
                if ni is not None and ni != new_prim[p]:
                    s.add(ni)
            for oi in (self.part_sec[p] if p < len(self.part_sec) else []):
                if oi < len(old_paths):
                    ni = path_to_new.get(old_paths[oi])
                    if ni is not None and ni != new_prim[p]:
                        s.add(ni)
            sec.append(sorted(s))
        return DataLayout(dirs, new_prim, sec)

    # ---- path resolution ----------------------------------------------

    @staticmethod
    def subpart_of(hash32: bytes) -> int:
        return (hash32[0] << 2) | (hash32[1] >> 6)  # top 10 bits

    def _dir_path(self, dir_idx: int, hash32: bytes) -> str:
        return os.path.join(
            self.dirs[dir_idx].path, hash32[:1].hex(), hash32[1:2].hex()
        )

    def primary_dir(self, hash32: bytes) -> str:
        return self._dir_path(self.part_prim[self.subpart_of(hash32)], hash32)

    def candidate_dirs(self, hash32: bytes) -> list[str]:
        p = self.subpart_of(hash32)
        out = [self._dir_path(self.part_prim[p], hash32)]
        for i in self.part_sec[p]:
            out.append(self._dir_path(i, hash32))
        return out

    def block_path(self, hash32: bytes, suffix: str = "") -> str:
        return os.path.join(self.primary_dir(hash32), hash32.hex() + suffix)

    # ---- serialization -------------------------------------------------

    def pack(self):
        return {
            "dirs": [[d.path, d.capacity] for d in self.dirs],
            "prim": self.part_prim,
            "sec": self.part_sec,
        }

    @classmethod
    def unpack(cls, o):
        return cls([DataDir(p, c) for p, c in o["dirs"]], o["prim"], o["sec"])
