"""Block store: the content-addressed data plane.

Ref parity: src/block/ (SURVEY.md §2.6). Blocks (≤1 MiB by default) are
keyed by the blake2 hash of their plain content and stored as files;
metadata refcounts arrive via the block_ref table trigger; a persistent
resync queue repairs missing/superfluous copies; scrub re-verifies every
stored byte.

TPU-native extension (the north star, BASELINE.md): the `BlockCodec`
boundary generalizes "replicate N whole copies" to "erasure(k, m)
stripes" whose GF(2^8) Reed-Solomon math runs as batched XLA/Pallas ops
(ops/rs.py) — encode on put, decode-any-k on get, parity-check on scrub.
"""

from .block import DataBlock, COMPRESSION_ZLIB, COMPRESSION_ZSTD  # noqa: F401
from .cache import BlockCache  # noqa: F401
from .codec import BlockCodec, ReplicateCodec, ErasureCodec  # noqa: F401
from .layout import DataLayout  # noqa: F401
from .rc import BlockRc  # noqa: F401
from .manager import BlockManager  # noqa: F401
from .resync import BlockResyncManager  # noqa: F401
from .repair import ScrubWorker, RepairWorker  # noqa: F401
