"""BlockCodec: the replicate-N / erasure(k,m) plugin boundary.

This is the north-star extension point (SURVEY.md §2.11 item 8,
BASELINE.md): the reference only replicates whole blocks
(rpc/replication_mode.rs); here the block data path is generic over a
codec that turns one block into `width` placed parts and back.

- ReplicateCodec(n): every part IS the whole block (the reference's
  behavior); any 1 part reconstructs.
- ErasureCodec(k, m): parts are Reed-Solomon GF(2^8) shards computed by
  the TPU data plane (ops/rs.py — Cauchy matrix, bit-matmul
  formulation); any k of k+m reconstruct. Writes are durable once
  `write_quorum` parts land; scrub can verify parity instead of
  re-reading every replica.

Shard placement uses the ring: part i of a block in partition p goes to
the i-th distinct node walking the ring from p (`shard_nodes_of`) — so
erasure width may exceed the metadata replication factor.
"""

from __future__ import annotations

import numpy as np

from ..ops import rs
from ..utils.data import content_hash_matches
from ..utils.error import MissingBlock


class BlockCodec:
    """width parts per block; any `read_need` reconstruct."""

    width: int
    read_need: int
    write_quorum: int

    def encode(self, data: bytes) -> list[bytes]:
        raise NotImplementedError

    def decode(self, parts: dict[int, bytes], plain_len: int) -> bytes:
        """parts: {part_index: bytes}, at least read_need of them."""
        raise NotImplementedError

    def parity_ok(self, parts: dict[int, bytes], hash32: bytes) -> bool:
        """Scrub check: do these parts reconstruct the block?"""
        raise NotImplementedError


class ReplicateCodec(BlockCodec):
    def __init__(self, n: int, write_quorum: int | None = None):
        self.width = n
        self.read_need = 1
        self.write_quorum = write_quorum if write_quorum is not None \
            else max(1, n // 2 + 1)

    def encode(self, data: bytes) -> list[bytes]:
        return [data] * self.width

    def decode(self, parts, plain_len):
        for _, b in sorted(parts.items()):
            return b
        raise MissingBlock(b"")

    def parity_ok(self, parts, hash32):
        return any(content_hash_matches(b, hash32) for b in parts.values())


class ErasureCodec(BlockCodec):
    """RS(k, m) striping; the math runs through ops/rs (jax on TPU,
    numpy fallback for tiny/offline use)."""

    def __init__(self, k: int, m: int, write_quorum: int | None = None,
                 use_jax: bool | None = None):
        self.k, self.m = k, m
        self.width = k + m
        self.read_need = k
        # durable-against-m-failures default (replication_mode.py):
        self.write_quorum = write_quorum if write_quorum is not None \
            else min(k + (m + 1) // 2, k + m)
        self._use_jax = use_jax

    def _jax_ok(self) -> bool:
        if self._use_jax is None:
            try:
                import jax  # noqa: F401

                self._use_jax = True
            except Exception:
                self._use_jax = False
        return self._use_jax

    def encode(self, data: bytes) -> list[bytes]:
        shards = rs.split_stripe(data, self.k)  # (k, slen) uint8, padded
        if self._jax_ok():
            parity = np.asarray(rs.encode(self.k, self.m, shards[None])[0])
        else:
            parity = rs.encode_np(self.k, self.m, shards)
        return [bytes(s) for s in shards] + [bytes(p) for p in parity]

    def encode_batch(self, blocks: list[bytes]) -> list[list[bytes]]:
        """Batched TPU path: encode many equal-ish blocks in one XLA
        call (pads to the longest; the per-part framing keeps true
        lengths). This is where MXU batching pays (BASELINE.md)."""
        if not blocks:
            return []
        slens = [rs.shard_len(len(b), self.k) for b in blocks]
        smax = max(slens)
        batch = np.zeros((len(blocks), self.k, smax), dtype=np.uint8)
        for i, b in enumerate(blocks):
            sh = rs.split_stripe(b, self.k)
            batch[i, :, : sh.shape[1]] = sh
        if self._jax_ok():
            parity = np.asarray(rs.encode(self.k, self.m, batch))
        else:
            parity = np.stack(
                [rs.encode_np(self.k, self.m, batch[i]) for i in range(len(blocks))]
            )
        out = []
        for i, b in enumerate(blocks):
            sl = slens[i]
            out.append(
                [bytes(batch[i, j, :sl]) for j in range(self.k)]
                + [bytes(parity[i, j, :sl]) for j in range(self.m)]
            )
        return out

    def decode(self, parts: dict[int, bytes], plain_len: int) -> bytes:
        """HOST-ONLY single-stripe decode (numpy). The device route is
        the feeder's batched `decode` op (BlockManager._decode_parts):
        a synchronous per-block device round-trip here would block the
        CALLER's thread on the tunnel — and the old `_jax_ok` branch
        also jitted one XLA program per erasure pattern (the unbounded
        `dec{k},{m},{present}` cache). Callers that can batch go
        through the feeder; everyone else gets the numpy path."""
        if len(parts) < self.k:
            raise MissingBlock(b"")
        idx = tuple(sorted(parts.keys())[: self.k])
        shards = np.stack(
            [np.frombuffer(parts[i], dtype=np.uint8) for i in idx]
        )
        if all(i < self.k for i in idx):
            data = shards  # all-systematic fast path: no math needed
        else:
            data = rs.decode_np(self.k, self.m, idx, shards)
        return rs.join_stripe(data, plain_len)

    def repair_parts(self, parts: dict[int, bytes],
                     missing: tuple[int, ...]) -> dict[int, bytes]:
        """Recompute lost shards from any k present ones. Host-only,
        one precomposed repair-matrix matmul per stripe (same rule as
        decode: the batched device route is feeder.repair)."""
        idx = tuple(sorted(parts.keys())[: self.k])
        shards = np.stack(
            [np.frombuffer(parts[i], dtype=np.uint8) for i in idx]
        )
        out = rs.repair_np(self.k, self.m, idx, tuple(missing), shards)
        return {mi: bytes(out[j]) for j, mi in enumerate(missing)}

    def parity_ok(self, parts: dict[int, bytes], hash32: bytes) -> bool:
        """All width parts present and mutually consistent: systematic
        shards re-encode to the stored parity."""
        if len(parts) < self.k:
            return False
        try:
            data = np.stack(
                [np.frombuffer(parts[i], dtype=np.uint8) for i in range(self.k)]
            )
        except KeyError:
            # missing a systematic shard: decode then compare what exists
            try:
                idx = tuple(sorted(parts.keys())[: self.k])
                shards = np.stack(
                    [np.frombuffer(parts[i], dtype=np.uint8) for i in idx]
                )
                data = rs.decode_np(self.k, self.m, idx, shards)
            except Exception:
                return False
        parity = rs.encode_np(self.k, self.m, data)
        for i, p in parts.items():
            if i >= self.k and bytes(parity[i - self.k]) != p:
                return False
            if i < self.k and bytes(data[i]) != p:
                return False
        return True


def shard_nodes_of(layout_version, hash32: bytes, width: int) -> list[bytes]:
    """`width` distinct nodes for a block's parts: the ring nodes of its
    partition, then of successive partitions, dedup'd, in order. For
    replicate-n this equals nodes_of (width == rf). Deterministic given
    a layout version, so every node computes the same placement."""
    from ..rpc.layout.version import N_PARTITIONS, partition_of

    p0 = partition_of(hash32)
    out: list[bytes] = []
    for off in range(N_PARTITIONS):
        for n in layout_version.nodes_of((p0 + off) % N_PARTITIONS):
            if n not in out:
                out.append(n)
                if len(out) == width:
                    return out
    return out  # cluster smaller than width: best effort
