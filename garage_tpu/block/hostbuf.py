"""Pinned host ingest buffers: the zero-copy landing zone of the PUT
path (ISSUE 17).

The S3 frontend used to re-materialize every PUT body several times
between the socket and the accelerator: the reader returned bytes, the
Chunker joined them into a block, DataBlock prepended its header,
split_stripe reshaped a padded copy, and the feeder's h2d stage packed
yet another padded batch. Each hop is a MiB-scale memcpy on the one
core that also runs the event loop — the r05 captures showed the RS
kernel idling at ~1% feed rate while the frontend shuffled bytes.

This module provides a small pool of PREALLOCATED flat buffers laid
out exactly as the erasure stripe the device consumes:

    [ scheme byte ][ body (block_size bytes) ][ zero tail ]
    '------------------ k * shard_len --------------------'

`rs.split_stripe(prefix + body, k)` is a zero-pad + row-major reshape,
so a full block landed in this layout IS the staged stripe: viewing the
flat buffer as (k, shard_len) is byte-identical to what the copy path
builds, and the feeder's h2d stage can `device_put` it directly. Socket
bytes are copied ONCE — into the leased buffer slice, by the body
reader's readinto1 — and every later stage (hashing, compression
probing, RS staging) reads views over the same memory.

Leases are loop-confined (acquired and released on the event loop
thread, like everything else in the PUT path). Exhaustion is
BACKPRESSURE, not allocation: acquire() parks the caller on a FIFO of
waiters until a release hands its buffer over, so a burst of PUTs
degrades to queueing instead of unbounded RAM. release() is idempotent
per lease, which keeps the abort paths simple: the request's finally,
a cancelled put task, and the conservation check can all release
without coordinating who got there first.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import numpy as np

from ..utils.metrics import registry


def stripe_shard_len(total: int, k: int) -> int:
    """ceil(total / k) — ops.rs.shard_len without the jax import (this
    module must stay importable from lightweight contexts)."""
    return (total + k - 1) // k


class BlockLease:
    """One leased buffer, valid until release(). Single-use: the pool
    hands out a fresh lease object per acquisition, so the released
    flag makes double-release a no-op instead of a recycle hazard."""

    __slots__ = ("pool", "buf", "k", "slen", "cap", "length", "released")

    def __init__(self, pool: "HostBufPool", buf: np.ndarray):
        self.pool = pool
        self.buf = buf  # flat uint8, k * slen; [0]=scheme, [1:1+cap]=body
        self.k = pool.k
        self.slen = pool.slen
        self.cap = pool.cap
        self.length = 0  # valid body bytes (set by the filler)
        self.released = False

    def __len__(self) -> int:
        return self.length

    @property
    def full(self) -> bool:
        return self.length == self.cap

    @property
    def total_len(self) -> int:
        """prefix byte + body — the packed stripe length pack_shard
        frames (what len(prefix + data) is on the copy path)."""
        return 1 + self.length

    def body_mv(self) -> memoryview:
        """Writable view of the whole body region (the reader's
        readinto1 target; the filler tracks its own offset)."""
        return memoryview(self.buf)[1:1 + self.cap]

    def view(self) -> memoryview:
        """The valid body bytes — what hashing/compression/parity read
        (and what bytes() materializes on the classic-path fallback)."""
        return memoryview(self.buf)[1:1 + self.length]

    def set_scheme(self, scheme: int) -> None:
        """Write the 1-byte DataBlock header in place (the prefix the
        copy path concatenates)."""
        self.buf[0] = scheme

    def stripe(self) -> np.ndarray:
        """(k, slen) view over the flat buffer — byte-identical to
        rs.split_stripe(prefix + body, k) for a FULL block (the tail
        past 1 + cap is kept zero for the life of the pool; see
        HostBufPool.__init__). Callers must check `full` first: a
        partial block's true shard length is smaller and takes the
        classic copy path."""
        return self.buf.reshape(self.k, self.slen)

    def release(self) -> None:
        self.pool.release(self)


class HostBufPool:
    """Fixed pool of `count` stripe-layout buffers for blocks of up to
    `block_size` body bytes split k ways. Loop-confined (no locks)."""

    def __init__(self, k: int, block_size: int, count: int):
        self.k = max(1, int(k))
        self.cap = int(block_size)
        self.slen = stripe_shard_len(1 + self.cap, self.k)
        self.count = max(1, int(count))
        # zeroed ONCE: body writes stay inside [1:1+cap] and the scheme
        # byte inside [0], so the reshape tail (< k bytes) remains zero
        # for the pool's lifetime — the invariant stripe() relies on
        self._free: deque[np.ndarray] = deque(
            np.zeros(self.k * self.slen, dtype=np.uint8)
            for _ in range(self.count))
        self._waiters: deque = deque()
        self._outstanding = 0

    def outstanding(self) -> int:
        """Leases issued and not yet released — the sanitizer
        conservation check asserts this returns to 0 after every
        request, abort paths included."""
        return self._outstanding

    def _issue(self, buf: np.ndarray) -> BlockLease:
        self._outstanding += 1
        return BlockLease(self, buf)

    def try_acquire(self) -> Optional[BlockLease]:
        if not self._free:
            return None
        return self._issue(self._free.popleft())

    async def acquire(self) -> BlockLease:
        """FIFO backpressure: when the pool is dry, park until a
        release hands this waiter a buffer directly (never allocates —
        a PUT burst queues instead of growing RAM)."""
        lease = self.try_acquire()
        if lease is not None:
            return lease
        import asyncio

        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        registry().inc("s3_ingest_buf_wait")
        return await fut

    def release(self, lease: BlockLease) -> None:
        if lease.released:
            return  # idempotent: abort paths release without electing an owner
        lease.released = True
        self._outstanding -= 1
        buf = lease.buf
        while self._waiters:
            fut = self._waiters.popleft()
            if fut.cancelled():
                continue
            fut.set_result(self._issue(buf))
            return
        self._free.append(buf)

    def stats(self) -> dict:
        return {"count": self.count, "free": len(self._free),
                "outstanding": self._outstanding,
                "waiters": len(self._waiters),
                "buf_bytes": self.k * self.slen}
