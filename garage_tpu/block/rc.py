"""Block reference counts.

Ref parity: src/block/rc.rs. The block_ref table trigger calls
block_incref/block_decref inside ITS transaction; the rc states are
Present{count} / Deletable{at} (GC delay so late readers finish) /
Absent. `recalculate_rc` rebuilds a count from the registered
CalculateRefcount callbacks (repair path, rc.rs:83-130).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

BLOCK_GC_DELAY = 600.0  # ref: block/manager.rs:51


class BlockRc:
    def __init__(self, db, gc_delay: float = BLOCK_GC_DELAY):
        self.db = db
        self.tree = db.open_tree("block_local_rc")
        self.gc_delay = gc_delay
        self.calculate_cbs: list[Callable[[bytes], int]] = []

    # values: b"C" + u64 count   | b"D" + f64 deletable-at-unixtime
    @staticmethod
    def _pack_count(n: int) -> bytes:
        return b"C" + n.to_bytes(8, "big")

    @staticmethod
    def _pack_deletable(at: float) -> bytes:
        return b"D" + int(at * 1000).to_bytes(8, "big")

    @classmethod
    def parse(cls, raw: Optional[bytes]) -> tuple[str, float]:
        """-> ("absent", 0) | ("present", count) | ("deletable", at)."""
        if raw is None:
            return ("absent", 0)
        if raw[:1] == b"C":
            return ("present", int.from_bytes(raw[1:], "big"))
        return ("deletable", int.from_bytes(raw[1:], "big") / 1000.0)

    # ---- transactional ops (called from table triggers) ----------------

    def block_incref(self, tx, hash32: bytes) -> bool:
        """Returns True if the block became newly needed
        (absent/deletable -> present), so the caller queues a resync
        fetch (ref: rc.rs:38-58)."""
        state, v = self.parse(tx.get(self.tree, hash32))
        if state == "present":
            tx.insert(self.tree, hash32, self._pack_count(int(v) + 1))
            return False
        tx.insert(self.tree, hash32, self._pack_count(1))
        # both absent->present and deletable->present need a resync
        # examination (ref rc.rs: old_rc.is_zero() covers Deletable too)
        return True

    def block_decref(self, tx, hash32: bytes) -> bool:
        """Returns True if the block became deletable (count hit 0), so
        the caller queues a resync to offload/delete (ref: rc.rs:60-81)."""
        state, v = self.parse(tx.get(self.tree, hash32))
        if state != "present":
            return state == "deletable"
        n = int(v) - 1
        if n > 0:
            tx.insert(self.tree, hash32, self._pack_count(n))
            return False
        tx.insert(self.tree, hash32,
                  self._pack_deletable(time.time() + self.gc_delay))
        return True

    # ---- queries -------------------------------------------------------

    def get(self, hash32: bytes) -> tuple[str, float]:
        return self.parse(self.tree.get(hash32))

    def is_needed(self, hash32: bytes) -> bool:
        return self.get(hash32)[0] == "present"

    def is_deletable_now(self, hash32: bytes) -> bool:
        state, at = self.get(hash32)
        return state == "deletable" and time.time() >= at

    def clear_deletable(self, hash32: bytes) -> None:
        def body(tx):
            state, _ = self.parse(tx.get(self.tree, hash32))
            if state == "deletable":
                tx.remove(self.tree, hash32)

        self.db.transaction(body)

    def all_hashes(self):
        for k, _ in self.tree.iter():
            yield k

    # ---- repair (ref: rc.rs:83-130) ------------------------------------

    def register_calculator(self, cb: Callable[[bytes], int]) -> None:
        self.calculate_cbs.append(cb)

    def recalculate(self, hash32: bytes) -> int:
        count = sum(cb(hash32) for cb in self.calculate_cbs)

        def body(tx):
            state, v = self.parse(tx.get(self.tree, hash32))
            if count > 0:
                tx.insert(self.tree, hash32, self._pack_count(count))
            elif state == "present":
                tx.insert(self.tree, hash32,
                          self._pack_deletable(time.time() + self.gc_delay))

        self.db.transaction(body)
        return count
