"""Hot-block read cache: a node-local, content-addressed RAM cache of
decoded block payloads.

The block store is content-addressed, so a cached payload can never be
stale — the hash IS the identity, and invalidation reduces to "drop the
entry when the node stops wanting to hold RAM for it" (delete/decref).
What a hit saves depends on the codec: replicate mode skips a disk read
+ content-hash verify (+ decompress); erasure mode skips the whole
k-shard gather over RPC, the GF(2^8) decode, and the verify.

Design (the CacheLib discipline named in ISSUE 3 — cache what is
expensive to rebuild, admission-filter what is scanned once):

  * Byte-budget SLRU, two segments. New entries land in a PROBATION
    segment; only a re-reference promotes into the PROTECTED segment.
    The protected segment is capped at (100 - probation_pct)% of the
    budget and is never evicted by inserts — so one full-object
    streaming read (every block touched exactly once) churns through
    probation and cannot displace the hot set. Probation itself is
    elastic: it may use whatever the protected segment doesn't, so a
    cold cache still admits a full working set on first touch.
  * Overflowing the protected cap demotes its LRU entries back to the
    MRU end of probation (one more trip around before eviction),
    mirroring classic SLRU.
  * Oversize entries (> max_bytes // 8) are rejected outright: one
    giant block must not be able to flush a whole segment.
  * Write-through PUTs insert into probation like read fills — freshly
    written blocks are the hottest, but a bulk upload is still a scan
    and must not evict the protected set.

Thread-safety: a plain lock around every operation. Hits happen on the
event loop, but purges arrive from table-trigger commit hooks and
delete_local can be driven from worker threads; the critical sections
are a few dict moves, so the lock is never contended for long.

SSE-C exclusion is the CALLER's job (`cacheable=False` on the manager
seam): those payloads are ciphertext the node can re-derive only while
the client's key is in hand, and the conservative rule is to never let
them outlive the request in RAM.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional


class BlockCache:
    """Content-addressed byte-budget SLRU. max_bytes == 0 disables the
    cache entirely (every call is a cheap no-op and no stats move)."""

    def __init__(self, max_bytes: int, probation_pct: int = 20):
        self._lock = threading.Lock()
        # hash -> bytes; OrderedDict order = LRU (oldest first)
        self._prob: OrderedDict[bytes, bytes] = OrderedDict()
        self._prot: OrderedDict[bytes, bytes] = OrderedDict()
        self._prob_bytes = 0
        self._prot_bytes = 0
        # per-key hit counts for entries currently IN the cache — the
        # hot-hash hint source (cache_tier.py gossips the top-N over
        # peering pings). Bounded by construction: an entry leaves the
        # map when it leaves the cache.
        self._hits_by_key: dict[bytes, int] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.inserts = 0
        self.rejected = 0
        self.hit_bytes = 0
        self.configure(max_bytes=max_bytes, probation_pct=probation_pct)

    # ---- configuration -------------------------------------------------

    def configure(self, max_bytes: Optional[int] = None,
                  probation_pct: Optional[int] = None) -> None:
        """Runtime retune (admin POST /v1/s3/tuning). Shrinking the
        budget evicts immediately; 0 clears and disables."""
        with self._lock:
            if max_bytes is not None:
                if max_bytes < 0:
                    raise ValueError("max_bytes must be >= 0")
                self.max_bytes = int(max_bytes)
            if probation_pct is not None:
                if not 1 <= probation_pct <= 90:
                    raise ValueError("probation_pct must be in [1, 90]")
                self.probation_pct = int(probation_pct)
            self._prot_cap = self.max_bytes \
                * (100 - self.probation_pct) // 100
            self._max_entry = self.max_bytes // 8
            self._shed_protected()
            self._evict_to_budget()

    # ---- data path -----------------------------------------------------

    def get(self, hash32: bytes) -> Optional[bytes]:
        """-> decoded payload or None. A probation hit promotes to
        protected (second touch = proven hot); a protected hit moves to
        MRU."""
        if self.max_bytes <= 0:
            return None
        with self._lock:
            data = self._prot.get(hash32)
            if data is not None:
                self._prot.move_to_end(hash32)
                self.hits += 1
                self.hit_bytes += len(data)
                self._hits_by_key[hash32] = \
                    self._hits_by_key.get(hash32, 0) + 1
                return data
            data = self._prob.pop(hash32, None)
            if data is not None:
                self._prob_bytes -= len(data)
                self._prot[hash32] = data
                self._prot_bytes += len(data)
                self._shed_protected()
                self.hits += 1
                self.hit_bytes += len(data)
                self._hits_by_key[hash32] = \
                    self._hits_by_key.get(hash32, 0) + 1
                return data
            self.misses += 1
            return None

    def contains(self, hash32: bytes) -> bool:
        """Presence peek that moves NO stats and NO LRU order — the
        prefetch planner's "already warm?" check must not promote an
        entry or inflate the hit counters the hint gossip reads."""
        if self.max_bytes <= 0:
            return False
        with self._lock:
            return hash32 in self._prot or hash32 in self._prob

    def top_keys(self, n: int) -> list[bytes]:
        """The n hottest cached hashes by hit count (hint gossip
        payload). Only actually-hot entries qualify — a key with no
        second touch is noise, not a hint."""
        import heapq

        with self._lock:
            return heapq.nlargest(n, self._hits_by_key,
                                  key=self._hits_by_key.get)

    def insert(self, hash32: bytes, data) -> None:
        """Admit into probation (read-miss fill and PUT write-through
        both land here; promotion is earned by a re-reference)."""
        if self.max_bytes <= 0:
            return
        if not isinstance(data, bytes):
            data = bytes(data)  # cached objects must be immutable
        n = len(data)
        if n > self._max_entry:
            self.rejected += 1
            return
        with self._lock:
            if hash32 in self._prot or hash32 in self._prob:
                return  # content-addressed: same hash = same bytes
            self._prob[hash32] = data
            self._prob_bytes += n
            self.inserts += 1
            self._evict_to_budget()

    def discard(self, hash32: bytes) -> None:
        """Explicit purge (delete_local / rc decref): a ghost of a
        deleted block must not pin RAM."""
        with self._lock:
            self._hits_by_key.pop(hash32, None)
            data = self._prob.pop(hash32, None)
            if data is not None:
                self._prob_bytes -= len(data)
                return
            data = self._prot.pop(hash32, None)
            if data is not None:
                self._prot_bytes -= len(data)

    def clear(self) -> None:
        with self._lock:
            self._prob.clear()
            self._prot.clear()
            self._hits_by_key.clear()
            self._prob_bytes = self._prot_bytes = 0

    # ---- internals (lock held) -----------------------------------------

    def _shed_protected(self) -> None:
        """Demote protected LRU entries to probation MRU until the
        protected segment fits its cap."""
        while self._prot_bytes > self._prot_cap and self._prot:
            h, data = self._prot.popitem(last=False)
            self._prot_bytes -= len(data)
            self._prob[h] = data
            self._prob_bytes += len(data)

    def _evict_to_budget(self) -> None:
        """Probation pays first; protected is only evicted when the
        budget itself shrank below the protected segment."""
        while self._prob_bytes + self._prot_bytes > self.max_bytes \
                and self._prob:
            h, data = self._prob.popitem(last=False)
            self._prob_bytes -= len(data)
            self._hits_by_key.pop(h, None)
            self.evictions += 1
        while self._prob_bytes + self._prot_bytes > self.max_bytes \
                and self._prot:
            h, data = self._prot.popitem(last=False)
            self._prot_bytes -= len(data)
            self._hits_by_key.pop(h, None)
            self.evictions += 1

    # ---- surface -------------------------------------------------------

    @property
    def bytes_used(self) -> int:
        return self._prob_bytes + self._prot_bytes

    @property
    def entries(self) -> int:
        return len(self._prob) + len(self._prot)

    def stats(self) -> dict:
        """Counter snapshot for /metrics (`cache_*`) and the tuning
        API."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "inserts": self.inserts,
            "rejected": self.rejected,
            "hit_bytes": self.hit_bytes,
            "bytes": self.bytes_used,
            "protected_bytes": self._prot_bytes,
            "entries": self.entries,
            "max_bytes": self.max_bytes,
        }
