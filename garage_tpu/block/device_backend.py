"""Staged device backends for the DeviceFeeder pipeline.

The feeder used to execute each device batch as one blocking
pack->transfer->compute->readback hop in a single thread, so transfer
never overlapped compute and the dispatcher idled until the hop
returned. This module is the staged replacement:

- `StageExecutor` / `DevicePipeline`: three dedicated daemon worker
  threads — h2d (host pack + host->device transfer), compute (kernel
  launch), d2h (readback + host-side finish). Each stage is a single
  thread, so stage N of batch B+1 runs WHILE stage N+1 of batch B runs:
  with the feeder's bounded in-flight depth that is classic
  double-buffering (batch N computes while N+1's bytes move h2d and
  N-1's results read back). Threads are daemon and generations are
  disposable: a hung tunnel call is ABANDONED (the feeder swaps in a
  fresh generation) instead of joined — a stuck non-daemon pool thread
  would wedge interpreter exit, the r3 rc=134 failure mode.

- `JaxDeviceBackend`: the real accelerator route, split into the three
  stages, with **fixed-shape padded launches**: item counts are padded
  up to a small set of bucket sizes (`[tpu] pad_buckets`) and RS shard
  lengths to the next power of two, so XLA compiles a handful of
  programs instead of one per distinct batch shape. Zero padding is
  safe for the RS ops because the code is linear (zero rows encode to
  zero parity — `_do_parity_check` already relies on this); hash pad
  rows are full-length zero messages whose digests are sliced away
  (BLAKE3's tree shape depends on the true chunk count, so the chunk
  axis is NOT bucketed — only the item axis is). Padding waste and
  recompile count are tracked in the feeder's stats
  (`feeder_pad_waste_bytes`, `feeder_recompiles`). When more than one
  device is visible, batches of at least `[tpu] mesh_min_items` items
  route through parallel/mesh.py's (dp, tp) data-plane mesh. The READ
  side (`decode` / `repair` ops, ISSUE 13) ships the erasure pattern as
  DATA: each stripe's decode/repair bit-matrix rides alongside the
  shard bytes into one batched matmul (rs.gf_apply_batched), so the
  launch-shape key — and with it the compile count — never depends on
  which shards survived.

- `StubDeviceBackend`: a deterministic device emulator (selected via
  `[tpu] device_backend = "stub"` or GARAGE_TPU_DEVICE_BACKEND=stub)
  that computes real results with the host kernels but sleeps a
  modelled transfer/compute/readback latency per stage, so pipeline
  overlap, the watchdog hang-fallback, and the `feeder_device_items`
  live gate are all CI-testable on a box with no accelerator.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

import numpy as np

log = logging.getLogger("garage_tpu.block.device_backend")

STAGES = ("h2d", "compute", "d2h")

# item-count bucket ladder for fixed-shape launches ([tpu] pad_buckets)
DEFAULT_PAD_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def bucket_items(n: int, buckets) -> int:
    """Smallest bucket >= n (n itself above the ladder)."""
    for b in buckets:
        if b >= n:
            return int(b)
    return n


def bucket_len(n: int, quantum: int = 1024) -> int:
    """Next power of two >= n (minimum `quantum`) — the shard-length
    bucket for RS launches. Lengths cluster at the block size anyway;
    power-of-two rounding keeps the tail shapes finite."""
    b = quantum
    while b < n:
        b <<= 1
    return b


def group_bytes(op: str, blobs: list) -> int:
    """Payload bytes of one op group (the feeder's accounting rule)."""
    if op in ("verify", "encode_put", "hash_md5"):
        # 2-tuples, except encode_put also carries ingest leases
        # (scheme byte + body in one pool buffer, sized total_len)
        return sum(b.total_len if hasattr(b, "total_len") else len(b[1])
                   for b in blobs)
    if op == "sha256":  # item = one message: a buffer or a span list
        from ..ops.sha256 import part_len

        return sum(part_len(b) for b in blobs)
    if op == "parity_check":  # item = one stripe (shard list)
        return sum(len(b) for s in blobs for b in s)
    if op == "decode":  # item = (present, shards, plain_len)
        return sum(len(b) for it in blobs for b in it[1])
    if op == "repair":  # item = (present, missing, shards)
        return sum(len(b) for it in blobs for b in it[2])
    return sum(len(b) for b in blobs
               if isinstance(b, (bytes, bytearray, memoryview)))


class StageJob:
    """One submitted stage execution. `claimed` flips True (worker
    thread, GIL-atomic) the instant the fn starts running — the feeder
    uses it to tell "queued, safely skippable" from "already executing,
    must be waited out" when a watchdog/abort cancels the future. A job
    cancelled BEFORE it is claimed is never executed at all: stage fns
    can carry side effects (the d2h MD5 lane advance), and running one
    after its batch already failed over to the host path would apply
    those effects twice. `busy` is the fn's exclusive execution time —
    what calibration records, NOT the pipeline wall (which includes
    queue wait behind sibling batches and would understate device
    throughput by up to the in-flight depth)."""

    __slots__ = ("loop", "fut", "fn", "claimed", "busy")

    def __init__(self, loop, fn):
        self.loop = loop
        self.fut = loop.create_future()
        self.fn = fn
        self.claimed = False
        self.busy = 0.0


class StageExecutor:
    """One daemon worker thread running one pipeline stage's jobs in
    submission order. Results are delivered to the submitting event
    loop via call_soon_threadsafe; a job whose future was cancelled
    before execution is skipped entirely, one cancelled mid-execution
    completes silently. Busy seconds accumulate into the shared
    per-stage dict — the numerator of the overlap-efficiency metric."""

    def __init__(self, name: str, busy: dict):
        self.name = name
        self._busy = busy
        self._jobs: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"feeder-{name}")
        self._thread.start()

    def submit(self, loop, fn) -> StageJob:
        job = StageJob(loop, fn)
        self._jobs.put(job)
        return job

    def _loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job.fut.cancelled():
                continue  # abandoned while queued: never execute
            job.claimed = True
            t0 = time.perf_counter()
            try:
                res, err = job.fn(), None
            except BaseException as e:
                res, err = None, e
            job.busy = time.perf_counter() - t0
            self._busy[self.name] += job.busy

            def deliver(fut=job.fut, res=res, err=err):
                if fut.cancelled():
                    return  # abandoned by the watchdog mid-execution
                if err is not None:
                    fut.set_exception(err)
                else:
                    fut.set_result(res)

            try:
                job.loop.call_soon_threadsafe(deliver)
            except RuntimeError:
                # loop already closed (feeder stopped mid-batch):
                # the caller's future is moot, nothing to deliver to
                pass


class DevicePipeline:
    """One GENERATION of the three stage executors plus its abort
    event. On a hang the feeder marks the generation dead and sets
    `aborted` so every sibling in-flight batch bails to the host path
    immediately instead of each waiting out its own full watchdog; the
    next device batch gets a fresh generation (fresh threads — the
    stuck ones are abandoned)."""

    def __init__(self, busy: dict):
        import asyncio

        self.dead = False
        self.aborted = asyncio.Event()
        self._execs = {s: StageExecutor(s, busy) for s in STAGES}

    def submit(self, stage: str, loop, fn) -> StageJob:
        return self._execs[stage].submit(loop, fn)


# ---------------------------------------------------------------------------
# JAX backend: padded fixed-shape staged launches (+ multi-chip mesh)
# ---------------------------------------------------------------------------


class JaxDeviceBackend:
    """The real accelerator route, split into h2d / compute / d2h so
    the pipeline can overlap them across batches. All three methods run
    in StageExecutor worker threads (never the event loop): jax import,
    device discovery and every XLA call stay off the loop and under the
    feeder watchdog."""

    name = "jax"

    def __init__(self, codec=None, pad_buckets=DEFAULT_PAD_BUCKETS,
                 mesh_min_items: int = 8, stats: dict | None = None):
        self.codec = codec
        self.pad_buckets = tuple(sorted(int(b) for b in pad_buckets)) \
            or DEFAULT_PAD_BUCKETS
        self.mesh_min_items = max(1, int(mesh_min_items))
        self.stats = stats if stats is not None else {
            "pad_waste_bytes": 0, "recompiles": 0, "mesh_batches": 0}
        self._shapes_seen: set = set()
        self._mesh = None
        self._mesh_tried = False

    # ---- shape accounting ------------------------------------------------

    def _note_shape(self, key: tuple, waste: int) -> None:
        if key not in self._shapes_seen:
            self._shapes_seen.add(key)
            self.stats["recompiles"] += 1
        self.stats["pad_waste_bytes"] += int(waste)

    def _get_mesh(self):
        """(dp, tp) mesh when >1 device is visible, else None. Resolved
        once, lazily, from a stage worker thread (jax.devices() on a
        tunnel can hang — the watchdog covers us here)."""
        if not self._mesh_tried:
            self._mesh_tried = True
            try:
                import jax

                if len(jax.devices()) > 1:
                    from ..parallel import mesh as pmesh

                    self._mesh = pmesh.data_plane_mesh()
                    log.info("feeder multi-chip mesh active: %s",
                             dict(self._mesh.shape))
            except Exception as e:
                log.info("multi-chip mesh unavailable, single-device "
                         "launches (%s: %s)", type(e).__name__, e)
        return self._mesh

    # ---- stage: host pack + pad + h2d -----------------------------------

    def stage(self, op: str, blobs: list):
        if op in ("hash", "verify", "hash_md5"):
            datas = blobs if op == "hash" else [d for _, d in blobs]
            return (op, blobs, self._stage_hash(datas))
        if op == "sha256":
            return (op, blobs, self._stage_sha256(blobs))
        if op in ("encode", "encode_put"):
            # encode_put items: (prefix, data) tuples, or ingest leases
            # whose stripe() already IS the split layout — those skip
            # the concatenate entirely
            blocks = (blobs if op == "encode" else
                      [b if hasattr(b, "stripe") else b[0] + b[1]
                       for b in blobs])
            return (op, blobs, self._stage_rs(blocks, "encode"))
        if op == "parity_check":
            return (op, blobs, self._stage_parity(blobs))
        if op in ("decode", "repair"):
            return (op, blobs, self._stage_gf(op, blobs))
        raise RuntimeError(f"unknown device op {op!r}")

    def _stage_hash(self, datas: list[bytes]):
        import jax

        from ..ops import treehash

        groups: dict[int, list[int]] = {}
        for i, d in enumerate(datas):
            groups.setdefault(treehash.n_chunks_for(len(d)), []).append(i)
        staged = []
        for c, idxs in groups.items():
            b = bucket_items(len(idxs), self.pad_buckets)
            padded = c * treehash.CHUNK_LEN
            buf = np.zeros((b, padded), dtype=np.uint8)
            # pad rows are full-length zero messages: the tree shape
            # (hence the compiled program) is per chunk count, so a
            # shorter pad length would be an invalid c-chunk message
            lengths = np.full(b, padded, dtype=np.int32)
            for row, i in enumerate(idxs):
                arr = np.frombuffer(datas[i], dtype=np.uint8)
                buf[row, : arr.size] = arr
                lengths[row] = arr.size
            waste = b * padded - sum(len(datas[i]) for i in idxs)
            self._note_shape(("hash", c, b), waste)
            staged.append((c, idxs, jax.device_put(buf),
                           jax.device_put(lengths)))
        return (len(datas), staged)

    def _stage_sha256(self, datas: list):
        import jax

        from ..ops import sha256 as sha

        groups: dict[int, list[int]] = {}
        for i, d in enumerate(datas):
            groups.setdefault(
                sha.blocks_bucket(sha.n_blocks_for(sha.part_len(d))),
                []).append(i)
        staged = []
        for npad, idxs in groups.items():
            b = bucket_items(len(idxs), self.pad_buckets)
            buf = np.zeros((b, npad * sha.BLOCK), dtype=np.uint8)
            # pad rows compress one zero block; the mask freezes the
            # rest and readback never reads them
            nbs = np.ones(b, dtype=np.int32)
            for row, i in enumerate(idxs):
                nbs[row] = sha.pad_row_into(buf[row], datas[i])
            waste = (b * npad * sha.BLOCK
                     - sum(sha.part_len(datas[i]) for i in idxs))
            self._note_shape(("sha256", npad, b), waste)
            staged.append((idxs, jax.device_put(buf),
                           jax.device_put(nbs), npad))
        return (len(datas), staged)

    def _stage_rs(self, blocks: list, tag: str):
        import jax

        from ..ops import rs

        k, m = self.codec.k, self.codec.m

        def blen(b):
            return b.total_len if hasattr(b, "total_len") else len(b)

        slens = [b.slen if hasattr(b, "slen") else rs.shard_len(len(b), k)
                 for b in blocks]
        smax = bucket_len(max(slens))
        bpad = bucket_items(len(blocks), self.pad_buckets)
        mesh = (self._get_mesh()
                if len(blocks) >= self.mesh_min_items else None)
        if mesh is not None:
            dp, tp = mesh.shape["dp"], mesh.shape["tp"]
            bpad = ((bpad + dp - 1) // dp) * dp
            smax = ((smax + tp - 1) // tp) * tp
        waste = bpad * k * smax - sum(blen(b) for b in blocks)
        self._note_shape((tag, k, m, bpad, smax, mesh is not None), waste)
        if mesh is None and blocks \
                and all(hasattr(b, "stripe") for b in blocks) \
                and len(set(slens)) == 1:
            # all-lease leg: the pool buffer IS the stripe layout, so
            # h2d reads it directly — no host-side re-pack copy. The
            # pad to (bpad, k, smax) happens on-device; batch=None
            # tells readback to slice the data shards straight from
            # the leases (host memory) instead of a staging array.
            import jax.numpy as jnp

            dev = jnp.stack([jax.device_put(b.stripe()) for b in blocks])
            if bpad > len(blocks) or smax > slens[0]:
                dev = jnp.pad(dev, ((0, bpad - len(blocks)), (0, 0),
                                    (0, smax - slens[0])))
            return (blocks, slens, None, dev, None, smax)
        batch = np.zeros((bpad, k, smax), dtype=np.uint8)
        copied = 0
        for i, b in enumerate(blocks):
            if hasattr(b, "stripe"):
                sh = b.stripe()
                copied += sh.size
            else:
                sh = rs.split_stripe(b, k)
            batch[i, :, : sh.shape[1]] = sh
        if copied and tag == "encode":
            # a lease fell off the zero-copy leg (mesh round-up or a
            # mixed-shape batch): the pad copy is real data-plane
            # bytes, so the wire->device copy audit must see it
            from ..utils.metrics import registry

            registry().inc("s3_put_copy_bytes", copied, path="stage_pack")
        if mesh is not None:
            from ..parallel import mesh as pmesh

            dev = jax.device_put(batch, pmesh.bytes_sharding(mesh))
        else:
            dev = jax.device_put(batch)
        return (blocks, slens, batch, dev, mesh, smax)

    def _stage_parity(self, stripes: list[list[bytes]]):
        import jax

        k, m = self.codec.k, self.codec.m
        smax = bucket_len(max(len(s[0]) for s in stripes))
        bpad = bucket_items(len(stripes), self.pad_buckets)
        mesh = (self._get_mesh()
                if len(stripes) >= self.mesh_min_items else None)
        if mesh is not None:
            dp, tp = mesh.shape["dp"], mesh.shape["tp"]
            bpad = ((bpad + dp - 1) // dp) * dp
            smax = ((smax + tp - 1) // tp) * tp
        arr = np.zeros((bpad, k + m, smax), dtype=np.uint8)
        for i, s in enumerate(stripes):
            for j, b in enumerate(s):
                arr[i, j, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        waste = bpad * (k + m) * smax - sum(
            len(b) for s in stripes for b in s)
        self._note_shape(("parity", k, m, bpad, smax, mesh is not None),
                         waste)
        if mesh is not None:
            from ..parallel import mesh as pmesh

            dev = jax.device_put(arr, pmesh.bytes_sharding(mesh))
        else:
            dev = jax.device_put(arr)
        return (len(stripes), dev, mesh, smax)

    def _stage_gf(self, op: str, items: list):
        """Pad + h2d for the pattern-as-data decode/repair launches.

        Items are grouped by OUTPUT ROW COUNT (decode always rebuilds
        k rows; repair rebuilds len(missing) — 1 for a resync shard
        rebuild, more for a multi-loss stripe), because one batched
        launch needs a uniform (B, 8k, 8·rows) matrix stack. Within a
        group the shard stacks pad up the usual bucket ladder and the
        per-item bit-matrices ride as DATA: the shape key deliberately
        EXCLUDES the erasure pattern, so feeder_recompiles stays flat
        across mixed present-sets — the whole point of the kernel."""
        import jax

        from ..ops import rs

        k, m = self.codec.k, self.codec.m
        shards_of = ((lambda it: it[1]) if op == "decode"
                     else (lambda it: it[2]))
        groups: dict[int, list[int]] = {}
        for i, it in enumerate(items):
            rows = k if op == "decode" else len(it[1])
            groups.setdefault(rows, []).append(i)
        staged = []
        for rows, idxs in groups.items():
            slens = [len(shards_of(items[i])[0]) for i in idxs]
            smax = bucket_len(max(slens))
            bpad = bucket_items(len(idxs), self.pad_buckets)
            mesh = (self._get_mesh()
                    if len(idxs) >= self.mesh_min_items else None)
            if mesh is not None:
                dp, tp = mesh.shape["dp"], mesh.shape["tp"]
                bpad = ((bpad + dp - 1) // dp) * dp
                smax = ((smax + tp - 1) // tp) * tp
            batch = np.zeros((bpad, k, smax), dtype=np.uint8)
            # pad rows keep zero matrices: zero maps to zero output
            # rows, sliced away at readback (the code is linear)
            mats = np.zeros((bpad, 8 * k, 8 * rows), dtype=np.int8)
            for row, i in enumerate(idxs):
                it = items[i]
                present = tuple(it[0])
                for j, s in enumerate(shards_of(it)):
                    batch[row, j, : len(s)] = np.frombuffer(s,
                                                            dtype=np.uint8)
                mats[row] = (rs.decode_bitmat_t(k, m, present)
                             if op == "decode"
                             else rs.repair_bitmat_t(k, m, present,
                                                     tuple(it[1])))
            waste = bpad * k * smax - sum(
                len(b) for i in idxs for b in shards_of(items[i]))
            self._note_shape((op, k, rows, bpad, smax, mesh is not None),
                             waste)
            if mesh is not None:
                from ..parallel import mesh as pmesh

                dev = jax.device_put(batch, pmesh.bytes_sharding(mesh))
                mdev = jax.device_put(mats, pmesh.mats_sharding(mesh))
            else:
                dev = jax.device_put(batch)
                mdev = jax.device_put(mats)
            staged.append((rows, idxs, slens, mdev, dev, mesh, smax))
        return staged

    # ---- compute: launch the kernels (async dispatch, no block) ---------

    def compute(self, op: str, staged):
        op, blobs, inner = staged
        if op in ("hash", "verify", "hash_md5"):
            from ..ops import treehash

            n, groups = inner
            launched = [(c, idxs, treehash.hash_fn(c)(buf, lens))
                        for c, idxs, buf, lens in groups]
            return (op, blobs, (n, launched))
        if op == "sha256":
            from ..ops import sha256 as sha

            n, groups = inner
            launched = [(idxs, sha.hash_fn(npad)(buf, nbs))
                        for idxs, buf, nbs, npad in groups]
            return (op, blobs, (n, launched))
        if op in ("encode", "encode_put"):
            from ..ops import rs

            blocks, slens, batch, dev, mesh, smax = inner
            k, m = self.codec.k, self.codec.m
            if mesh is not None:
                from ..parallel import mesh as pmesh

                parity = pmesh.make_encode_step(mesh, k, m, smax)(dev)
                self.stats["mesh_batches"] += 1
            else:
                parity = rs.encode(k, m, dev)
            return (op, blobs, (blocks, slens, batch, parity))
        if op == "parity_check":
            from ..ops import rs

            n, dev, mesh, smax = inner
            k, m = self.codec.k, self.codec.m
            if mesh is not None:
                from ..parallel import mesh as pmesh

                ok = pmesh.make_parity_check_step(mesh, k, m, smax)(dev)
                self.stats["mesh_batches"] += 1
            else:
                ok = rs.parity_check(k, m, dev)
            return (op, blobs, (n, ok))
        if op in ("decode", "repair"):
            from ..ops import rs

            k = self.codec.k
            launched = []
            for rows, idxs, slens, mats, dev, mesh, smax in inner:
                if mesh is not None:
                    from ..parallel import mesh as pmesh

                    out = pmesh.make_gf_apply_step(mesh, k, rows,
                                                   smax)(mats, dev)
                    self.stats["mesh_batches"] += 1
                else:
                    out = rs.gf_apply_batched(mats, dev)
                launched.append((idxs, slens, out))
            return (op, blobs, launched)
        raise RuntimeError(f"unknown device op {op!r}")

    # ---- readback: d2h + host-side finish -------------------------------

    def readback(self, op: str, handle) -> list:
        op, blobs, inner = handle
        if op in ("hash", "verify", "hash_md5"):
            n, launched = inner
            digests: list = [None] * n
            for c, idxs, cvs in launched:
                # u32 cvs -> 32 little-endian digest bytes, same
                # conversion as treehash.hash_batch_jax
                arr = np.ascontiguousarray(
                    np.asarray(cvs).astype("<u4")).view(np.uint8)
                arr = arr.reshape(arr.shape[0], 32)
                for row, i in enumerate(idxs):
                    digests[i] = arr[row].tobytes()
            if op == "verify":
                from .feeder import _verify_matches

                return _verify_matches(digests, blobs)
            if op == "hash_md5":
                # hash results are safely back on the host FIRST: a
                # device failure raises before this point, so the host
                # retry re-runs with MD5 state untouched (no
                # double-counted ETag bytes). Only then batch-advance
                # the serial MD5 chains host-side.
                from .. import native

                native.md5_update_many(list(blobs))
            return digests
        if op == "sha256":
            from ..ops import sha256 as sha

            n, launched = inner
            out: list = [None] * n
            for idxs, cvs in launched:
                for i, hx in zip(idxs, sha.digests_to_hex(cvs)):
                    out[i] = hx
            return out
        if op in ("encode", "encode_put"):
            blocks, slens, batch, parity = inner
            k, m = self.codec.k, self.codec.m
            par = np.asarray(parity)
            out = []
            for i in range(len(blocks)):
                sl = slens[i]
                # batch=None: all-lease leg — the data shards live in
                # the lease buffers (still held by the PUT tasks, which
                # await this op before releasing), no staging array
                src = blocks[i].stripe() if batch is None else batch[i]
                out.append([bytes(src[j, :sl]) for j in range(k)]
                           + [bytes(par[i, j, :sl]) for j in range(m)])
            if op == "encode_put":
                from .manager import pack_shard

                return [[pack_shard(pp, b.total_len
                                    if hasattr(b, "total_len")
                                    else len(b[0]) + len(b[1]))
                         for pp in parts]
                        for b, parts in zip(blobs, out)]
            return out
        if op == "parity_check":
            n, ok = inner
            arr = np.asarray(ok)
            return [bool(v) for v in arr[:n]]
        if op in ("decode", "repair"):
            from ..ops import rs

            results: list = [None] * len(blobs)
            for idxs, slens, out in inner:
                arr = np.asarray(out)
                for row, i in enumerate(idxs):
                    sl = slens[row]
                    if op == "decode":
                        # (present, shards, plain_len) -> packed bytes
                        results[i] = rs.join_stripe(arr[row, :, :sl],
                                                    blobs[i][2])
                    else:
                        # (present, missing, shards) -> {idx: payload}
                        results[i] = {
                            mi: bytes(arr[row, j, :sl])
                            for j, mi in enumerate(tuple(blobs[i][1]))}
            return results
        raise RuntimeError(f"unknown device op {op!r}")


# ---------------------------------------------------------------------------
# Stub backend: deterministic latency emulation over the host kernels
# ---------------------------------------------------------------------------


class StubDeviceBackend:
    """Emulated device: real results (host kernels), modelled latency.

    Each stage sleeps `fixed_s + bytes / (rate_gbps * 1e9)` with the
    op's payload bytes (d2h uses the result-size estimate), so overlap
    and watchdog behavior are measurable and DETERMINISTIC — no
    randomness anywhere. Rates come from the constructor or the
    GARAGE_TPU_STUB_GBPS env var ("h2d,compute,d2h").

    Test hook: setting `hang_stage` to one of ("h2d", "compute",
    "d2h") makes the next entry into that stage block forever —
    the injected mid-pipeline device hang the watchdog tests use.
    """

    name = "stub"

    def __init__(self, feeder=None, h2d_gbps: float = 1.0,
                 compute_gbps: float = 8.0, d2h_gbps: float = 1.0,
                 fixed_s: float = 0.0):
        env = os.environ.get("GARAGE_TPU_STUB_GBPS")
        if env:
            try:
                parts = [float(x) for x in env.split(",")]
                # pad a short list with the remaining POSITIONAL
                # defaults ("1,2" keeps d2h's default, not compute's)
                defaults = [h2d_gbps, compute_gbps, d2h_gbps]
                h2d_gbps, compute_gbps, d2h_gbps = (
                    parts + defaults[len(parts):])[:3]
            except ValueError:
                log.warning("bad GARAGE_TPU_STUB_GBPS %r; using defaults",
                            env)
        self.feeder = feeder
        self.rates = {"h2d": h2d_gbps, "compute": compute_gbps,
                      "d2h": d2h_gbps}
        self.fixed_s = float(fixed_s)
        self.hang_stage: str | None = None

    def _maybe_hang(self, stage: str) -> None:
        if self.hang_stage == stage:
            self.hang_stage = None  # one hang; siblings abort via event
            log.warning("stub backend: injected hang in %s stage", stage)
            threading.Event().wait()  # daemon thread, abandoned forever

    def _sleep(self, stage: str, nbytes: int) -> None:
        time.sleep(self.fixed_s + nbytes / (self.rates[stage] * 1e9))

    def stage(self, op: str, blobs: list):
        self._maybe_hang("h2d")
        nbytes = group_bytes(op, blobs)
        self._sleep("h2d", nbytes)
        return (op, blobs, nbytes)

    def compute(self, op: str, staged):
        self._maybe_hang("compute")
        op, blobs, nbytes = staged
        self._sleep("compute", nbytes)
        f = self.feeder
        if op in ("hash", "verify", "hash_md5"):
            datas = blobs if op == "hash" else [d for _, d in blobs]
            res = f._do_hash(list(datas), "host")
        elif op == "sha256":
            res = f._do_sha256(list(blobs), "host")
        elif op == "encode":
            res = f._do_encode(list(blobs), "host")
        elif op == "encode_put":
            res = f._do_encode_put(list(blobs), "host")
        elif op == "parity_check":
            res = f._do_parity_check(list(blobs), "host")
        elif op == "decode":
            res = f._do_decode(list(blobs), "host")
        elif op == "repair":
            res = f._do_repair(list(blobs), "host")
        else:
            raise RuntimeError(f"unknown device op {op!r}")
        return (op, blobs, res)

    def readback(self, op: str, handle) -> list:
        self._maybe_hang("d2h")
        op, blobs, res = handle
        if op in ("hash", "verify", "hash_md5", "sha256"):
            out_bytes = 32 * len(res)
        elif op in ("encode", "encode_put"):
            out_bytes = sum(len(b) for parts in res for b in parts)
        elif op == "decode":
            out_bytes = sum(len(b) for b in res)
        elif op == "repair":
            out_bytes = sum(len(b) for d in res for b in d.values())
        else:
            out_bytes = len(res)
        self._sleep("d2h", out_bytes)
        if op == "verify":
            from .feeder import _verify_matches

            return _verify_matches(res, blobs)
        if op == "hash_md5":
            from .. import native

            native.md5_update_many(list(blobs))
        return res
