"""Cluster-wide read cache tier (ISSUE 15): turn N node-local decoded-
block caches into ONE cluster cache.

PR 3 gave every node a decoded-block cache and PR 8 sharded it across
gateway workers — but both stop at the process/node boundary, so N
cluster nodes still pay N cold erasure decodes (k-shard gather +
GF(2^8) matmul + verify) for the same hot block and hold N duplicate
copies. This module is the cross-NODE lane:

  * **Owner routing** — every cacheable block hash has one OWNER node,
    chosen by rendezvous hashing (gateway/ring.py's weight function,
    shared so the worker and cluster layers can never disagree) over
    the roster, FILTERED through the shared PeerHealthTracker: a node
    whose circuit breaker is open drops out of the ring, so a degraded
    owner remaps its share to the next-highest weight instead of
    blackholing reads (Karger et al., "Web Caching with Consistent
    Hashing"). The roster is PER ZONE (ISSUE 16): a storage node's
    ring is the current layout's storage nodes IN ITS OWN ZONE, so a
    hot-block probe is an intra-zone hop, never a cross-WAN one, and a
    cold zone warms from its own decode instead of a cross-zone shm
    miss. Each zone therefore holds one decoded copy of its hot set —
    deliberate: a WAN round-trip costs more than the decode it would
    save, and a zone partition must not sever the cache lane. A node
    with NO zone (gateway worker, zoneless test rig) falls back to the
    global ring, which is also the pre-zone behavior when every node
    shares one zone.
  * **Single-hop probe** — a non-owner read first issues
    `rpc_cache_probe` to the owner: a read-only, hedge-safe op that
    answers from the owner's RAM cache and NEVER touches the store
    (one hop by construction, no probe chains). A hit returns the
    decoded payload — zero shard gathers and zero decodes anywhere in
    the cluster — verified against the content address before it is
    served, the same end-to-end integrity rule as every other remote
    read. A miss (or an unreachable owner) falls back to today's local
    path, and the decoded result is then write-through-inserted AT THE
    OWNER (`rpc_cache_insert`, background, bounded in flight) so the
    next reader cluster-wide wins. Non-owners do not fill their local
    cache — one decoded copy per cluster, not per node.
  * **Hot-hash hints** — each node's top-N cache keys by hit count
    (BlockCache.top_keys) piggyback on the existing peering pings
    (net/peering.py hint hooks; ~32 B per hash, bounded both ways).
    Hints are INTRA-ZONE like the ring (ISSUE 16): a hint arriving
    from a peer in another zone is dropped on receipt, so is_hot()
    reflects this ZONE's hot set and a background probe gated on it
    never targets a cross-WAN owner.
    The hint set tells BACKGROUND readers which blocks are worth a
    probe: resync's replicate fetches route through the tier only for
    hinted-hot hashes, so a rebalance enumeration of a million cold
    blocks never sprays a million wasted probe RPCs (the
    lease/hint-based hot-set placement shape of Nishtala et al.,
    NSDI'13).

What deliberately does NOT route through the tier: SSE-C payloads
(`cacheable=False` skips lookup, probe and insert end to end — the
GL03 taint rule audits the `cache_tier_probe`/`cache_tier_insert`
seam); erasure SHARD rebuilds (the tier holds decoded plaintext, and
re-deriving exact stripe bytes would require byte-deterministic
recompression — a rebuilt shard must match its stripe-mates exactly);
and scrub (its whole job is to touch the disks the cache exists to
avoid).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Optional

from ..gateway.ring import rendezvous_owner
from ..net.message import PRIO_BACKGROUND, PRIO_NORMAL
from ..utils.metrics import registry

log = logging.getLogger("garage_tpu.block.cache_tier")

# hints remembered per node (hash -> last-seen monotonic time); beyond
# this the oldest hint is dropped — an attacker-spun key space must not
# grow this map without bound
HINT_MAX = 1024
HINT_TTL_S = 120.0
# hashes carried per ping (outbound) and accepted per ping (inbound)
HINT_TOP_N = 16
HINT_ACCEPT_MAX = 64
# a probe is a RAM lookup plus one payload transfer; the flat budget
# is deliberately TIGHT so a blackholed owner (no RST, packets
# dropped) costs foreground GETs seconds — not tens of seconds — for
# the handful of failures it takes to open its breaker and drop it
# out of the ring. The rpc helper's adaptive per-peer timeout
# (clamp(p99*4), floor 1 s) tightens under this once samples exist; a
# legitimately slow transfer that gets cut off just falls back to the
# local decode path, which is the safe direction.
PROBE_TIMEOUT_S = 2.0
# concurrent background owner-insert pushes; beyond this the push is
# skipped (the next reader warms the owner instead) — a decode burst
# must not turn into an unbounded RPC fan-out of MiB-scale payloads
INSERT_INFLIGHT_MAX = 8


class ClusterCacheTier:
    """Router + hint book installed on BlockManager (`manager.cache_tier`)
    when `[block] cache_tier` is on and the node has a cluster system."""

    def __init__(self, manager, hint_top_n: int = HINT_TOP_N):
        self.manager = manager
        self.enabled = True
        self.hint_top_n = int(hint_top_n)
        # hash -> last-seen time, LRU-ordered (move_to_end on refresh)
        self._hints: "OrderedDict[bytes, float]" = OrderedDict()
        self._insert_inflight = 0
        self.probes = 0
        self.probe_hits = 0
        self.probe_misses = 0
        self.probe_fails = 0
        self.probe_corrupt = 0
        self.remote_hit_bytes = 0
        self.inserts_pushed = 0
        self.insert_skips = 0
        self.hints_sent = 0
        self.hints_seen = 0
        self.cross_zone_probes = 0
        self.hints_dropped_cross_zone = 0

    # ---- ring -----------------------------------------------------------

    def _health(self):
        return self.manager.rpc.health()

    def _zone_of(self, node: bytes) -> Optional[str]:
        role = self.manager.system.layout_helper.current().node_role(node)
        if role is None or not role.zone:
            return None
        return role.zone

    def members(self) -> list[bytes]:
        """Live ring membership: the current layout's storage nodes IN
        THIS NODE'S ZONE (the whole cluster when this node has no zone
        — gateway worker, zoneless rig; with every node in one zone the
        two are the same roster), minus open-breaker peers (a degraded
        owner drops OUT of the ring — its share remaps — instead of
        blackholing probes). Breaker state is a local observation, so
        two nodes can briefly disagree on ownership while a breaker is
        open; the tier is a cache, so the cost is a duplicate fill,
        never a wrong answer. Zone membership comes from the shared
        layout, so all nodes of a zone DO agree on the zone roster."""
        system = self.manager.system
        me = system.id
        nodes = sorted(
            system.layout_helper.current().storage_nodes())
        my_zone = self._zone_of(me)
        if my_zone is not None:
            # per-zone ring (ISSUE 16): hot-block probes stay
            # intra-zone; a zoneless node in the roster is unreachable
            # as "same zone" and drops out too
            nodes = [n for n in nodes
                     if n == me or self._zone_of(n) == my_zone]
        health = self._health()
        if health is None:
            return nodes
        now = time.monotonic()
        return [n for n in nodes
                if n == me or health.breaker_state(n, now) != "open"]

    def owner_of(self, hash32: bytes) -> Optional[bytes]:
        """Remote owner to probe, or None when this node should serve
        locally (it owns the hash, routing is moot, or the tier is
        off). A node OUTSIDE the roster (gateway worker, draining node)
        still probes owners — it just never owns anything itself."""
        if not self.enabled or self.manager.cache.max_bytes <= 0:
            return None
        members = self.members()
        me = self.manager.system.id
        if not members or (len(members) == 1 and members[0] == me):
            return None
        owner = rendezvous_owner(members, hash32)
        if owner is None or owner == me:
            return None
        return owner

    def local_owner(self, hash32: bytes) -> bool:
        """True when a real multi-node ring elects THIS node the
        hash's cache owner — the gateway-worker shortcut's test: a
        local GET on the owner can serve straight from its own cache
        ring slot instead of paying a loopback router hop. Distinct
        from owns(): moot routing (tier off, lone member) is False
        here — the shortcut only fires when the ring genuinely routed
        the hash home."""
        if not self.enabled or self.manager.cache.max_bytes <= 0:
            return False
        members = self.members()
        if len(members) < 2:
            return False
        return rendezvous_owner(members, hash32) == self.manager.system.id

    def owns(self, hash32: bytes) -> bool:
        """Whether this node should hold the cached copy (True when
        routing is moot — an unrouted cache owns everything it sees)."""
        if not self.enabled or self.manager.cache.max_bytes <= 0:
            return True
        members = self.members()
        if len(members) < 2:
            return True
        owner = rendezvous_owner(members, hash32)
        return owner is None or owner == self.manager.system.id

    # ---- probe / insert (the cross-node seam) ---------------------------

    async def probe(self, owner: bytes, hash32: bytes,
                    cacheable: bool = True) -> Optional[bytes]:
        """Single-hop read-only probe of the owner's cache; -> decoded
        payload (content-verified) or None (miss / owner unreachable /
        failed verification). Never raises: a tier failure must degrade
        to the local path, not fail the read. `cacheable` is the same
        GL03 audit flag as the rpc_get_block seam — SSE-C state must
        pass cacheable=False, which makes the probe a no-op (an SSE-C
        hash is never even ASKED about across nodes)."""
        if not cacheable:
            return None
        self.probes += 1
        m = self.manager
        my_zone = self._zone_of(m.system.id)
        if my_zone is not None and self._zone_of(owner) != my_zone:
            # the per-zone ring makes this structurally unreachable for
            # storage nodes; the counter is the drill's assertion that
            # it STAYS that way (a regression here turns every hot read
            # into a WAN round-trip)
            self.cross_zone_probes += 1
            registry().inc("cache_tier_cross_zone_probe")
        try:
            resp = await m.rpc.call(
                m.endpoint, owner,
                {"op": "cache_probe", "hash": hash32},
                PRIO_NORMAL, timeout=PROBE_TIMEOUT_S)
            data = resp.get("data") if isinstance(resp, dict) else None
        except Exception as e:
            self.probe_fails += 1
            registry().inc("cache_tier_probe_fail")
            log.debug("cache probe of %s at %s failed: %s",
                      hash32[:4].hex(), owner[:4].hex(), e)
            return None
        if data is None:
            self.probe_misses += 1
            registry().inc("cache_tier_probe_miss")
            return None
        # end-to-end integrity: a remote payload is served only after
        # it re-derives the content address (the store read paths all
        # verify remote bytes; the tier must not be the one lane that
        # trusts the wire). content_hash_matches tolerates the legacy
        # algo exactly like DataBlock.verify; off-loop — MiB-scale
        # hashing must not stall sibling requests.
        from ..utils.data import content_hash_matches

        if not await asyncio.to_thread(content_hash_matches, data,
                                       hash32):
            self.probe_corrupt += 1
            registry().inc("cache_tier_probe_corrupt")
            log.warning("cache probe of %s at %s returned corrupt "
                        "payload; falling back to the store",
                        hash32[:4].hex(), owner[:4].hex())
            return None
        self.probe_hits += 1
        self.remote_hit_bytes += len(data)
        registry().inc("cache_tier_probe_hit")
        registry().inc("cache_tier_remote_hit_bytes", len(data))
        return data

    def insert_at(self, owner: bytes, hash32: bytes, data) -> None:
        """Write-through at the owner after a local miss-decode: fire a
        bounded background push so the NEXT reader — on any node —
        probe-hits instead of re-decoding. Never blocks the caller."""
        if self._insert_inflight >= INSERT_INFLIGHT_MAX:
            self.insert_skips += 1
            return
        self._insert_inflight += 1
        from ..utils.background import spawn

        spawn(self._push_insert(owner, hash32, data),
              "cache-tier-insert")

    async def _push_insert(self, owner: bytes, hash32: bytes,
                           data) -> None:
        # background lane: a MiB-scale push over a slow link may
        # legitimately outlive the tight foreground probe budget
        m = self.manager
        try:
            await m.endpoint.call(
                owner, {"op": "cache_insert", "hash": hash32,
                        "data": data},
                PRIO_BACKGROUND, timeout=15.0)
            self.inserts_pushed += 1
            registry().inc("cache_tier_insert_push")
        except Exception as e:
            log.debug("cache insert push of %s to %s failed: %s",
                      hash32[:4].hex(), owner[:4].hex(), e)
        finally:
            self._insert_inflight -= 1

    # ---- hot-hash hints (peering ping piggyback) ------------------------

    def hot_hashes(self) -> list[bytes]:
        """Outbound hint payload: this node's hottest cached hashes."""
        out = self.manager.cache.top_keys(self.hint_top_n) \
            if self.enabled else []
        self.hints_sent += len(out)
        return out

    def note_hints(self, from_node: bytes, hashes) -> None:
        """Inbound hints from a peer's ping. Bounded both ways: at most
        HINT_ACCEPT_MAX per ping, at most HINT_MAX remembered. Filtered
        to THIS zone on receipt (the outbound ping payload is shared by
        all peers, so the receive side is where intra-zone hint gossip
        is enforced): another zone's hot set must not make is_hot()
        send our background reads probing across the WAN."""
        my_zone = self._zone_of(self.manager.system.id)
        if my_zone is not None and self._zone_of(from_node) != my_zone:
            self.hints_dropped_cross_zone += 1
            registry().inc("cache_tier_hint_drop_cross_zone")
            return
        now = time.monotonic()
        for h in list(hashes)[:HINT_ACCEPT_MAX]:
            if not isinstance(h, bytes) or len(h) != 32:
                continue
            self._hints[h] = now
            self._hints.move_to_end(h)
            self.hints_seen += 1
        while len(self._hints) > HINT_MAX:
            self._hints.popitem(last=False)

    def is_hot(self, hash32: bytes) -> bool:
        """Whether any peer recently advertised hash32 as hot — the
        gate background reads (resync fetches) use before spending a
        probe RPC on a block that is overwhelmingly likely cold."""
        t = self._hints.get(hash32)
        if t is None:
            return False
        if time.monotonic() - t > HINT_TTL_S:
            del self._hints[hash32]
            return False
        return True

    # ---- surface --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "zone": self._zone_of(self.manager.system.id),
            "members": len(self.members()),
            "cross_zone_probes": self.cross_zone_probes,
            "hints_dropped_cross_zone": self.hints_dropped_cross_zone,
            "hints_known": len(self._hints),
            "hint_top_n": self.hint_top_n,
            "probes": self.probes,
            "probe_hits": self.probe_hits,
            "probe_misses": self.probe_misses,
            "probe_fails": self.probe_fails,
            "probe_corrupt": self.probe_corrupt,
            "remote_hit_bytes": self.remote_hit_bytes,
            "inserts_pushed": self.inserts_pushed,
            "insert_skips": self.insert_skips,
            "hints_seen": self.hints_seen,
        }
