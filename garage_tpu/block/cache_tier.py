"""Cluster-wide read cache tier (ISSUE 15): turn N node-local decoded-
block caches into ONE cluster cache.

PR 3 gave every node a decoded-block cache and PR 8 sharded it across
gateway workers — but both stop at the process/node boundary, so N
cluster nodes still pay N cold erasure decodes (k-shard gather +
GF(2^8) matmul + verify) for the same hot block and hold N duplicate
copies. This module is the cross-NODE lane:

  * **Owner routing** — every cacheable block hash has one OWNER node,
    chosen by rendezvous hashing (gateway/ring.py's weight function,
    shared so the worker and cluster layers can never disagree) over
    the roster, FILTERED through the shared PeerHealthTracker: a node
    whose circuit breaker is open drops out of the ring, so a degraded
    owner remaps its share to the next-highest weight instead of
    blackholing reads (Karger et al., "Web Caching with Consistent
    Hashing"). The roster is PER ZONE (ISSUE 16): a storage node's
    ring is the current layout's storage nodes IN ITS OWN ZONE, so a
    hot-block probe is an intra-zone hop, never a cross-WAN one, and a
    cold zone warms from its own decode instead of a cross-zone shm
    miss. Each zone therefore holds one decoded copy of its hot set —
    deliberate: a WAN round-trip costs more than the decode it would
    save, and a zone partition must not sever the cache lane. A node
    with NO zone (gateway worker, zoneless test rig) falls back to the
    global ring, which is also the pre-zone behavior when every node
    shares one zone.
  * **Single-hop probe** — a non-owner read first issues
    `rpc_cache_probe` to the owner: a read-only, hedge-safe op that
    answers from the owner's RAM cache and NEVER touches the store
    (one hop by construction, no probe chains). A hit returns the
    decoded payload — zero shard gathers and zero decodes anywhere in
    the cluster — verified against the content address before it is
    served, the same end-to-end integrity rule as every other remote
    read. A miss (or an unreachable owner) falls back to today's local
    path, and the decoded result is then write-through-inserted AT THE
    OWNER (`rpc_cache_insert`, background, bounded in flight) so the
    next reader cluster-wide wins. Non-owners do not fill their local
    cache — one decoded copy per cluster, not per node.
  * **Hot-hash hints** — each node's top-N cache keys by hit count
    (BlockCache.top_keys) piggyback on the existing peering pings
    (net/peering.py hint hooks; ~32 B per hash, bounded both ways).
    Hints are INTRA-ZONE like the ring (ISSUE 16): a hint arriving
    from a peer in another zone is dropped on receipt, so is_hot()
    reflects this ZONE's hot set and a background probe gated on it
    never targets a cross-WAN owner.
    The hint set tells BACKGROUND readers which blocks are worth a
    probe: resync's replicate fetches route through the tier only for
    hinted-hot hashes, so a rebalance enumeration of a million cold
    blocks never sprays a million wasted probe RPCs (the
    lease/hint-based hot-set placement shape of Nishtala et al.,
    NSDI'13).

ISSUE 18 adds the COLD-herd machinery on top of the hot-path routing:

  * **Probe singleflight leases** — a probe that misses at the owner
    MINTS a lease (ProbeLeaseTable): the first prober becomes the
    holder, decodes via its store path and write-through-inserts;
    every concurrent prober — remote via the `cache_probe` wait-mode,
    local via the owner's own lease check in rpc_get_block — parks on
    the owner for a bounded wait (`[block] cache_lease_wait_ms`) and
    is woken by the insert's arrival. The wait is budgeted INSIDE the
    probe's flat PROBE_TIMEOUT_S, never stacked on top, so a dead or
    blackholed lease holder can never push a GET past the pre-lease
    worst case: waiters time out, fall back to the store path, and
    the expired lease is reaped. This is the memcache lease shape of
    Nishtala et al. (NSDI'13) on the rendezvous ring — a cold flash
    crowd pays O(blocks) decodes cluster-wide, not O(blocks x nodes).
  * **Hint-driven prefetch** — the owner ACTS on an inbound hint for a
    block it doesn't hold: a bounded background queue decodes it ahead
    of the herd (<= `[block] cache_prefetch_inflight` concurrent, one
    governor-paced sleep per fetch), converting the first herd into a
    warm probe hit. Hints are zone-filtered BEFORE the prefetch
    trigger sees them, and the fetch itself is the owner's local store
    path — prefetch can neither be triggered by nor fetch across a
    zone boundary.
  * **Packed-bytes segment** — a second byte-budgeted cache
    (`manager.packed_cache`, keyed (hash, kind=packed)) holding the
    EXACT on-disk packed bytes an erasure decode reassembles. That
    dissolves the old byte-deterministic-recompression restriction:
    shard rebuilds (`resync._rebuild_shard`) and scrub stripe repairs
    re-encode straight from cached packed bytes — zero shard-gather
    RPCs on a warm rebuild — and degraded GETs serve from it before
    gathering. Probes carry a `kinds` list so one RPC checks both
    segments; the packed segment rides the same zone ring.

What deliberately does NOT route through the tier: SSE-C payloads
(`cacheable=False` skips lookup, probe, lease and insert end to end —
the GL03 taint rule audits the `cache_tier_probe`/`cache_tier_insert`
seam, `probe_full` included); and scrub's VERIFY passes (their whole
job is to touch the disks the cache exists to avoid — only the repair
leg, which needs ground-truth packed bytes, rides the tier).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Optional

from ..gateway.ring import rendezvous_owner
from ..net.message import PRIO_BACKGROUND, PRIO_NORMAL
from ..utils.metrics import registry

log = logging.getLogger("garage_tpu.block.cache_tier")

# hints remembered per node (hash -> last-seen monotonic time); beyond
# this the oldest hint is dropped — an attacker-spun key space must not
# grow this map without bound
HINT_MAX = 1024
HINT_TTL_S = 120.0
# hashes carried per ping (outbound) and accepted per ping (inbound)
HINT_TOP_N = 16
HINT_ACCEPT_MAX = 64
# a probe is a RAM lookup plus one payload transfer; the flat budget
# is deliberately TIGHT so a blackholed owner (no RST, packets
# dropped) costs foreground GETs seconds — not tens of seconds — for
# the handful of failures it takes to open its breaker and drop it
# out of the ring. The rpc helper's adaptive per-peer timeout
# (clamp(p99*4), floor 1 s) tightens under this once samples exist; a
# legitimately slow transfer that gets cut off just falls back to the
# local decode path, which is the safe direction.
PROBE_TIMEOUT_S = 2.0
# concurrent background owner-insert pushes; beyond this the push is
# skipped (the next reader warms the owner instead) — a decode burst
# must not turn into an unbounded RPC fan-out of MiB-scale payloads
INSERT_INFLIGHT_MAX = 8
# lease wait default (`[block] cache_lease_wait_ms`): ≈ the observed
# p95 of a 1 MiB erasure gather+decode on the loopback bench — long
# enough that the holder's insert usually lands, short enough that a
# dead holder costs less than the decode the wait tried to save
LEASE_WAIT_MS_DEFAULT = 250.0
# a lease the holder never resolves expires after this multiple of the
# wait bound: waiters have all timed out by then, and the NEXT prober
# must be able to mint a fresh lease instead of parking forever behind
# a corpse
LEASE_TTL_FACTOR = 4.0
# leases outstanding per owner; beyond this a miss answers plainly (no
# lease, no wait) — an attacker-spun key space must not grow the table
LEASE_MAX = 512
# the lease wait must fit INSIDE the probe's flat timeout with room
# for the transfer of the woken payload — a wait that consumed the
# whole RPC budget would turn every wake into a caller-side timeout,
# stacking the wait on top of the budget instead of inside it
PROBE_WAIT_MARGIN_S = 0.5
# hint-driven prefetch: queue bound and per-fetch governor pacing cap
PREFETCH_QUEUE_MAX = 64
PREFETCH_INFLIGHT_DEFAULT = 2


class _Lease:
    __slots__ = ("holder", "deadline", "event")

    def __init__(self, holder: bytes, deadline: float):
        self.holder = holder
        self.deadline = deadline
        self.event = asyncio.Event()


class ProbeLeaseTable:
    """Owner-side singleflight ledger: one live lease per missing hash.

    The first prober to miss mints (becoming the holder); concurrent
    probers park on the lease's event with a bounded wait and re-check
    the cache on wake. The holder's write-through insert resolves the
    lease; a holder that dies (SIGKILL, cancel, partition) simply never
    resolves, waiters time out within their own budget, and the lease
    is reaped at its TTL so the next prober re-mints.

    Conservation invariant (GARAGE_SANITIZE=1, checked at every loop
    teardown): no waiter stays parked once the handlers that parked it
    completed, and every minted lease is accounted resolved, expired,
    or still live — a leak here means probers parking forever behind a
    lease nobody can resolve."""

    def __init__(self, wait_ms: float = LEASE_WAIT_MS_DEFAULT):
        self.wait_ms = float(wait_ms)
        self._leases: dict[bytes, _Lease] = {}
        self._waiters = 0
        self.minted = 0
        self.resolved = 0
        self.expired = 0
        self.waits = 0
        self.wait_hits = 0
        self.wait_timeouts = 0
        from ..utils import sanitizer

        sanitizer.track_conservation(self)  # no-op unless armed

    @property
    def depth(self) -> int:
        return len(self._leases)

    @property
    def ttl_s(self) -> float:
        return max(0.05, self.wait_ms / 1000.0 * LEASE_TTL_FACTOR)

    def _reap(self, now: float) -> None:
        for h in [h for h, ls in self._leases.items()
                  if ls.deadline <= now]:
            ls = self._leases.pop(h)
            ls.event.set()  # wake anyone parked behind the corpse
            self.expired += 1
            registry().inc("cache_lease_expired")

    def live(self, hash32: bytes) -> bool:
        self._reap(time.monotonic())
        return hash32 in self._leases

    def mint(self, hash32: bytes, holder: bytes) -> bool:
        """True when the caller became the lease holder (no live lease
        existed and the table had room). Synchronous — no await between
        the live check and the insert, so concurrent probe handlers on
        one loop elect exactly one holder."""
        now = time.monotonic()
        self._reap(now)
        if hash32 in self._leases or len(self._leases) >= LEASE_MAX \
                or self.wait_ms <= 0:
            return False
        self._leases[hash32] = _Lease(holder, now + self.ttl_s)
        self.minted += 1
        registry().inc("cache_lease_minted")
        return True

    def resolve(self, hash32: bytes) -> None:
        """The awaited bytes arrived (owner-side insert): wake every
        parked prober. No-op without a live lease."""
        ls = self._leases.pop(hash32, None)
        if ls is not None:
            ls.event.set()
            self.resolved += 1
            registry().inc("cache_lease_resolved")

    async def wait(self, hash32: bytes, wait_s: float) -> bool:
        """Park behind the live lease for at most wait_s; -> True when
        woken by a resolve (the caller re-checks the cache), False on
        timeout or when no lease is live (mint raced away / already
        resolved — re-check either way, the cache is the truth)."""
        ls = self._leases.get(hash32)
        if ls is None or wait_s <= 0:
            return False
        self.waits += 1
        registry().inc("cache_lease_wait")
        self._waiters += 1
        try:
            await asyncio.wait_for(ls.event.wait(), wait_s)
            self.wait_hits += 1
            registry().inc("cache_lease_wait_hit")
            return True
        except asyncio.TimeoutError:
            self.wait_timeouts += 1
            registry().inc("cache_lease_wait_timeout")
            self._reap(time.monotonic())
            return False
        finally:
            self._waiters -= 1

    @property
    def conservation_ok(self) -> bool:
        self._reap(time.monotonic())
        return (self._waiters == 0
                and self.minted == self.resolved + self.expired
                + len(self._leases))

    def __repr__(self) -> str:
        return (f"<ProbeLeaseTable depth={len(self._leases)} "
                f"waiters={self._waiters} minted={self.minted} "
                f"resolved={self.resolved} expired={self.expired}>")


class ProbeResult:
    """One probe's answer across both segments + the lease verdict."""

    __slots__ = ("plain", "packed", "lease", "timed_out")

    def __init__(self, plain=None, packed=None, lease=False,
                 timed_out=False):
        self.plain = plain        # decoded payload (verified) or None
        self.packed = packed      # exact on-disk packed bytes or None
        self.lease = lease        # this prober holds the decode lease
        self.timed_out = timed_out  # parked behind a lease, then lost


class ClusterCacheTier:
    """Router + hint book installed on BlockManager (`manager.cache_tier`)
    when `[block] cache_tier` is on and the node has a cluster system."""

    def __init__(self, manager, hint_top_n: int = HINT_TOP_N,
                 lease_wait_ms: float = LEASE_WAIT_MS_DEFAULT,
                 prefetch_inflight: int = PREFETCH_INFLIGHT_DEFAULT):
        self.manager = manager
        self.enabled = True
        self.hint_top_n = int(hint_top_n)
        # hash -> last-seen time, LRU-ordered (move_to_end on refresh)
        self._hints: "OrderedDict[bytes, float]" = OrderedDict()
        self._insert_inflight = 0
        # owner-side singleflight leases (`[block] cache_lease_wait_ms`;
        # 0 disables the wait-mode entirely — probes answer flat misses)
        self.leases = ProbeLeaseTable(lease_wait_ms)
        # hint-driven prefetch: bounded FIFO of owned-but-cold hinted
        # hashes, drained by <= prefetch_inflight background tasks, one
        # governor-paced sleep per fetch (qos/governor.py writes
        # prefetch_tranquility the same way it writes resync/scrub
        # tranquility)
        self.prefetch_inflight = max(0, int(prefetch_inflight))
        self.prefetch_tranquility = 0.0
        self._prefetch_q: "OrderedDict[bytes, None]" = OrderedDict()
        self._prefetch_running = 0
        self.prefetched = 0
        self.prefetch_skips = 0
        self.prefetch_drops = 0
        self.prefetch_errors = 0
        self.probes = 0
        self.probe_hits = 0
        self.probe_misses = 0
        self.probe_fails = 0
        self.probe_corrupt = 0
        self.probe_packed_hits = 0
        self.lease_grants = 0
        self.lease_wait_hits = 0
        self.lease_wait_timeouts = 0
        self.remote_hit_bytes = 0
        self.inserts_pushed = 0
        self.insert_skips = 0
        self.hints_sent = 0
        self.hints_seen = 0
        self.cross_zone_probes = 0
        self.hints_dropped_cross_zone = 0

    @property
    def lease_wait_ms(self) -> float:
        return self.leases.wait_ms

    @lease_wait_ms.setter
    def lease_wait_ms(self, v: float) -> None:
        self.leases.wait_ms = float(v)

    def probe_wait_ms(self) -> float:
        """The wait a prober may ask the owner for: the configured
        lease wait, clamped INSIDE the flat probe timeout minus the
        transfer margin — the satellite contract that a blackholed
        lease holder can never push a GET past the pre-lease worst
        case (probe timeout + one store read)."""
        budget = (PROBE_TIMEOUT_S - PROBE_WAIT_MARGIN_S) * 1000.0
        return max(0.0, min(self.leases.wait_ms, budget))

    # ---- ring -----------------------------------------------------------

    def _health(self):
        return self.manager.rpc.health()

    def _zone_of(self, node: bytes) -> Optional[str]:
        role = self.manager.system.layout_helper.current().node_role(node)
        if role is None or not role.zone:
            return None
        return role.zone

    def members(self) -> list[bytes]:
        """Live ring membership: the current layout's storage nodes IN
        THIS NODE'S ZONE (the whole cluster when this node has no zone
        — gateway worker, zoneless rig; with every node in one zone the
        two are the same roster), minus open-breaker peers (a degraded
        owner drops OUT of the ring — its share remaps — instead of
        blackholing probes). Breaker state is a local observation, so
        two nodes can briefly disagree on ownership while a breaker is
        open; the tier is a cache, so the cost is a duplicate fill,
        never a wrong answer. Zone membership comes from the shared
        layout, so all nodes of a zone DO agree on the zone roster."""
        system = self.manager.system
        me = system.id
        nodes = sorted(
            system.layout_helper.current().storage_nodes())
        my_zone = self._zone_of(me)
        if my_zone is not None:
            # per-zone ring (ISSUE 16): hot-block probes stay
            # intra-zone; a zoneless node in the roster is unreachable
            # as "same zone" and drops out too
            nodes = [n for n in nodes
                     if n == me or self._zone_of(n) == my_zone]
        health = self._health()
        if health is None:
            return nodes
        now = time.monotonic()
        return [n for n in nodes
                if n == me or health.breaker_state(n, now) != "open"]

    def owner_of(self, hash32: bytes) -> Optional[bytes]:
        """Remote owner to probe, or None when this node should serve
        locally (it owns the hash, routing is moot, or the tier is
        off). A node OUTSIDE the roster (gateway worker, draining node)
        still probes owners — it just never owns anything itself."""
        if not self.enabled or self.manager.cache.max_bytes <= 0:
            return None
        members = self.members()
        me = self.manager.system.id
        if not members or (len(members) == 1 and members[0] == me):
            return None
        owner = rendezvous_owner(members, hash32)
        if owner is None or owner == me:
            return None
        return owner

    def local_owner(self, hash32: bytes) -> bool:
        """True when a real multi-node ring elects THIS node the
        hash's cache owner — the gateway-worker shortcut's test: a
        local GET on the owner can serve straight from its own cache
        ring slot instead of paying a loopback router hop. Distinct
        from owns(): moot routing (tier off, lone member) is False
        here — the shortcut only fires when the ring genuinely routed
        the hash home."""
        if not self.enabled or self.manager.cache.max_bytes <= 0:
            return False
        members = self.members()
        if len(members) < 2:
            return False
        return rendezvous_owner(members, hash32) == self.manager.system.id

    def owns(self, hash32: bytes) -> bool:
        """Whether this node should hold the cached copy (True when
        routing is moot — an unrouted cache owns everything it sees)."""
        if not self.enabled or self.manager.cache.max_bytes <= 0:
            return True
        members = self.members()
        if len(members) < 2:
            return True
        owner = rendezvous_owner(members, hash32)
        return owner is None or owner == self.manager.system.id

    # ---- probe / insert (the cross-node seam) ---------------------------

    async def probe(self, owner: bytes, hash32: bytes,
                    cacheable: bool = True) -> Optional[bytes]:
        """Single-hop read-only probe of the owner's DECODED cache; ->
        decoded payload (content-verified) or None (miss / owner
        unreachable / failed verification). Never raises: a tier
        failure must degrade to the local path, not fail the read.
        `cacheable` is the same GL03 audit flag as the rpc_get_block
        seam — SSE-C state must pass cacheable=False, which makes the
        probe a no-op (an SSE-C hash is never even ASKED about across
        nodes). Lease-free and plain-only: the background callers
        (hint-gated resync) must not park behind a foreground herd's
        lease; the GET path's full form is probe_full."""
        res = await self.probe_full(owner, hash32, cacheable=cacheable,
                                    kinds=("plain",), wait=False)
        return res.plain

    async def probe_packed(self, owner: bytes,
                           hash32: bytes) -> Optional[bytes]:
        """Exact on-disk packed bytes from the owner's packed segment,
        or None — the rebuild/repair lane (verified by unpack+content
        check before returning). Lease-free: rebuilds are background
        work and fall straight back to the shard gather."""
        res = await self.probe_full(owner, hash32, cacheable=True,
                                    kinds=("packed",), wait=False)
        return res.packed

    async def probe_full(self, owner: bytes, hash32: bytes,
                         cacheable: bool = True,
                         kinds=("plain",), wait: bool = True
                         ) -> ProbeResult:
        """One probe RPC across the owner's requested segments, with
        the lease protocol engaged when `wait` is True:

          * a hit answers (plain or packed — packed is unpacked and
            verified here, so .plain is served either way);
          * a miss with no live lease MINTS one for this prober
            (.lease=True: the caller MUST decode and write-through,
            that insert is what wakes the parked herd);
          * a miss behind a live lease PARKS at the owner for at most
            probe_wait_ms() — budgeted inside the flat RPC timeout,
            never stacked — then re-checks; a timeout answers
            .timed_out=True and the caller falls back to the store
            WITHOUT pushing (the holder's insert is presumed in
            flight; N more MiB pushes are the waste leases kill).

        Never raises. `cacheable` is the GL03 audit flag; SSE-C state
        passes cacheable=False and nothing crosses the wire."""
        if not cacheable:
            return ProbeResult()
        self.probes += 1
        m = self.manager
        my_zone = self._zone_of(m.system.id)
        if my_zone is not None and self._zone_of(owner) != my_zone:
            # the per-zone ring makes this structurally unreachable for
            # storage nodes; the counter is the drill's assertion that
            # it STAYS that way (a regression here turns every hot read
            # into a WAN round-trip)
            self.cross_zone_probes += 1
            registry().inc("cache_tier_cross_zone_probe")
        wait_ms = self.probe_wait_ms() if wait else 0.0
        try:
            resp = await m.rpc.call(
                m.endpoint, owner,
                {"op": "cache_probe", "hash": hash32,
                 "kinds": list(kinds), "wait_ms": wait_ms,
                 "lease": bool(wait and wait_ms > 0)},
                PRIO_NORMAL, timeout=PROBE_TIMEOUT_S)
            if not isinstance(resp, dict):
                resp = {}
            data = resp.get("data")
        except Exception as e:
            self.probe_fails += 1
            registry().inc("cache_tier_probe_fail")
            log.debug("cache probe of %s at %s failed: %s",
                      hash32[:4].hex(), owner[:4].hex(), e)
            return ProbeResult()
        if data is None:
            self.probe_misses += 1
            registry().inc("cache_tier_probe_miss")
            if resp.get("lease"):
                self.lease_grants += 1
                registry().inc("cache_lease_granted")
                return ProbeResult(lease=True)
            if resp.get("waited"):
                self.lease_wait_timeouts += 1
                return ProbeResult(timed_out=True)
            return ProbeResult()
        kind = resp.get("kind", "plain")
        verified = await asyncio.to_thread(self._verify_probe, data,
                                           hash32, kind)
        if verified is None:
            self.probe_corrupt += 1
            registry().inc("cache_tier_probe_corrupt")
            log.warning("cache probe of %s at %s returned corrupt "
                        "%s payload; falling back to the store",
                        hash32[:4].hex(), owner[:4].hex(), kind)
            return ProbeResult()
        if resp.get("waited"):
            self.lease_wait_hits += 1
        self.probe_hits += 1
        if kind == "packed":
            self.probe_packed_hits += 1
            registry().inc("cache_tier_probe_packed_hit")
        self.remote_hit_bytes += len(data)
        registry().inc("cache_tier_probe_hit")
        registry().inc("cache_tier_remote_hit_bytes", len(data))
        if kind == "packed":
            return ProbeResult(plain=verified, packed=data)
        return ProbeResult(plain=data)

    @staticmethod
    def _verify_probe(data, hash32: bytes, kind: str):
        """End-to-end integrity off-loop: a remote payload is served
        only after it re-derives the content address (the store read
        paths all verify remote bytes; the tier must not be the one
        lane that trusts the wire). -> the decoded plain payload, or
        None on verification failure. Packed bytes verify through
        unpack + DataBlock.verify — the content address covers the
        plain bytes, so the unpack is the verification."""
        try:
            if kind == "packed":
                from .block import DataBlock

                blk = DataBlock.unpack(data)
                blk.verify(hash32)
                return blk.plain_bytes()
            from ..utils.data import content_hash_matches

            return data if content_hash_matches(data, hash32) else None
        except Exception as e:
            log.debug("probe payload failed %s verification for %s: %s",
                      kind, hash32[:4].hex(), e)
            return None

    def insert_at(self, owner: bytes, hash32: bytes, data,
                  kind: str = "plain") -> None:
        """Write-through at the owner after a local miss-decode: fire a
        bounded background push so the NEXT reader — on any node —
        probe-hits instead of re-decoding. Never blocks the caller.
        kind="packed" targets the owner's packed-bytes segment (exact
        on-disk bytes; the rebuild/repair lane's currency)."""
        if self._insert_inflight >= INSERT_INFLIGHT_MAX:
            self.insert_skips += 1
            return
        self._insert_inflight += 1
        from ..utils.background import spawn

        spawn(self._push_insert(owner, hash32, data, kind),
              "cache-tier-insert")

    async def _push_insert(self, owner: bytes, hash32: bytes,
                           data, kind: str = "plain") -> None:
        # background lane: a MiB-scale push over a slow link may
        # legitimately outlive the tight foreground probe budget
        m = self.manager
        try:
            await m.endpoint.call(
                owner, {"op": "cache_insert", "hash": hash32,
                        "data": data, "kind": kind},
                PRIO_BACKGROUND, timeout=15.0)
            self.inserts_pushed += 1
            registry().inc("cache_tier_insert_push")
        except Exception as e:
            log.debug("cache insert push of %s to %s failed: %s",
                      hash32[:4].hex(), owner[:4].hex(), e)
        finally:
            self._insert_inflight -= 1

    # ---- hot-hash hints (peering ping piggyback) ------------------------

    def hot_hashes(self) -> list[bytes]:
        """Outbound hint payload: this node's hottest cached hashes."""
        out = self.manager.cache.top_keys(self.hint_top_n) \
            if self.enabled else []
        self.hints_sent += len(out)
        return out

    def note_hints(self, from_node: bytes, hashes) -> None:
        """Inbound hints from a peer's ping. Bounded both ways: at most
        HINT_ACCEPT_MAX per ping, at most HINT_MAX remembered. Filtered
        to THIS zone on receipt (the outbound ping payload is shared by
        all peers, so the receive side is where intra-zone hint gossip
        is enforced): another zone's hot set must not make is_hot()
        send our background reads probing across the WAN."""
        my_zone = self._zone_of(self.manager.system.id)
        if my_zone is not None and self._zone_of(from_node) != my_zone:
            self.hints_dropped_cross_zone += 1
            registry().inc("cache_tier_hint_drop_cross_zone")
            return
        now = time.monotonic()
        for h in list(hashes)[:HINT_ACCEPT_MAX]:
            if not isinstance(h, bytes) or len(h) != 32:
                continue
            self._hints[h] = now
            self._hints.move_to_end(h)
            self.hints_seen += 1
            # prefetch trigger sits AFTER the zone filter above, so a
            # cross-zone hint can never reach it — and the fetch itself
            # is this node's own store path, so nothing is fetched
            # across a zone boundary either (satellite conformance)
            self._maybe_prefetch(h)
        while len(self._hints) > HINT_MAX:
            self._hints.popitem(last=False)

    # ---- hint-driven prefetch (ISSUE 18) --------------------------------

    def _maybe_prefetch(self, hash32: bytes) -> None:
        """A peer says hash32 is hot; if WE own it and don't hold it,
        queue a background decode so the first herd probe-hits instead
        of minting a lease. Queue is bounded (drops counted), drained
        by <= prefetch_inflight tasks, each fetch governor-paced."""
        if self.prefetch_inflight <= 0 or not self.enabled:
            return
        if not self.local_owner(hash32):
            # local_owner (not owns): a moot ring (lone member, tier
            # off) "owns" everything but has no herd to pre-warm for —
            # prefetch only when a real ring routed the hash HERE
            return
        if self.manager.cache.contains(hash32) \
                or hash32 in self._prefetch_q:
            self.prefetch_skips += 1
            return
        if len(self._prefetch_q) >= PREFETCH_QUEUE_MAX:
            self.prefetch_drops += 1
            registry().inc("cache_prefetch_drop")
            return
        self._prefetch_q[hash32] = None
        registry().inc("cache_prefetch_queued")
        self._kick_prefetch()

    def _kick_prefetch(self) -> None:
        from ..utils.background import spawn

        while self._prefetch_running < self.prefetch_inflight \
                and self._prefetch_q:
            # count BEFORE spawn: a second hint arriving before the
            # drainer's first tick must not over-spawn past the bound
            self._prefetch_running += 1
            spawn(self._prefetch_drain(), "cache-tier-prefetch")

    async def _prefetch_drain(self) -> None:
        m = self.manager
        try:
            while self._prefetch_q:
                h, _ = self._prefetch_q.popitem(last=False)
                if not self.owns(h) or m.cache.contains(h):
                    self.prefetch_skips += 1
                    continue
                if self.prefetch_tranquility > 0:
                    # governor pacing: same tranquility discipline as
                    # resync/scrub — client pressure stretches the
                    # inter-fetch gap instead of competing for disk
                    await asyncio.sleep(self.prefetch_tranquility)
                try:
                    # route=False: the owner decodes via its OWN store
                    # path (intra-zone by placement) and the read fill
                    # lands in this cache because owns(h) is True;
                    # charge=False: prefetch is the node's own bet, not
                    # a client read, so it must not count against any
                    # api quota
                    data = await m.rpc_get_block(h, route=False,
                                                 charge=False)
                    if data is not None:
                        self.prefetched += 1
                        registry().inc("cache_prefetch_done")
                except Exception as e:
                    self.prefetch_errors += 1
                    registry().inc("cache_prefetch_error")
                    log.debug("prefetch of %s failed: %s",
                              h[:4].hex(), e)
        finally:
            self._prefetch_running -= 1

    def is_hot(self, hash32: bytes) -> bool:
        """Whether any peer recently advertised hash32 as hot — the
        gate background reads (resync fetches) use before spending a
        probe RPC on a block that is overwhelmingly likely cold."""
        t = self._hints.get(hash32)
        if t is None:
            return False
        if time.monotonic() - t > HINT_TTL_S:
            del self._hints[hash32]
            return False
        return True

    # ---- surface --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "zone": self._zone_of(self.manager.system.id),
            "members": len(self.members()),
            "cross_zone_probes": self.cross_zone_probes,
            "hints_dropped_cross_zone": self.hints_dropped_cross_zone,
            "hints_known": len(self._hints),
            "hint_top_n": self.hint_top_n,
            "probes": self.probes,
            "probe_hits": self.probe_hits,
            "probe_misses": self.probe_misses,
            "probe_fails": self.probe_fails,
            "probe_corrupt": self.probe_corrupt,
            "probe_packed_hits": self.probe_packed_hits,
            "remote_hit_bytes": self.remote_hit_bytes,
            "inserts_pushed": self.inserts_pushed,
            "insert_skips": self.insert_skips,
            "hints_seen": self.hints_seen,
            # lease singleflight (ISSUE 18)
            "lease_wait_ms": self.leases.wait_ms,
            "lease_depth": self.leases.depth,
            "lease_minted": self.leases.minted,
            "lease_resolved": self.leases.resolved,
            "lease_expired": self.leases.expired,
            "lease_waits": self.leases.waits,
            "lease_wait_hits_local": self.leases.wait_hits,
            "lease_wait_timeouts_local": self.leases.wait_timeouts,
            "lease_grants": self.lease_grants,
            "lease_wait_hits": self.lease_wait_hits,
            "lease_wait_timeouts": self.lease_wait_timeouts,
            # hint-driven prefetch (ISSUE 18)
            "prefetch_inflight_max": self.prefetch_inflight,
            "prefetch_queue": len(self._prefetch_q),
            "prefetch_running": self._prefetch_running,
            "prefetched": self.prefetched,
            "prefetch_skips": self.prefetch_skips,
            "prefetch_drops": self.prefetch_drops,
            "prefetch_errors": self.prefetch_errors,
        }
