"""DataBlock: the on-wire/on-disk representation of one block or shard.

Ref parity: src/block/block.rs:12-106. A block travels either plain or
compressed; the content hash always refers to the PLAIN bytes, and a
compressed block is checked by decompressing and hashing. The reference
uses zstd level 1; this build uses zlib level 1 (no zstd in the runtime
— the header byte records the scheme so formats can coexist).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

from ..utils.data import content_hash_matches
from ..utils.error import CorruptData

COMPRESSION_NONE = 0
COMPRESSION_ZLIB = 1

COMPRESSION_LEVEL = 1  # ref: util/config.rs:280 (zstd level 1 default)


@dataclass
class DataBlock:
    compression: int
    bytes: bytes

    @classmethod
    def plain(cls, data: bytes) -> "DataBlock":
        return cls(COMPRESSION_NONE, data)

    # compressing a 64 KiB sample costs ~3 ms and reliably detects
    # already-compressed/encrypted payloads, for which a full-block
    # zlib pass would burn ~45 ms per 1 MiB for nothing
    _SAMPLE = 64 * 1024
    _SAMPLE_RATIO = 0.97

    @classmethod
    def compress(cls, data: bytes, level: int = COMPRESSION_LEVEL) -> "DataBlock":
        """Compress if it helps; otherwise keep plain
        (ref: block.rs:85-99 from_buffer). Incompressible payloads are
        detected from a leading sample before paying for the full pass."""
        if len(data) > 2 * cls._SAMPLE:
            probe = zlib.compress(data[: cls._SAMPLE], level)
            if len(probe) > cls._SAMPLE * cls._SAMPLE_RATIO:
                return cls(COMPRESSION_NONE, data)
        c = zlib.compress(data, level)
        if len(c) < len(data):
            return cls(COMPRESSION_ZLIB, c)
        return cls(COMPRESSION_NONE, data)

    def plain_bytes(self) -> bytes:
        if self.compression == COMPRESSION_NONE:
            return self.bytes
        return zlib.decompress(self.bytes)

    def verify(self, hash32: bytes) -> None:
        """ref: block.rs:69-83 (plain: content-hash check; compressed:
        integrity of the decompression stream + content hash of the
        result). Content hash is BLAKE3 by default (utils/data.py),
        blake2 accepted for stores migrated from the legacy algo."""
        try:
            plain = self.plain_bytes()
        except zlib.error as e:
            raise CorruptData(hash32) from e
        if not content_hash_matches(plain, hash32):
            raise CorruptData(hash32)

    # wire format: 1 header byte + payload
    def pack(self) -> bytes:
        return bytes([self.compression]) + self.bytes

    @classmethod
    def unpack(cls, raw: bytes) -> "DataBlock":
        return cls(raw[0], raw[1:])

    def file_suffix(self) -> str:
        return ".zlib" if self.compression == COMPRESSION_ZLIB else ""
