"""DataBlock: the on-wire/on-disk representation of one block or shard.

Ref parity: src/block/block.rs:12-106. A block travels either plain or
compressed; the content hash always refers to the PLAIN bytes, and a
compressed block is checked by decompressing and hashing. Default
scheme is zstd level 1 like the reference (util/config.rs:280); zlib
blocks written by earlier builds still decode — the header byte records
the scheme so formats coexist on disk and on the wire.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

try:
    import zstandard
    _ZstdError = zstandard.ZstdError
except ModuleNotFoundError:  # bare image: fall back to the zlib scheme
    zstandard = None

    class _ZstdError(Exception):
        """Placeholder so except-tuples stay valid; never raised."""

from ..utils.data import content_hash_matches
from ..utils.error import CorruptData

COMPRESSION_NONE = 0
COMPRESSION_ZLIB = 1
COMPRESSION_ZSTD = 2

COMPRESSION_LEVEL = 1  # ref: util/config.rs:280 (zstd level 1 default)

# scheme -> block-file suffix; every reader probes all of these
SUFFIX_OF = {COMPRESSION_NONE: "", COMPRESSION_ZLIB: ".zlib",
             COMPRESSION_ZSTD: ".zst"}
COMP_OF_SUFFIX = {v: k for k, v in SUFFIX_OF.items()}
BLOCK_SUFFIXES = list(SUFFIX_OF.values())


class MissingCodec(RuntimeError):
    """A stored block uses a compression scheme whose codec wheel is
    not installed here. The data is NOT corrupt — readers must fail the
    read without quarantining the file."""


def comp_of_path(p: str) -> int:
    """Compression scheme from a block-file path's suffix."""
    for sfx, comp in COMP_OF_SUFFIX.items():
        if sfx and p.endswith(sfx):
            return comp
    return COMPRESSION_NONE


@dataclass
class DataBlock:
    compression: int
    bytes: bytes

    @classmethod
    def plain(cls, data: bytes) -> "DataBlock":
        return cls(COMPRESSION_NONE, data)

    # compressing a 64 KiB sample costs ~3 ms and reliably detects
    # already-compressed/encrypted payloads, for which a full-block
    # zlib pass would burn ~45 ms per 1 MiB for nothing
    _SAMPLE = 64 * 1024
    _SAMPLE_RATIO = 0.97

    @classmethod
    def compress(cls, data: bytes, level: int = COMPRESSION_LEVEL) -> "DataBlock":
        """Compress (zstd, ref default scheme) if it helps; otherwise
        keep plain (ref: block.rs:85-99 from_buffer). Incompressible
        payloads are detected from a leading sample before paying for
        the full pass. Without the zstandard wheel the zlib scheme is
        written instead — every reader probes all schemes, so mixed
        stores interoperate."""
        if zstandard is None:
            if len(data) > 2 * cls._SAMPLE:
                probe = zlib.compress(data[: cls._SAMPLE], level)
                if len(probe) > cls._SAMPLE * cls._SAMPLE_RATIO:
                    return cls(COMPRESSION_NONE, data)
            c = zlib.compress(data, level)
            if len(c) < len(data):
                return cls(COMPRESSION_ZLIB, c)
            return cls(COMPRESSION_NONE, data)
        cctx = zstandard.ZstdCompressor(level=level)
        if len(data) > 2 * cls._SAMPLE:
            probe = cctx.compress(data[: cls._SAMPLE])
            if len(probe) > cls._SAMPLE * cls._SAMPLE_RATIO:
                return cls(COMPRESSION_NONE, data)
        c = cctx.compress(data)
        if len(c) < len(data):
            return cls(COMPRESSION_ZSTD, c)
        return cls(COMPRESSION_NONE, data)

    def plain_bytes(self) -> bytes:
        if self.compression == COMPRESSION_NONE:
            return self.bytes
        if self.compression == COMPRESSION_ZSTD:
            if zstandard is None:
                raise MissingCodec(
                    "zstd-compressed block but the zstandard wheel is "
                    "not installed")
            # a fresh decompressor per call: ZstdDecompressor instances
            # are not safe for concurrent use, and GET (to_thread) can
            # race a ScrubWorker read on another worker thread
            return zstandard.ZstdDecompressor().decompress(self.bytes)
        return zlib.decompress(self.bytes)

    def verify(self, hash32: bytes) -> None:
        """ref: block.rs:69-83 (plain: content-hash check; compressed:
        integrity of the decompression stream + content hash of the
        result). Content hash is BLAKE3 by default (utils/data.py),
        blake2 accepted for stores migrated from the legacy algo."""
        try:
            plain = self.plain_bytes()
        except (zlib.error, _ZstdError) as e:
            raise CorruptData(hash32) from e
        if not content_hash_matches(plain, hash32):
            raise CorruptData(hash32)

    # wire format: 1 header byte + payload
    def pack(self) -> bytes:
        return bytes([self.compression]) + self.bytes

    @classmethod
    def unpack(cls, raw: bytes) -> "DataBlock":
        return cls(raw[0], raw[1:])

    def file_suffix(self) -> str:
        return SUFFIX_OF[self.compression]
