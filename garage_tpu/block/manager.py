"""BlockManager: the content-addressed block data path.

Ref parity: src/block/manager.rs. Public surface mirrors the reference
(`rpc_put_block`, `rpc_get_block`, `block_incref/decref`) but the write
path is generic over the BlockCodec: replicate-N sends the whole
(optionally compressed) block to every node of the hash's write sets;
erasure(k, m) RS-encodes the packed block into k+m shards (TPU math)
placed on k+m distinct ring nodes, and reads gather any k.

Local files (under the DataLayout path scheme):
  whole blocks:  {hex}[.zst|.zlib] content = DataBlock payload
  shards:        {hex}.s{i}        content = shard file (len+checksum hdr)

RPC ops on endpoint "garage_tpu/block":
  {op: "put", hash, part|None, comp?, data}  part=None -> whole block (comp present: data = bare payload; absent: packed)
  {op: "get", hash, part|None}
  {op: "need", hash}                      -> {needed: bool}
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from typing import Optional

from ..chaos import injector as _chaos
from ..net.message import PRIO_BACKGROUND, PRIO_NORMAL
from ..rpc.rpc_helper import (
    HedgedRace,
    RequestStrategy,
    RpcHelper,
)
from ..utils.data import blake2sum
from ..utils.metrics import registry
from ..utils.error import CorruptData, MissingBlock, QuorumError, RpcError
from .block import BLOCK_SUFFIXES, COMPRESSION_NONE, DataBlock, comp_of_path
from .codec import BlockCodec, ErasureCodec, ReplicateCodec, shard_nodes_of
from .layout import DataLayout
from .rc import BlockRc

log = logging.getLogger("garage_tpu.block")

INLINE_THRESHOLD = 3072  # ref: block/manager.rs:46

_tmp_ctr = itertools.count()
_TMP_MAX_AGE = 3600.0  # stale .tmpN orphans (crash mid-write) get swept

_SHARD_MAGIC_V1 = b"GTS1"  # blake2-256 checksum (legacy)
_SHARD_MAGIC_C32C = b"GTS2"  # crc32c (native slice-by-8 kernel)
_SHARD_MAGIC_C32 = b"GTS3"  # zlib crc32 (no native toolchain)


def pack_shard(data: bytes, packed_len: int) -> bytes:
    """Shard file: magic + whole-block packed length + shard checksum +
    shard bytes (the checksum lets scrub detect bit rot in a shard
    without its k-1 siblings; the cryptographic integrity anchor remains
    the whole-block content hash, so a 32-byte blake2 here bought
    nothing but ~9 ms/block). The magic names the CRC flavor, so a
    native-less writer (zlib crc32) and a native reader interoperate —
    never fall back to pure-Python CRC on this path."""
    from .. import native

    # loaded() only — triggering the C build here would block the event
    # loop for seconds; until warm_async() lands, write the zlib flavor
    if native.loaded():
        magic = _SHARD_MAGIC_C32C
        ck = native.crc32c(data)
    else:
        import zlib

        magic = _SHARD_MAGIC_C32
        ck = zlib.crc32(data)
    return (magic + packed_len.to_bytes(8, "big")
            + ck.to_bytes(4, "big") + data)


def validate_shard(raw) -> int:
    """Checksum-verify a shard file image WITHOUT copying its payload
    (store-side validation: six shards per block made the old
    slice-copy a measured cost); -> whole-block packed length.
    Raises CorruptData. Reads every format (crc32c, zlib crc32,
    legacy blake2)."""
    mv = memoryview(raw)
    magic = bytes(mv[:4])
    packed_len = int.from_bytes(mv[4:12], "big")
    if magic == _SHARD_MAGIC_C32C:
        ck, data = bytes(mv[12:16]), mv[16:]
        from .. import native

        if native.loaded():
            good = native.crc32c(data).to_bytes(4, "big") == ck
        else:  # cross-node file from a native writer, no library here
            good = native.crc32c_py(data).to_bytes(4, "big") == ck
        if not good:
            raise CorruptData(b"")
    elif magic == _SHARD_MAGIC_C32:
        import zlib

        ck, data = bytes(mv[12:16]), mv[16:]
        if zlib.crc32(data).to_bytes(4, "big") != ck:
            raise CorruptData(b"")
    elif magic == _SHARD_MAGIC_V1:
        ck, data = bytes(mv[12:44]), mv[44:]
        if blake2sum(data) != ck:
            raise CorruptData(b"")
    else:
        raise CorruptData(b"")
    return packed_len


def unpack_shard(raw: bytes) -> tuple[bytes, int]:
    """-> (shard bytes, whole-block packed length); raises CorruptData."""
    packed_len = validate_shard(raw)
    hdr = 44 if bytes(raw[:4]) == _SHARD_MAGIC_V1 else 16
    return raw[hdr:], packed_len


def _hex_in(x: str, parts: set) -> bool:
    """Is the 2-hex-char prefix dir `x` one of the wanted partitions?
    (Foreign dir names in a data root are skipped, not crashed on.)"""
    try:
        return int(x, 16) in parts
    except ValueError:
        return False


class _ByteSemaphore:
    """Async counting semaphore over bytes with FIFO wakeup; a single
    oversize request (> capacity) is allowed when it is alone, so giant
    blocks don't deadlock."""

    def __init__(self, capacity: int):
        self.capacity = max(1, capacity)
        self.in_use = 0
        self._waiters: list[tuple[int, asyncio.Future]] = []

    async def acquire(self, n: int) -> None:
        # the fast path must not barge past queued waiters, or a large
        # request starves under a steady stream of small ones
        if not self._waiters and (
                self.in_use == 0 or self.in_use + n <= self.capacity):
            self.in_use += n
            return
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((n, fut))
        try:
            await fut
        except BaseException:
            try:
                self._waiters.remove((n, fut))
            except ValueError:
                # already popped by release(): granted unless cancelled
                if fut.done() and not fut.cancelled():
                    self.release(n)
            raise

    def queue_depth(self) -> int:
        """Writers currently parked behind the byte budget — a pressure
        signal that reacts BEFORE request latency does (the qos
        governor samples it alongside its latency EWMA)."""
        return len(self._waiters)

    def waiting_bytes(self) -> int:
        return sum(n for n, _ in self._waiters)

    def release(self, n: int) -> None:
        self.in_use -= n
        while self._waiters:
            need, fut = self._waiters[0]
            if self.in_use != 0 and self.in_use + need > self.capacity:
                break
            self._waiters.pop(0)
            if not fut.cancelled():
                self.in_use += need
                fut.set_result(None)


class BlockManager:
    def __init__(self, system, db, data_layout: DataLayout,
                 codec: Optional[BlockCodec] = None,
                 compression: bool = True, fsync: bool = False,
                 device_mode: str = "auto",
                 device_batch_blocks: int = 256,
                 tpu_cfg=None,
                 ram_buffer_max: int = 256 * 1024 * 1024,
                 read_cache_max_bytes: Optional[int] = None,
                 resync_breaker_aware: bool = True,
                 cache_tier: bool = True,
                 cache_tier_hint_top_n: int = 16,
                 cache_lease_wait_ms: float = 250.0,
                 cache_prefetch_inflight: int = 2,
                 cache_packed_max_bytes: Optional[int] = None):
        self.system = system
        self.db = db
        self.data_layout = data_layout
        self.compression = compression
        self.fsync = fsync
        self.rc = BlockRc(db)
        self.rpc = RpcHelper(system)
        if codec is None:
            rm = system.replication
            if rm.erasure is not None:
                codec = ErasureCodec(*rm.erasure,
                                     write_quorum=rm.block_write_quorum)
            else:
                codec = ReplicateCodec(rm.factor,
                                       write_quorum=rm.write_quorum)
        self.codec = codec
        from .feeder import DeviceFeeder

        self.feeder = DeviceFeeder(
            codec=codec if isinstance(codec, ErasureCodec) else None,
            mode=device_mode,
            max_batch=device_batch_blocks,
            tpu_cfg=tpu_cfg,
        )
        # RAM held by in-flight outbound block writes, bounded like the
        # reference's buffer_stream semaphore (ref: manager.rs:156,
        # util/config.rs:272-274 block_ram_buffer_max). Slot unit = one
        # byte; putters acquire len(packed) before fan-out.
        self._ram_sem = _ByteSemaphore(ram_buffer_max)
        # hot-block read cache (block/cache.py): decoded payloads keyed
        # by content hash, sized off block_ram_buffer_max unless the
        # `[block] read_cache_max_bytes` knob says otherwise (0 = off)
        from .cache import BlockCache

        if read_cache_max_bytes is None:
            read_cache_max_bytes = ram_buffer_max // 4
        self.cache = BlockCache(read_cache_max_bytes)
        # packed-bytes tier segment (ISSUE 18): the EXACT on-disk packed
        # bytes an erasure decode reassembles, keyed by the same content
        # hash. Shard rebuilds and scrub stripe repairs re-encode from
        # it (deterministic RS encode -> byte-identical shards, the
        # _repair_stripe precedent), skipping the k-shard gather; a
        # degraded GET serves from it before gathering. Erasure-only: in
        # replicate mode the packed form is just scheme byte + payload
        # and the plain cache already covers it. `[block]
        # cache_packed_max_bytes` (0 = off), default ram_buffer_max/8.
        if cache_packed_max_bytes is None:
            cache_packed_max_bytes = ram_buffer_max // 8
        self.packed_cache = BlockCache(
            cache_packed_max_bytes if self.erasure else 0)
        # node-local read singleflight (ISSUE 18): one store gather+
        # decode per hash per process, concurrent readers collapse onto
        # the leader's future (the node-local leg of the lease story)
        self._sf: dict[bytes, asyncio.Future] = {}
        self.sf_leaders = 0
        self.sf_collapsed = 0
        # optional async hook (Garage wires qos.shape_bytes): every
        # foreground block read — hit or miss — charges the qos bytes
        # budget, so GET/copy traffic is paced evenly whether it is
        # served from RAM or from the store (background resync/scrub
        # reads don't come through rpc_get_block and stay uncharged)
        self.read_qos_charge = None
        # worker-sharded read cache (gateway/): when set (gateway API
        # workers only), cacheable reads are routed to the rendezvous-
        # hash OWNER worker over loopback RPC so the node holds one
        # decoded copy of a hot block instead of one per worker. The
        # router duck-type is {owner_of(h), owns(h), forward(owner, h)}.
        self.cache_router = None
        self.endpoint = system.netapp.endpoint("garage_tpu/block").set_handler(
            self._handle
        )
        # CLUSTER cache tier (block/cache_tier.py, ISSUE 15): rendezvous
        # owner routing over the layout's storage-node roster, breaker-
        # filtered; non-owner reads probe the owner's cache in one hop
        # and warm it on miss, so the cluster pays ~1 decode per hot
        # block instead of 1 per node. `[block] cache_tier = false`
        # kills the lane (every read serves node-locally as before).
        self.cache_tier = None
        peering = getattr(system, "peering", None)
        if cache_tier and peering is not None:
            from .cache_tier import ClusterCacheTier

            self.cache_tier = ClusterCacheTier(
                self, hint_top_n=cache_tier_hint_top_n,
                lease_wait_ms=cache_lease_wait_ms,
                prefetch_inflight=cache_prefetch_inflight)
            # hot-hash hints ride the existing peering pings: the
            # peering layer stays block-agnostic (plain callables), the
            # tier decides what is hot and what a hint means
            peering.hint_provider = self.cache_tier.hot_hashes
            peering.hint_sink = self.cache_tier.note_hints
        from .resync import BlockResyncManager

        self.resync = BlockResyncManager(
            self, db, breaker_aware=resync_breaker_aware)
        # set by spawn_workers; pre-set so API-only processes (gateway
        # workers never spawn block workers) can render metrics/state
        self.scrub_worker = None
        self.metrics = {"bytes_read": 0, "bytes_written": 0,
                        "corruptions": 0, "resync_sent": 0,
                        "resync_recv": 0, "resync_bytes": 0,
                        # full store reads (gather+decode / disk+verify)
                        # — what the cluster cache tier exists to
                        # dedupe; bench_cache_tier sums this across
                        # nodes to prove "~1 decode per hot block"
                        "store_reads": 0}
        # layout-transition participation (ISSUE 6): a new layout
        # version means every block held or needed here must be
        # re-examined (fetch what moved in, offload what moved away),
        # and once that backlog drains the block layer reports its
        # sync position so old layout versions can be GC'd. The block
        # layer registers as a sync SOURCE next to the table syncers —
        # the node's sync tracker advances at the minimum across
        # layers.
        lm = getattr(system, "layout_manager", None)
        if lm is not None:
            lm.register_sync_source("blocks")
            self.resync.bootstrap_layout_marker()
            lm.on_change.append(self.resync.note_layout_change)

    @property
    def erasure(self) -> bool:
        return isinstance(self.codec, ErasureCodec)

    def spawn_workers(self, runner, scrub: bool = True) -> None:
        from .repair import ScrubWorker

        self.resync.spawn_workers(runner)
        self.scrub_worker = None
        if scrub:
            self.scrub_worker = ScrubWorker(self)
            runner.spawn_worker(self.scrub_worker)

    def register_bg_vars(self, vars) -> None:
        """Runtime-tunables for `worker get/set` (ref: BgVars
        registrations in block/manager.rs:213-233)."""
        res = self.resync

        def set_rt(v):
            res.tranquility = float(v)
            # an explicit operator set takes the knob away from the qos
            # governor until it is explicitly re-enabled
            res.tranquility_manual = True

        vars.register_rw("resync-tranquility",
                         lambda: res.tranquility, set_rt)
        sw = getattr(self, "scrub_worker", None)
        if sw is not None:
            def set_st(v):
                sw.state.tranquility = float(v)
                sw.state.tranquility_manual = True
                sw.persister.save(sw.state)

            def set_paused(v):
                sw.state.paused = v.lower() in ("1", "true", "yes")
                sw.persister.save(sw.state)

            vars.register_rw("scrub-tranquility",
                             lambda: sw.state.tranquility, set_st)
            vars.register_rw("scrub-paused",
                             lambda: sw.state.paused, set_paused)
            vars.register_rw(
                "scrub-last-completed",
                lambda: sw.state.last_completed,
                lambda v: (_ for _ in ()).throw(
                    ValueError("read-only variable")),
            )

            def set_deep(v):
                sw.deep = v.lower() in ("1", "true", "yes")

            vars.register_rw("scrub-deep", lambda: int(sw.deep), set_deep)

    async def stop(self) -> None:
        await self.feeder.stop()

    # ==== cluster write path (ref: manager.rs:366-450) ==================

    async def hash_block(self, data: bytes) -> bytes:
        """Content hash of a plain block — batched with all concurrent
        callers through the device feeder (API PUT path entry point)."""
        return await self.feeder.hash(data)

    async def hash_block_md5(self, data: bytes, md5acc) -> bytes:
        """Content hash + ETag-MD5 advance in one feeder call (fused
        single native pass on the host route; see feeder.hash_with_md5)."""
        return await self.feeder.hash_with_md5(data, md5acc)

    def ingest_pool(self, block_size: int, count: int):
        """The pinned ingest buffer pool for the PUT fast path
        (block/hostbuf.py), built lazily once. Erasure-only: the pool's
        flat layout IS the RS staging stripe; replicate mode returns
        None and PUTs keep the classic path. `count` comes from
        `[s3_api] ingest_buffers` (0 disables)."""
        if not self.erasure or count <= 0:
            return None
        pool = getattr(self, "_ingest_pool", None)
        if pool is None:
            from .hostbuf import HostBufPool

            pool = HostBufPool(self.codec.k, block_size, count)
            self._ingest_pool = pool
        return pool

    async def rpc_put_block(self, hash32: bytes, data: bytes,
                            compress: Optional[bool] = None,
                            cacheable: bool = True) -> None:
        """`data` is the block payload: bytes, or a hostbuf.BlockLease
        on the zero-copy ingest path (erasure + no SSE; the caller owns
        the lease and releases it after this returns)."""
        from ..utils.tracing import span

        lease = data if hasattr(data, "stripe") else None
        await self._ram_sem.acquire(len(data))
        try:
            async with span("block.put", size=len(data), hash=hash32):
                do_compress = (self.compression if compress is None
                               else compress)
                if lease is not None:
                    blk = (await asyncio.to_thread(
                        DataBlock.compress, lease.view())
                        if do_compress else None)
                    if blk is None or blk.compression == COMPRESSION_NONE:
                        # zero-copy leg: scheme byte lands in the
                        # lease's header slot and the feeder stages the
                        # prefilled stripe directly — no pack, no pad
                        lease.set_scheme(COMPRESSION_NONE)
                        await self._put_erasure(
                            hash32, bytes([COMPRESSION_NONE]), lease)
                    else:
                        # the body shrank: the compressed copy is a NEW
                        # (smaller) buffer, so the classic path costs
                        # nothing extra
                        await self._put_erasure(hash32,
                                                bytes([blk.compression]),
                                                blk.bytes)
                elif self.erasure:
                    blk = (await asyncio.to_thread(DataBlock.compress, data)
                           if do_compress else DataBlock.plain(data))
                    # the 1-byte DataBlock header travels as a prefix so
                    # the megabyte payload is never concat-copied
                    await self._put_erasure(hash32,
                                            bytes([blk.compression]),
                                            blk.bytes)
                else:
                    blk = (await asyncio.to_thread(DataBlock.compress, data)
                           if do_compress else DataBlock.plain(data))
                    # scheme byte travels as its own field: the
                    # megabyte payload is never concat-copied into a
                    # packed buffer (same trick as the erasure prefix)
                    await self._put_replicate(hash32, blk.compression,
                                              blk.bytes)
            # write-through: freshly written blocks are the hottest
            # reads (read-after-write). `data` is exactly the decoded
            # payload rpc_get_block returns. SSE-C callers pass
            # cacheable=False — never cache payloads the node cannot
            # re-derive without the client's key. Under a sharded
            # gateway cache only the OWNER worker keeps the copy, and
            # under the CLUSTER tier only the owner NODE does (a
            # non-owner write-through would recreate the N-duplicates
            # problem the routing exists to kill): a non-owner PUT
            # warms the cluster owner with a bounded background push
            # instead of filling its own cache.
            if cacheable:
                tier = getattr(self, "cache_tier", None)
                if lease is not None and (self.cache.max_bytes > 0
                                          or tier is not None):
                    # caches keep references past the request; a lease's
                    # buffer is recycled at release, so the write-through
                    # needs its own durable copy (a CACHE fill, not a
                    # data-plane hop — deliberately outside
                    # s3_put_copy_bytes)
                    data = bytes(lease.view())
                tier_owner = (tier.owner_of(hash32)
                              if tier is not None else None)
                if tier_owner is not None:
                    # lint: ignore[GL03] guarded by the cacheable= audit flag itself: SSE-C callers pass cacheable=False (pinned by conformance tests), so tainted payloads never reach the tier push
                    self.cache_tier.insert_at(tier_owner, hash32, data)
                # a storage node that is not the cluster owner keeps no
                # local copy; gateway workers (cache_router set) keep
                # their worker-sharded node-level copy regardless —
                # the frontend L1 under the cluster tier's L2
                if (tier_owner is None
                        or self.cache_router is not None) and (
                        self.cache_router is None
                        or self.cache_router.owns(hash32)):
                    # lint: ignore[GL03] guarded by the cacheable= audit flag itself: SSE-C callers pass cacheable=False (pinned by conformance tests), so tainted payloads never reach this insert
                    self.cache.insert(hash32, data)
        finally:
            self._ram_sem.release(len(data))

    async def _put_replicate(self, hash32: bytes, comp: int,
                             payload: bytes) -> None:
        helper = self.system.layout_helper
        with helper.write_lock():
            sets = helper.write_sets_of(hash32)
            # lint: ignore[GL06] write_lock is a layout-version PIN (refcount), not mutual exclusion; holding it across the quorum write IS the union-window contract (manager.rs:344)
            await self.rpc.try_write_many_sets(
                self.endpoint, sets,
                {"op": "put", "hash": hash32, "part": None, "comp": comp,
                 "data": payload},
                RequestStrategy(quorum=self.codec.write_quorum,
                                prio=PRIO_NORMAL,
                                timeout=60.0),
            )

    async def _put_erasure(self, hash32: bytes, prefix: bytes,
                           data: bytes) -> None:
        from ..utils.tracing import span

        async with span("block.encode", size=len(data)):
            payloads = await self.feeder.encode_put(data, prefix=prefix)
        # shard payloads stay memoryviews over the encoder's one output
        # buffer: split_blob hoists them out of the dict before msgpack
        # (never serialized), self-calls hand them to validate/write
        # directly, and remote sends scatter them as raw blob sections
        helper = self.system.layout_helper
        with helper.write_lock():
            # One shard placement per live layout version, mirroring
            # try_write_many_sets: the write is acked only once EVERY
            # version's placement holds a write quorum of shards, so a
            # layout transition never weakens the ack-lock guarantee.
            sets: list[list[tuple[bytes, int]]] = []
            for v in helper.versions_for_writes():
                placement = shard_nodes_of(v, hash32, self.codec.width)
                if len(placement) < self.codec.write_quorum:
                    raise QuorumError(self.codec.write_quorum, 1, 0,
                                      len(placement), ["cluster too small"])
                s = [(n, i) for i, n in enumerate(placement)]
                if s not in sets:
                    sets.append(s)
            # quorum unit = placement entry (node, shard index): a node
            # may be assigned different shard indices under different
            # layout versions, so keys are tuples, not bare node ids
            async with span("block.write_shards", width=self.codec.width):
                await self._write_shard_sets(hash32, payloads, sets)

    async def _write_shard_sets(self, hash32, payloads, sets) -> None:
        # hedge=True (ROADMAP carry-over): a shard holder that sits in
        # the quorum-critical set and goes quiet used to hold the whole
        # PUT to its timeout — exactly the tail a draining node grows
        # during a resize. Shard puts are keyed by content hash + shard
        # index, so a re-issued backup push landing twice writes the
        # same bytes to the same path: idempotent, first ack wins.
        await self.rpc.try_write_many_sets(
            self.endpoint, sets, None,
            RequestStrategy(quorum=self.codec.write_quorum,
                            prio=PRIO_NORMAL, timeout=60.0,
                            hedge=True),  # lint: ignore[GL02] shard puts are content-addressed and idempotent; a duplicate backup push re-writes identical bytes
            make_call=lambda key: self.endpoint.call(
                key[0],
                {"op": "put", "hash": hash32, "part": key[1],
                 "data": payloads[key[1]]},
                PRIO_NORMAL, timeout=60.0,
            ),
        )

    # ==== cluster read path (ref: manager.rs:243-363) ===================

    async def rpc_get_block(self, hash32: bytes,
                            cacheable: bool = True, route: bool = True,
                            charge: bool = True) -> bytes:
        """Decoded block payload. A read-cache hit returns without any
        block RPC — in erasure mode that means the whole shard gather +
        RS decode + verify is skipped. `cacheable=False` (SSE-C) both
        bypasses the lookup and suppresses the miss fill — and, on a
        gateway worker, also skips cross-worker routing, so an SSE-C
        payload never crosses a worker boundary.

        `route=False` serves locally even when a gateway cache router
        is installed (the owner-side handler of a forwarded read uses
        it — one hop, never a chain; the CLUSTER tier probe below is a
        different layer and stays live, so a worker serving a sibling's
        forward still exploits the cluster owner's cache). `charge=False`
        skips the qos byte charge (the FORWARDING worker charges its
        own lease for bytes it serves to its client; the owner must not
        double-charge)."""
        charge_fn = self.read_qos_charge if charge else None
        fill = cacheable
        tier = None
        tier_owner = None
        push_owner = True
        if cacheable:
            data = self.cache.get(hash32)
            if data is not None:
                if charge_fn is not None:
                    await charge_fn(len(data))
                return data
            # routing exists to exploit the OWNER's cache; with the
            # cache disabled (read_cache_max_bytes = 0) a forward is a
            # guaranteed miss plus a second loopback hop — skip it
            router = (self.cache_router
                      if route and self.cache.max_bytes > 0 else None)
            tier = getattr(self, "cache_tier", None)
            if router is not None and tier is not None \
                    and tier.local_owner(hash32):
                # tier-aware worker shortcut (ISSUE 17): this NODE is
                # the block's cluster cache-tier owner, so the cluster
                # copy (write-through + probe warms) already lives
                # here — a worker-ring forward would spend a loopback
                # hop reaching a sibling whose best answer is bytes
                # this process can serve itself
                registry().inc("cache_tier_local_owner_shortcut")
                router = None
            if router is not None:
                owner = router.owner_of(hash32)
                if owner is not None:
                    data = await router.forward(owner, hash32)
                    if data is not None:
                        if charge_fn is not None:
                            await charge_fn(len(data))
                        return data
                    # owner unreachable: serve from the store directly,
                    # WITHOUT filling our cache — a transient forward
                    # failure must not seed duplicate copies
                    fill = False
            # cluster cache tier (block/cache_tier.py): a non-owner
            # read probes the block's owner NODE in one hedge-safe hop
            # — a hit is the whole point of the tier (zero gathers,
            # zero decodes anywhere); a miss or open-breaker owner
            # falls through to today's local path, and the decoded
            # result warms the owner below. The probe carries the
            # lease protocol (ISSUE 18): a cold herd's first prober is
            # granted the decode lease, the rest park at the owner
            # INSIDE the probe's flat timeout and are woken by the
            # holder's insert — a flash crowd pays ~1 decode per
            # block cluster-wide, not 1 per node. SSE-C never reaches
            # this probe: cacheable=False skips the enclosing branch.
            if tier is not None:
                tier_owner = tier.owner_of(hash32)
                if tier_owner is not None:
                    kinds = ("plain", "packed") if self.erasure \
                        else ("plain",)
                    res = await tier.probe_full(tier_owner, hash32,
                                                cacheable=cacheable,
                                                kinds=kinds)
                    if res.plain is not None:
                        if charge_fn is not None:
                            await charge_fn(len(res.plain))
                        return res.plain
                    if res.timed_out:
                        # parked behind the lease and lost: the
                        # holder's MiB-scale insert push is presumed in
                        # flight — do NOT pile this node's own push on
                        # top (N redundant pushes are exactly the
                        # amplification leases exist to kill)
                        push_owner = False
                    if self.cache_router is None:
                        # storage node: one decoded copy per CLUSTER —
                        # the owner gets the write-through, this node
                        # does not keep one. Gateway WORKERS keep their
                        # worker-sharded node-level copy (the frontend
                        # L1; the cluster tier is its L2) — without it
                        # every hot forward would re-probe the storage
                        # owner over loopback.
                        fill = False
                elif tier.leases.live(hash32):
                    # THIS node is the hash's cache owner and a remote
                    # prober currently holds the decode lease: park
                    # behind it like a remote waiter would, then
                    # re-check — the holder's insert usually lands
                    # first and this read never touches the store
                    await tier.leases.wait(
                        hash32, tier.probe_wait_ms() / 1000.0)
                    data = self.cache.get(hash32)
                    if data is not None:
                        if charge_fn is not None:
                            await charge_fn(len(data))
                        return data
                if tier_owner is None and tier.enabled \
                        and self.cache.max_bytes > 0:
                    # owner-side SELF-lease: this node is about to pay
                    # the herd's decode, so a remote prober arriving
                    # while it is in flight must PARK behind this lease
                    # instead of being granted a second one — without
                    # it a herd that includes the owner pays two
                    # decodes per block, not one. No-op when a lease
                    # is already live or the wait-mode is off; the fill
                    # below resolves it (the error path resolves too).
                    tier.leases.mint(hash32, self.system.id)
        if cacheable:
            # node-local singleflight: concurrent readers of one hash
            # collapse onto a single gather+decode (SSE-C stays on the
            # direct path — its payloads must not transit a shared
            # future other requests can await)
            try:
                data = await self._read_store(hash32)
            except BaseException:
                if tier is not None and tier_owner is None:
                    # a failed owner read must not leave probers parked
                    # out their full wait behind a lease nobody will
                    # resolve — wake them now; they re-check the cache
                    # (the truth) and fall back to their own stores
                    tier.leases.resolve(hash32)
                raise
        else:
            data = await self._get_uncached(hash32)
        if fill:
            # fill is only ever True inside the cacheable branch; SSE-C
            # callers pass cacheable=False (pinned by conformance tests)
            self.cache.insert(hash32, data)
        if cacheable and tier is not None and tier_owner is None:
            # owner-side fill: wake every prober parked on this hash
            # (no-op without a live lease)
            tier.leases.resolve(hash32)
        if tier_owner is not None and push_owner:
            # write-through at the owner (bounded background push): the
            # next reader of this block — on any node — probe-hits
            # instead of paying another gather+decode. tier_owner is
            # only resolved inside the cacheable branch, so SSE-C
            # payloads never reach the tier push
            tier.insert_at(tier_owner, hash32, data)
        if charge_fn is not None:
            # charged symmetrically with the hit path above: a byte
            # budget that only priced one of RAM/store reads would
            # invert the cache's advantage (or let hot sets ride free)
            await charge_fn(len(data))
        return data

    async def _read_store(self, hash32: bytes) -> bytes:
        """Node-local read singleflight (ISSUE 18): the first caller of
        a hash becomes the LEADER and pays the store gather+decode;
        every concurrent caller awaits the leader's future instead of
        decoding the same bytes again. A leader that fails or is
        cancelled releases the hash — one surviving waiter retries (and
        becomes the new leader), so collapse can never lose a read that
        would have succeeded solo. Cacheable reads only: SSE-C stays on
        the direct _get_uncached path."""
        fut = self._sf.get(hash32)
        if fut is not None:
            self.sf_collapsed += 1
            registry().inc("cache_sf_collapsed")
            try:
                # shield: one waiter's client disconnecting must not
                # cancel the leader's decode out from under the rest
                return await asyncio.shield(fut)
            except asyncio.CancelledError:
                if not fut.cancelled():
                    raise  # THIS caller was cancelled, not the leader
            except Exception as e:
                # leader failed; retry below, possibly as the new leader
                log.debug("read singleflight leader for %s failed: %s",
                          hash32[:4].hex(), e)
            return await self._read_store(hash32)
        fut = asyncio.get_running_loop().create_future()
        self._sf[hash32] = fut
        self.sf_leaders += 1
        registry().inc("cache_sf_leader")
        try:
            data = await self._get_uncached(hash32, fill_packed=True)
        except BaseException as e:
            if isinstance(e, asyncio.CancelledError):
                fut.cancel()
            else:
                fut.set_exception(e)
                fut.exception()  # consumed: no orphan-future warning
            raise
        else:
            fut.set_result(data)
            return data
        finally:
            self._sf.pop(hash32, None)

    async def _get_uncached(self, hash32: bytes,
                            fill_packed: bool = False) -> bytes:
        self.metrics["store_reads"] += 1
        if self.erasure:
            # verification happens inside: a decode is retried against
            # every distinct packed_len candidate before giving up.
            # fill_packed (cacheable reads only — _read_store sets it,
            # the direct SSE-C path never does) admits the reassembled
            # packed bytes into the packed tier segment for the
            # rebuild/repair lane.
            return await self._get_erasure(hash32,
                                           fill_packed=fill_packed)
        packed, verified = await self._get_replicate(hash32)

        def unpack_verify() -> bytes:
            blk = DataBlock.unpack(packed)
            if not verified:
                blk.verify(hash32)
            return blk.plain_bytes()

        # MiB-scale decompress+hash release the GIL: run them in a
        # worker thread so the GET readahead pipeline's prefetches
        # genuinely overlap instead of serializing on the event loop
        if len(packed) >= 64 * 1024:
            return await asyncio.to_thread(unpack_verify)
        return unpack_verify()

    async def _get_replicate(self, hash32: bytes) -> tuple[bytes, bool]:
        """-> (packed block, already_content_verified). Local reads
        verify inside read_local — re-hashing the same MiB in
        rpc_get_block doubled the CPU cost of every local GET block.

        Remote failover is HEDGED: when the current holder hasn't
        answered within its observed p95, the next candidate (breaker-
        and ping-ranked) is asked in parallel instead of waiting out
        the full timeout — a hung holder costs one hedge delay, not
        30-60 s (Dean & Barroso, "The Tail at Scale")."""
        me = self.system.id
        nodes = self.system.layout_helper.block_read_nodes_of(hash32)
        errs: list[Exception] = []
        if me in nodes:
            try:
                # off the event loop: a cold-cache disk read plus the
                # content verify would stall every other request for
                # milliseconds per block
                local = await asyncio.to_thread(self.read_local, hash32)
                if local is not None:
                    return local, True
            except OSError as e:
                # injected/real local EIO: degrade to the remote holders
                errs.append(e)
        remote = self.rpc.request_order([n for n in nodes if n != me])
        race = HedgedRace(self.rpc.health(), "block_get")
        i = 0

        def launch(hedged: bool = False):
            nonlocal i
            node = remote[i]
            i += 1
            race.launch(node, self.rpc.call(
                self.endpoint, node,
                {"op": "get", "hash": hash32, "part": None},
                PRIO_NORMAL, timeout=60.0,
            ), hedged)

        if remote:
            launch()
        try:
            while race.pending:
                done = await race.wait(
                    can_hedge=i < len(remote),
                    launch_hedge=lambda: launch(hedged=True))
                # drain EVERY completed task before returning: a loser
                # that failed in the same wait round must have its
                # exception retrieved, or asyncio logs an orphan
                won = None
                won_node = None
                for _node, was_hedged, t in done:
                    try:
                        resp = t.result()
                        if won is None and resp.get("data") is not None:
                            won = resp["data"]
                            won_node = _node
                            race.note_success(was_hedged)
                    except Exception as e:
                        errs.append(e)
                if won is not None:
                    self._count_remote_read(won_node, len(won))
                    return won, False
                # every holder in this round failed or had no copy:
                # move down the list
                if done and i < len(remote):
                    launch()
        finally:
            # a task that finished between the wait and this cleanup
            # still needs its exception consumed
            race.cancel_pending()
        raise MissingBlock(hash32)

    def _count_remote_read(self, node: bytes, nbytes: int) -> None:
        """Remote-read byte accounting by zone locality (ISSUE 16):
        request_order keeps reads local-zone-first, so the cross-zone
        series should stay a small fraction of the total — bench_zone
        and the zone-partition drill assert on exactly that ratio."""
        registry().inc("block_remote_read_bytes", nbytes)
        layout = self.system.layout_helper.current()
        mine = layout.node_role(self.system.id)
        theirs = layout.node_role(node)
        if mine is None or theirs is None \
                or not mine.zone or not theirs.zone:
            return
        if mine.zone != theirs.zone:
            registry().inc("block_cross_zone_read_bytes", nbytes)

    async def _get_erasure(self, hash32: bytes,
                           fill_packed: bool = False) -> bytes:
        """Gather k shards, decode, verify against the content address.

        The shard header's packed_len field sits outside the shard
        checksum, so _gather_parts majority-votes it — but a vote can
        TIE (e.g. k=2 with one rotted header). On verify failure every
        other distinct candidate is decoded and checked before moving
        on: a recoverable block must never be reported corrupt because
        the wrong tiebreak was picked (ADVICE r5).

        A local packed-tier hit (ISSUE 18) short-circuits the whole
        gather: the cached bytes ARE the reassembled packed block
        (content-verified at admission), so only the unpack+verify
        remains."""
        if fill_packed:
            cached = self.packed_cache.get(hash32)
            if cached is not None:
                registry().inc("cache_packed_local_hit")

                def unpack_cached() -> bytes:
                    blk = DataBlock.unpack(cached)
                    blk.verify(hash32)
                    return blk.plain_bytes()

                try:
                    if len(cached) >= 64 * 1024:
                        return await asyncio.to_thread(unpack_cached)
                    return unpack_cached()
                except CorruptData:
                    # can't happen for an admission-verified entry, but
                    # a cache must never be the lane that serves rot
                    self.packed_cache.discard(hash32)
        helper = self.system.layout_helper
        versions = list(reversed(
            helper.history.versions + helper.history.old_versions
        ))
        tried = set()
        gathered_any = False
        for v in versions:
            placement = shard_nodes_of(v, hash32, self.codec.width)
            key = tuple(placement)
            if key in tried or not placement:
                continue
            tried.add(key)
            got = await self._gather_parts(hash32, placement,
                                           self.codec.read_need)
            if got is None:
                continue
            gathered_any = True
            parts, candidates, _lens = got
            for packed_len in candidates:
                try:
                    packed = await self._decode_parts(parts, packed_len)

                    def unpack_verify(packed=packed) -> bytes:
                        blk = DataBlock.unpack(packed)
                        blk.verify(hash32)
                        return blk.plain_bytes()

                    # MiB-scale decompress+verify off the event loop,
                    # same rule as the replicate read path
                    if len(packed) >= 64 * 1024:
                        plain = await asyncio.to_thread(unpack_verify)
                    else:
                        plain = unpack_verify()
                    if fill_packed:
                        # the decode just proved these ARE the packed
                        # bytes behind the content address: admit them
                        # into the packed tier segment so the next
                        # rebuild/degraded read skips the gather
                        self._packed_fill(hash32, packed)
                    return plain
                except (CorruptData, ValueError, IndexError):
                    # a forged/rotted length can make the decode itself
                    # blow up, not just the content check — either way
                    # the next candidate gets its chance
                    log.info("block %s: decode at packed_len=%d failed "
                             "verification", hash32[:4].hex(), packed_len)
                    continue
        if gathered_any:
            raise CorruptData(hash32)
        raise MissingBlock(hash32)

    def _packed_fill(self, hash32: bytes, packed) -> None:
        """Admit freshly decoded+verified packed bytes into the packed
        tier segment: locally when this node is the hash's ring owner
        (or routing is moot), else a bounded background push to the
        owner — same one-copy-per-ring discipline as the plain segment.
        Only reachable from fill_packed=True paths, which only cacheable
        reads set (the SSE-C audit boundary)."""
        pc = getattr(self, "packed_cache", None)
        if pc is None:
            return
        tier = getattr(self, "cache_tier", None)
        owner = tier.owner_of(hash32) if tier is not None else None
        if owner is not None:
            # fill_packed is only set by _read_store, which SSE-C reads
            # (cacheable=False) never enter
            tier.insert_at(owner, hash32, bytes(packed), kind="packed")
        elif pc.max_bytes > 0:
            pc.insert(hash32, bytes(packed))
            registry().inc("cache_packed_insert_local")

    async def packed_from_tier(self, hash32: bytes) -> Optional[bytes]:
        """Exact on-disk packed block bytes from the packed tier
        segment, or None — the rebuild/repair lane (resync's
        _rebuild_shard, repair's _repair_stripe). Local segment first;
        a REMOTE probe is hint-gated like resync's plain-tier fetches,
        so a rebalance wave over a million cold blocks never sprays a
        million wasted probes. Returned bytes were content-verified at
        admission (and re-verified by probe_packed for the remote
        case)."""
        pc = getattr(self, "packed_cache", None)
        packed = pc.get(hash32) if pc is not None else None
        if packed is not None:
            registry().inc("cache_packed_local_hit")
            return packed
        tier = getattr(self, "cache_tier", None)
        if tier is None or not tier.is_hot(hash32):
            return None
        owner = tier.owner_of(hash32)
        if owner is None:
            return None
        return await tier.probe_packed(owner, hash32)

    async def _decode_parts(self, parts: dict[int, bytes],
                            packed_len: int) -> bytes:
        """Stripe parts -> packed block bytes. The all-systematic case
        is a pure concat (codec.decode, no math, no queue hop); a
        DEGRADED set routes through the feeder's batched `decode` op,
        so concurrent degraded GETs — and scrub/resync rebuild waves —
        coalesce into one pattern-as-data device launch instead of one
        blocking host matmul per block on the event loop."""
        codec = self.codec
        idx = tuple(sorted(parts.keys())[: codec.read_need])
        if len(parts) < codec.read_need:
            raise MissingBlock(b"")
        if all(i < codec.k for i in idx):
            return codec.decode(parts, packed_len)
        return await self.feeder.decode(idx, [parts[i] for i in idx],
                                        packed_len)

    async def _gather_parts(self, hash32: bytes, placement: list[bytes],
                            need: int):
        """Fetch parts concurrently until `need` distinct indices are in
        hand; over-request nothing (systematic shards first, then the
        rest on failure). -> (parts, packed_len candidates ranked by
        vote count majority first, per-index header packed_len) or
        None. The per-index map lets deep scrub see WHICH holder's
        header disagrees with the majority (header rot repair)."""
        me = self.system.id

        async def fetch(node, idx):
            try:
                if node == me:
                    # off the event loop: deep scrub drives MiB-scale
                    # local reads through here, and a cold-cache disk
                    # read would stall every foreground request
                    # (ADVICE r5)
                    raw = await asyncio.to_thread(
                        self.read_local_shard, hash32, idx)
                    if raw is None:
                        return None
                    # lint: ignore[GL10] shard crc is native-C microseconds; the flagged open/cc chain is the one-time kernel build, cached for the process lifetime
                    return unpack_shard(raw)
                # self.rpc.call (not endpoint.call): the helper records
                # per-peer health and applies the adaptive timeout, so
                # a hung holder stops costing the full flat timeout
                # once its p99 is known
                resp = await self.rpc.call(
                    self.endpoint, node,
                    {"op": "get", "hash": hash32, "part": idx},
                    PRIO_NORMAL, timeout=60.0,
                )
                if resp.get("data") is None:
                    return None
                return unpack_shard(resp["data"])
            except Exception as e:
                # local disk/unpack failures are a different signal
                # than a peer fetch failing; don't conflate them
                registry().inc("block_shard_fetch_errors",
                               source="local" if node == me else "remote")
                log.debug("shard fetch part=%d from %s failed: %s",
                          idx, node[:4].hex(), e)
                return None

        race = HedgedRace(self.rpc.health(), "block_get_shard")
        parts: dict[int, bytes] = {}
        lens_by_idx: dict[int, int] = {}
        order = list(enumerate(placement))  # systematic first by design
        i = 0

        def launch_next(hedged: bool = False):
            nonlocal i
            idx, node = order[i]
            i += 1
            race.launch(idx, fetch(node, idx), hedged)

        try:
            while len(parts) < need and (race.pending or i < len(order)):
                while i < len(order) \
                        and len(race.pending) < need - len(parts):
                    launch_next()
                if not race.pending:
                    break
                # when every in-flight shard fetch is past its holder's
                # observed p95, the hedge launches the next candidate
                # shard instead of waiting out a hung holder (exceeds
                # the need-len(parts) concurrency cap by design)
                done = await race.wait(
                    can_hedge=i < len(order),
                    launch_hedge=lambda: launch_next(hedged=True),
                    hedge_nodes=[placement[idx]
                                 for idx, _ in race.pending.values()])
                for idx, was_hedged, t in done:
                    r = t.result()
                    if r is not None:
                        parts[idx] = r[0]
                        lens_by_idx[idx] = r[1]
                        race.note_success(was_hedged)
        finally:
            # cancel stragglers (hedges included) on every exit path —
            # a client disconnect cancels this coroutine at the wait
            # above, and the in-flight MiB-scale fetches must not keep
            # running for nobody; fetch() swallows its own errors so
            # nothing logs
            race.cancel_pending()
        if len(parts) < need:
            return None
        lens = list(lens_by_idx.values())
        # MAJORITY packed_len, not last-arrival: the shard header's
        # length field is outside the shard checksum, so one rotted or
        # forged header must not poison the whole decode (deep-scrub
        # repair decodes candidate subsets against this value; the read
        # path would fail content verification and miss a recoverable
        # block). With <= m corrupt shards the majority is the truth —
        # but a vote can TIE, so every distinct value is returned ranked
        # by count (ties broken toward the larger length: truncating a
        # real block always fails verification, padding can succeed for
        # trailing-zero payloads) and callers that verify content try
        # them in order.
        ranked = sorted(set(lens),
                        key=lambda v: (-lens.count(v), -v))
        return parts, ranked, lens_by_idx

    # ==== refcount hooks (called from block_ref table trigger) ==========

    def block_incref(self, tx, hash32: bytes) -> None:
        if self.rc.block_incref(tx, hash32):
            tx.on_commit(lambda: self.resync.push_now(hash32))

    def block_decref(self, tx, hash32: bytes) -> None:
        if self.rc.block_decref(tx, hash32):
            def on_unreferenced():
                # the block just became deletable: drop its cached
                # payload now — a ghost must not pin RAM for gc_delay
                cache = getattr(self, "cache", None)
                if cache is not None:
                    cache.discard(hash32)
                pc = getattr(self, "packed_cache", None)
                if pc is not None:
                    pc.discard(hash32)
                self.resync.push_at(hash32, time.time() + self.rc.gc_delay)

            tx.on_commit(on_unreferenced)

    @property
    def _chaos_node(self) -> bytes:
        """Local node id for chaos fault scoping (bare test managers
        built via __new__ have no system)."""
        s = getattr(self, "system", None)
        return getattr(s, "id", b"") or b""

    # ==== local file store (ref: manager.rs:709-805) ====================

    def _find(self, hash32: bytes, suffixes) -> Optional[str]:
        for d in self.data_layout.candidate_dirs(hash32):
            for sfx in suffixes:
                p = os.path.join(d, hash32.hex() + sfx)
                if os.path.exists(p):
                    return p
        return None

    def _write_file(self, path: str, content: bytes) -> None:
        d = os.path.dirname(path)
        # lazy init: tests build bare managers via __new__
        made = getattr(self, "_made_dirs", None)
        if made is None:
            made = self._made_dirs = set()
        if d not in made:
            os.makedirs(d, exist_ok=True)
            if len(made) >= 65536:
                made.clear()
            made.add(d)
        # unique tmp per writer: two concurrent puts of the same
        # content-addressed file must not steal each other's tmp (the
        # reference serializes via hash-sharded mutexes, manager.rs:113;
        # here either rename winning is fine — the bytes are identical)
        tmp = path + f".tmp{next(_tmp_ctr)}"
        for attempt in range(2):
            try:
                with open(tmp, "wb") as f:
                    f.write(content)
                    if self.fsync:
                        f.flush()
                        os.fsync(f.fileno())
                break
            except FileNotFoundError:
                # cached dir vanished under us (quarantine/rebalance
                # pruning): recreate and retry once
                if attempt:
                    raise
                os.makedirs(d, exist_ok=True)
        os.replace(tmp, path)
        if self.fsync:
            dirfd = os.open(os.path.dirname(path), os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        self.metrics["bytes_written"] += len(content)

    def write_local(self, hash32: bytes, packed: bytes) -> None:
        """Store a whole packed DataBlock (1-byte scheme + payload)."""
        self.write_local_payload(hash32, packed[0],
                                 memoryview(packed)[1:])

    def write_local_payload(self, hash32: bytes, comp: int,
                            payload) -> None:
        """Store a whole block from (scheme, payload) — the zero-copy
        form the "put" RPC carries (the payload is never concat-copied
        behind a packed header byte)."""
        from .block import SUFFIX_OF

        suffix = SUFFIX_OF.get(comp)
        if suffix is None:
            raise CorruptData(hash32)
        if _chaos.ACTIVE is not None:
            # chaos seam (disk write): EIO or torn write
            payload = _chaos.ACTIVE.disk_write(self._chaos_node, hash32,
                                               payload)
        path = self.data_layout.block_path(hash32, suffix)
        self._write_file(path, payload)
        # drop other-compression variants if present (ref: manager.rs
        # write_block replaces regardless of compression state)
        for sfx in BLOCK_SUFFIXES:
            if sfx == suffix:
                continue
            other = self.data_layout.block_path(hash32, sfx)
            if os.path.exists(other):
                os.remove(other)

    def read_local(self, hash32: bytes) -> Optional[bytes]:
        """-> packed DataBlock bytes, verifying content hash
        (ref: manager.rs:554-609)."""
        p = self._find(hash32, BLOCK_SUFFIXES)
        if p is None:
            return None
        with open(p, "rb") as f:
            raw = f.read()
        if _chaos.ACTIVE is not None:
            # chaos seam (disk read): EIO or single-bit rot, scoped by
            # local node id + hash prefix; rot is caught by the content
            # verify below exactly like real media decay would be
            raw = _chaos.ACTIVE.disk_read(self._chaos_node, hash32, raw)
        self.metrics["bytes_read"] += len(raw)
        blk = DataBlock(comp_of_path(p), raw)
        try:
            blk.verify(hash32)
        except CorruptData:
            self._quarantine(p, hash32)
            return None
        return blk.pack()

    def write_local_shard(self, hash32: bytes, part: int, raw: bytes) -> None:
        validate_shard(raw)  # checksum before storing (no payload copy)
        if _chaos.ACTIVE is not None:
            # chaos seam (disk write), after validation: a torn image
            # lands on disk and the next read's checksum catches it
            raw = _chaos.ACTIVE.disk_write(self._chaos_node, hash32, raw)
        self._write_file(self.data_layout.block_path(hash32, f".s{part}"), raw)

    def read_local_shard(self, hash32: bytes, part: int) -> Optional[bytes]:
        p = self._find(hash32, [f".s{part}"])
        if p is None:
            return None
        with open(p, "rb") as f:
            raw = f.read()
        if _chaos.ACTIVE is not None:
            # chaos seam (disk read): a rotted shard fails the checksum
            # check below -> quarantine + resync, and the erasure read
            # falls through to the remaining shards (degraded decode)
            raw = _chaos.ACTIVE.disk_read(self._chaos_node, hash32, raw)
        self.metrics["bytes_read"] += len(raw)
        try:
            unpack_shard(raw)
        except CorruptData:
            self._quarantine(p, hash32)
            return None
        return raw

    def local_parts(self, hash32: bytes) -> list[int]:
        """Shard indices stored here."""
        out = []
        for d in self.data_layout.candidate_dirs(hash32):
            if not os.path.isdir(d):
                continue
            pre = hash32.hex() + ".s"
            for fn in os.listdir(d):
                if fn.startswith(pre) and ".tmp" not in fn \
                        and not fn.endswith(".corrupted"):
                    try:
                        out.append(int(fn[len(pre):]))
                    except ValueError:
                        pass
        return sorted(set(out))

    def has_local(self, hash32: bytes) -> bool:
        if self.erasure:
            return bool(self.local_parts(hash32))
        return self._find(hash32, BLOCK_SUFFIXES) is not None

    def is_shard_needed(self, hash32: bytes) -> bool:
        """Answer to the 'need' RPC: does this node still want data for
        this block? In erasure mode, needed = rc-referenced AND our
        layout-assigned shard index is missing (holding some *other*
        stale shard doesn't satisfy the assignment)."""
        if not self.rc.is_needed(hash32):
            return False
        if not self.erasure:
            return not self.has_local(hash32)
        placement = shard_nodes_of(self.system.layout_helper.current(),
                                   hash32, self.codec.width)
        me = self.system.id
        if me not in placement:
            return False
        return placement.index(me) not in self.local_parts(hash32)

    def delete_local(self, hash32: bytes) -> None:
        cache = getattr(self, "cache", None)
        if cache is not None:
            cache.discard(hash32)
        pc = getattr(self, "packed_cache", None)
        if pc is not None:
            pc.discard(hash32)
        for d in self.data_layout.candidate_dirs(hash32):
            if not os.path.isdir(d):
                continue
            for fn in os.listdir(d):
                if not fn.startswith(hash32.hex()) \
                        or fn.endswith(".corrupted"):
                    continue
                if ".tmp" in fn:
                    # in-flight write (writer renames tmp -> final):
                    # since ISSUE 9 delete_local and write_local run in
                    # worker threads, so the listdir can catch a tmp
                    # that the writer renames before our remove lands;
                    # abandoned tmps are sweep_stale_tmp's job
                    continue
                try:
                    os.remove(os.path.join(d, fn))
                except FileNotFoundError:
                    pass  # lost the race to a concurrent delete/rename

    def _quarantine(self, path: str, hash32: bytes) -> None:
        """Corrupted file: move aside + queue resync
        (ref: manager.rs:586-601)."""
        log.warning("corrupted block file %s", path)
        self.metrics["corruptions"] += 1
        try:
            os.replace(path, path + ".corrupted")
        except OSError:
            pass
        self.resync.push_now(hash32)

    def sweep_stale_tmp(self, root: str, files: list[str]) -> None:
        """Delete .tmpN orphans older than _TMP_MAX_AGE (a writer that
        crashed between open and rename leaves one; unique tmp names
        mean nothing else ever reclaims it). Called from the walking
        iterators so the scrub pass doubles as the janitor."""
        now = time.time()
        for fn in files:
            if ".tmp" not in fn:
                continue
            p = os.path.join(root, fn)
            try:
                if now - os.stat(p).st_mtime > _TMP_MAX_AGE:
                    os.remove(p)
            except OSError:
                pass

    def iter_local_blocks(self, parts: Optional[set] = None):
        """Yield (hash32, path) for every stored block/shard file.
        `parts` restricts the walk to those partitions (h[0] values —
        PARTITION_BITS is 8, so partition == first hash byte): the
        on-disk layout keys the first directory level by h[0].hex(),
        so pruning there skips whole subtrees instead of stat()ing
        every file in the store (the rebalance enumerator's
        moved-partition scoping)."""
        seen = set()
        for d in self.data_layout.dirs:
            for root, dirs, files in os.walk(d.path):
                if parts is not None and root == d.path:
                    dirs[:] = [x for x in dirs
                               if len(x) == 2 and _hex_in(x, parts)]
                self.sweep_stale_tmp(root, files)
                for fn in files:
                    if ".tmp" in fn or fn.endswith(".corrupted"):
                        continue
                    hexpart = fn.split(".")[0]
                    try:
                        h = bytes.fromhex(hexpart)
                    except ValueError:
                        continue
                    if len(h) == 32 and h not in seen:
                        if parts is not None and h[0] not in parts:
                            continue
                        seen.add(h)
                        yield h, os.path.join(root, fn)

    def iter_local_blocks_sorted(self, start: bytes = b""):
        """Yield distinct hash32 in ascending hash order, resuming after
        `start`. One pass over the tree: the on-disk layout is keyed by
        hash prefix ({h[0]}/{h[1]}/{hex}), so walking the two prefix
        levels in sorted order gives global hash order without holding
        the whole listing in memory (scrub cursor resume, ref
        repair.rs:169-232 BlockStoreIterator)."""
        roots = [d.path for d in self.data_layout.dirs]
        # discover which prefix dirs actually exist (a sparse store has
        # few) instead of probing all 65,536 combinations
        lvl1_of: dict[str, list[str]] = {}
        for r in roots:
            try:
                l1s = os.listdir(r)
            except OSError:
                continue
            for l1 in l1s:
                if len(l1) == 2:
                    lvl1_of.setdefault(l1, []).append(r)
        start_l1 = start[:1].hex() if start else ""
        start_l2 = start[1:2].hex() if len(start) >= 2 else ""
        for lvl1 in sorted(lvl1_of):
            if lvl1 < start_l1:
                continue
            lvl2s: dict[str, list[str]] = {}
            for r in lvl1_of[lvl1]:
                try:
                    l2s = os.listdir(os.path.join(r, lvl1))
                except OSError:
                    continue
                for l2 in l2s:
                    if len(l2) == 2:
                        lvl2s.setdefault(l2, []).append(r)
            for lvl2 in sorted(lvl2s):
                if lvl1 == start_l1 and lvl2 < start_l2:
                    continue
                names = set()
                for r in lvl2s[lvl2]:
                    d = os.path.join(r, lvl1, lvl2)
                    try:
                        ls = os.listdir(d)
                    except OSError:
                        continue
                    self.sweep_stale_tmp(d, ls)
                    names.update(ls)
                hashes = set()
                for fn in names:
                    if ".tmp" in fn or fn.endswith(".corrupted"):
                        continue
                    try:
                        h = bytes.fromhex(fn.split(".")[0])
                    except ValueError:
                        continue
                    if len(h) == 32 and h > start:
                        hashes.add(h)
                yield from sorted(hashes)

    # ==== server side ===================================================

    async def _handle(self, from_node: bytes, payload, stream):
        op = payload["op"]
        h = payload.get("hash", b"")
        if op == "put":
            part = payload.get("part")
            if part is None:
                comp = payload.get("comp")
                if comp is not None:
                    await asyncio.to_thread(self.write_local_payload, h,
                                            comp, payload["data"])
                else:  # legacy packed form (resync push path)
                    await asyncio.to_thread(self.write_local, h,
                                            payload["data"])
            else:
                data = payload["data"]
                if self.fsync or len(data) > (512 << 10):
                    await asyncio.to_thread(self.write_local_shard, h,
                                            part, data)
                else:
                    # a ~256 KiB tmpfs/page-cache write costs less than
                    # the thread handoff it would ride; six shards per
                    # block made the hops a measured top cost
                    # lint: ignore[GL10] measured: small no-fsync shard writes cost less than the to_thread handoff (the fsync/large branch above does hop)
                    self.write_local_shard(h, part, data)
            return {"ok": True}
        if op == "get":
            part = payload.get("part")
            if part is None:
                data = await asyncio.to_thread(self.read_local, h)
            else:
                data = await asyncio.to_thread(self.read_local_shard, h, part)
            return {"data": data}
        if op == "need":
            needed = await asyncio.to_thread(self.is_shard_needed, h)
            return {"needed": needed}
        if op == "cache_probe":
            # cluster cache tier (ISSUE 15): read-only, single-hop,
            # RAM-only — a miss answers None and NEVER falls through to
            # the store (the prober's local path is the fallback, so a
            # probe can't chain or amplify). Hedge-safe by construction:
            # re-asking an idempotent RAM lookup is free (a re-asked
            # lease grant re-mints or re-parks, both idempotent too).
            # ISSUE 18: `kinds` selects the segments (plain/packed);
            # `wait_ms`+`lease` engage the singleflight protocol — a
            # miss behind a live lease PARKS here (inside the caller's
            # flat probe timeout, clamped again server-side), a bare
            # miss with lease=True mints one for the caller.
            kinds = payload.get("kinds") or ("plain",)
            data, kind = self._tier_lookup(h, kinds)
            tier = getattr(self, "cache_tier", None)
            if data is None and tier is not None and "plain" in kinds:
                wait_ms = min(float(payload.get("wait_ms") or 0.0),
                              tier.probe_wait_ms())
                if wait_ms > 0 and tier.leases.live(h):
                    await tier.leases.wait(h, wait_ms / 1000.0)
                    data, kind = self._tier_lookup(h, kinds)
                    if data is None:
                        registry().inc("cache_tier_serve_miss")
                        return {"data": None, "waited": True}
                    registry().inc("cache_tier_serve_hit")
                    return {"data": data, "kind": kind,
                            "waited": True}
                if wait_ms > 0 and payload.get("lease") \
                        and self.cache.max_bytes > 0 \
                        and tier.leases.mint(h, from_node):
                    registry().inc("cache_tier_serve_miss")
                    return {"data": None, "lease": True}
            if data is not None:
                registry().inc("cache_tier_serve_hit")
            else:
                registry().inc("cache_tier_serve_miss")
            return {"data": data, "kind": kind}
        if op == "cache_insert":
            # write-through from a non-owner's miss-decode. Content-
            # verified before admission: a content-addressed cache must
            # never hold bytes that don't hash to their key, or every
            # future probe hit serves corruption with a straight face.
            data = payload["data"]
            if payload.get("kind", "plain") == "packed":
                # packed segment (ISSUE 18): verification = unpack +
                # content verify — the address covers the plain bytes,
                # so a successful unpack-verify proves the packed image
                pc = getattr(self, "packed_cache", None)
                if pc is None or pc.max_bytes <= 0:
                    return {"ok": False}

                def check_packed() -> None:
                    DataBlock.unpack(data).verify(h)

                try:
                    await asyncio.to_thread(check_packed)
                except Exception:
                    registry().inc("cache_tier_insert_corrupt")
                    log.warning("packed tier insert of %s from %s "
                                "failed verification; dropped",
                                h[:4].hex(), from_node[:4].hex())
                    return {"ok": False}
                pc.insert(h, data)
                registry().inc("cache_tier_insert_served")
                return {"ok": True}
            cache = getattr(self, "cache", None)
            if cache is None or cache.max_bytes <= 0:
                return {"ok": False}
            from ..utils.data import content_hash_matches

            if not await asyncio.to_thread(content_hash_matches,
                                           data, h):
                registry().inc("cache_tier_insert_corrupt")
                log.warning("tier insert of %s from %s failed content "
                            "verification; dropped", h[:4].hex(),
                            from_node[:4].hex())
                return {"ok": False}
            cache.insert(h, data)
            tier = getattr(self, "cache_tier", None)
            if tier is not None:
                # the lease holder's bytes just landed: wake every
                # prober parked on this hash (no-op without a lease)
                tier.leases.resolve(h)
            registry().inc("cache_tier_insert_served")
            return {"ok": True}
        raise RpcError(f"unknown block op {op!r}")

    def _tier_lookup(self, h: bytes, kinds):
        """RAM-only lookup across the requested tier segments, plain
        preferred (a GET wants the decoded payload; packed costs the
        prober an unpack). -> (data, kind) or (None, None)."""
        if "plain" in kinds:
            cache = getattr(self, "cache", None)
            data = cache.get(h) if cache is not None else None
            if data is not None:
                return data, "plain"
        if "packed" in kinds:
            pc = getattr(self, "packed_cache", None)
            data = pc.get(h) if pc is not None else None
            if data is not None:
                return data, "packed"
        return None, None
