"""DeviceFeeder: batches block math from concurrent requests onto the TPU.

The reference does its per-block CPU work (hashing, compression) inline
in each request task (src/api/s3/put.rs:413-477 spawn_blocking, one
block at a time). A TPU earns its keep only on *batches* — so the data
path here funnels every block-math request (content hash, RS encode,
scrub verify) through one bounded queue. A single dispatcher drains
whatever has accumulated, groups it by operation and shape, and issues
one batched JAX call per group (ops/treehash.hash_batch_jax,
ops/rs.encode). Under load, concurrent PUTs coalesce into MXU-sized
batches for free; when idle, single requests take the native C path
(garage_tpu/native) which beats a device round-trip for one block.

Backend selection: the `axon` remote-TPU backend can hang indefinitely
on init when the tunnel is down (observed: jax.devices() blocked >500 s)
— so device use is gated behind a subprocess probe with a timeout,
cached in /tmp. Until the probe succeeds, everything runs host-side;
the data path never blocks on a dead tunnel.

Once the device is up, the feeder CALIBRATES rather than assumes: it
tracks observed bytes/s per (op, backend) and routes each batch to the
faster one, re-probing the loser periodically. On a real TPU host
(PCIe/DMA) the batched device path wins by an order of magnitude; on a
tunneled dev chip where host<->device moves at tens of MB/s the native C
kernels win — measured, not guessed (a fixed threshold was wrong on both
ends: this box's tunnel does ~300 MB/s h2d but ~15 MB/s d2h).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

import numpy as np

log = logging.getLogger("garage_tpu.block.feeder")

# a (possibly remote) device round trip only pays above these sizes
_DEVICE_MIN_BYTES = 4 << 20
_DEVICE_MIN_ITEMS = 4
# re-try the losing backend at most this often (wall clock) so a
# recovered tunnel (or a warmed-up XLA program) gets re-discovered.
# Time-based, not count-based: on a slow tunnel one exploration batch
# can cost ~0.5 s, so a per-N-calls rule taxed busy traffic heavily
# while an idle server never re-probed at all.
_EXPLORE_SECS = 60.0
# exploration trials of the LOSING backend are capped: over a ~2 MB/s
# tunnel a full 8x1 MiB production batch costs seconds, and the
# re-probe only needs one timing sample, not the whole batch. The cap
# is byte-aware — at least 2 items, growing to 8 while the slice is
# under _TRIAL_MAX_BYTES — so a trial of small blobs still amortizes a
# recovered backend's fixed round-trip latency instead of permanently
# under-measuring it. The rest of the batch runs on the winner.
_TRIAL_MAX_ITEMS = 2
_TRIAL_ITEMS_CAP = 8
_TRIAL_MAX_BYTES = 4 << 20
# a batch stuck longer than this means the device backend hung (the
# axon tunnel can block inside XLA calls); the batch re-runs host-side
# and the device path is disabled
_BATCH_TIMEOUT = 300.0

PROBE_TIMEOUT = 60.0


def _probe_cache_path() -> str:
    # per-uid (a shared /tmp name would let another local user pin the
    # verdict for everyone) AND per-platform-pin: a JAX_PLATFORMS=cpu
    # test process probing "cpu" must not poison the cache consulted by
    # an unpinned server on the same box
    uid = os.getuid() if hasattr(os, "getuid") else 0
    pin = os.environ.get("JAX_PLATFORMS", "auto") or "auto"
    pin = "".join(c if c.isalnum() else "_" for c in pin)[:16]
    return os.path.join(tempfile.gettempdir(),
                        f"garage_tpu_device_probe.{uid}.{pin}.json")


_PROBE_TTL = 600.0

_probe_lock = threading.Lock()
_probe_result: Optional[dict] = None


def _read_probe_cache() -> Optional[dict]:
    """Disk-cached verdict if fresh, else None. Caller holds no lock."""
    try:
        with open(_probe_cache_path()) as f:
            cached = json.load(f)
        age = time.time() - cached.get("at", 0)
        if 0 <= age < _PROBE_TTL:  # reject future timestamps
            return cached
    except Exception:
        # lint: ignore[GL05] stale/corrupt probe cache is the same as no cache
        pass
    return None


def _write_probe_cache(res: dict) -> None:
    try:
        cache = _probe_cache_path()
        with open(cache + ".tmp", "w") as f:
            json.dump(res, f)
        os.replace(cache + ".tmp", cache)
    except OSError:
        pass


def probe_device(timeout: float = PROBE_TIMEOUT, force: bool = False) -> dict:
    """Subprocess-probe the default JAX backend. Returns
    {"ok": bool, "platform": str, "error": str}. Cached in-process and in
    /tmp (TTL 10 min) so a dead tunnel costs one timeout, not one per
    worker."""
    global _probe_result
    with _probe_lock:
        if _probe_result is not None and not force:
            return _probe_result
        if not force:
            cached = _read_probe_cache()
            if cached is not None:
                _probe_result = cached
                return cached
        res = {"ok": False, "platform": "cpu", "error": "", "at": time.time()}
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=timeout, capture_output=True, text=True,
            )
            if r.returncode == 0:
                plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "cpu"
                res["platform"] = plat
                res["ok"] = plat not in ("cpu",)
            else:
                res["error"] = (r.stderr or "")[-500:]
        except subprocess.TimeoutExpired:
            res["error"] = f"jax.devices() did not return within {timeout}s"
        except OSError as e:
            res["error"] = str(e)
        if force and res["ok"]:
            cached = _read_probe_cache()
            if cached is not None and cached.get("hung"):
                # the poison marks a device that ANSWERS probes but hangs
                # on real work — this probe-only success proves nothing
                # new, so the forced caller gets its result while the
                # shared verdict stays poisoned until the TTL expires
                return res
        _probe_result = res
        _write_probe_cache(res)
        return res


def poison_probe_cache(error: str) -> None:
    """Record a negative device verdict (in-process + /tmp TTL cache)
    with the `hung` marker. Used when the device answered the probe but
    then HUNG in real work (calibration/batch): without this every
    co-located feeder re-reads the stale positive probe and pays the
    full watchdog timeout itself. mode="require" still force-re-probes
    and proceeds on its own result, but a probe-only success does NOT
    clear the hung marker for auto feeders (only the TTL does).

    May block up to a probe timeout on _probe_lock — call from a worker
    thread, never the event loop."""
    global _probe_result
    res = {"ok": False, "platform": "cpu", "error": error,
           "at": time.time(), "hung": True}
    with _probe_lock:
        _probe_result = res
        _write_probe_cache(res)


def _verify_matches(digs: list, items: list) -> list[bool]:
    """Per-item content-hash verdicts; one copy of the match rule
    (digest equality, legacy-algo fallback) for the inline fast path
    and the batch-queue path alike."""
    from ..utils.data import content_hash_matches

    return [dg == h or content_hash_matches(d, h)
            for dg, (h, d) in zip(digs, items)]


class _Item:
    __slots__ = ("op", "data", "future", "extra")

    def __init__(self, op: str, data, future, extra=None):
        self.op = op
        self.data = data
        self.future = future
        self.extra = extra


class DeviceFeeder:
    """One per BlockManager. mode: "auto" (probe, then use device when
    batches are big enough), "off" (host only), "require" (device always;
    raise if probe fails — bench/test use)."""

    def __init__(self, codec=None, mode: str = "auto",
                 max_batch: int = 256):
        self.codec = codec
        # greedy-drain cap: blocks per device batch ([tpu] batch_blocks)
        self.max_batch = max(1, int(max_batch))
        env_mode = os.environ.get("GARAGE_TPU_DEVICE")
        if mode == "auto" and env_mode == "off":
            # test/CI kill-switch: never probe, never spawn calibration
            # threads (a probed tunnel leaves C++ threads that abort on
            # interpreter teardown — the r3 rc=134)
            mode = "off"
        elif mode == "auto" and env_mode == "require":
            # bench override: force every batch through the device even
            # where auto-calibration would route to the host (the live
            # S3-path device proof, bench.py bench_s3_put(device=True))
            mode = "require"
        self.mode = mode
        self._q: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        self._require_lock: Optional[asyncio.Lock] = None
        self._require_err: Optional[tuple[float, str]] = None
        self._device_ok: Optional[bool] = None
        self._probing = False
        self._calibrating = False
        self.stats = {"batches": 0, "items": 0, "device_batches": 0,
                      "device_items": 0, "inline_items": 0, "max_batch": 0}
        # PUT streams currently inside read_and_put_blocks: sizes the
        # hash_md5 gather window (one block hash in flight per stream)
        self.active_streams = 0
        # calibration: (op, backend) -> [bytes, seconds]; routing picks
        # the backend with the best observed bytes/s, exploring the
        # other every _EXPLORE_EVERY batches
        self._perf: dict[tuple[str, str], list[float]] = {}
        self._perf_lock = threading.Lock()  # inline (loop) vs worker thread
        self._last_explore: dict[str, float] = {}
        self._force_device: dict[str, bool] = {}

    def perf_summary(self) -> dict[str, float]:
        """Observed MB/s per (op, backend) — /metrics + bench surface."""
        with self._perf_lock:
            return {f"{op}/{be}": round(b / t / 1e6, 1)
                    for (op, be), (b, t) in self._perf.items() if t > 0}

    def _rates(self, op: str):
        """(device_rate|None, host_rate|None) under the lock — readers
        on the loop thread race _record in the worker thread."""
        with self._perf_lock:
            dev = self._perf.get((op, "device"))
            host = self._perf.get((op, "host"))
            return (dev[0] / dev[1] if dev else None,
                    host[0] / host[1] if host else None)

    # ---- lifecycle ----------------------------------------------------

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._q = asyncio.Queue()
            self._task = asyncio.create_task(self._run(), name="device-feeder")
        if self.mode == "off":
            self._device_ok = False

    async def _require_probe(self) -> None:
        """Resolve the device verdict for mode="require" WITHOUT
        blocking the event loop: the probe is a jax subprocess that can
        take ~2 min cold, and running it inline wedged every other
        connection past its client timeout (first r5 live capture)."""
        if self._require_lock is None:
            self._require_lock = asyncio.Lock()
        async with self._require_lock:
            if self._device_ok is not None:
                return
            if self._require_err is not None:
                # fail fast on a recent verdict: without this, every
                # request on a dead tunnel pays the full forced-probe
                # chain while serialized behind this lock. TTL must
                # exceed that chain's cost (up to 4 × PROBE_TIMEOUT)
                # or steady traffic spends most wall time re-probing.
                ts, msg = self._require_err
                if time.monotonic() - ts < 5 * PROBE_TIMEOUT:
                    raise RuntimeError(msg)
                self._require_err = None
            res = await asyncio.to_thread(probe_device)
            if not res["ok"]:
                # A negative verdict may be a stale cache entry or a
                # transient co-tenant fallback (unpinned jax discovery
                # degrades to cpu under load); "require" exists for
                # proof runs, so pay one forced re-probe before
                # failing — with a longer leash, since a congested
                # tunnel can hold jax.devices() past the default.
                res = await asyncio.to_thread(
                    probe_device, 3 * PROBE_TIMEOUT, True)
            if not res["ok"]:
                msg = (f"device required but probe failed: "
                       f"{res['error'] or res['platform']}")
                self._require_err = (time.monotonic(), msg)
                raise RuntimeError(msg)
            self._device_ok = True

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        # fail anything still queued so no caller awaits forever
        if self._q is not None:
            while not self._q.empty():
                item = self._q.get_nowait()
                if not item.future.done():
                    item.future.set_exception(RuntimeError("feeder stopped"))

    def _maybe_start_probe(self) -> None:
        """Kick the backend probe in a thread; host path until it lands."""
        if self._device_ok is not None or self._probing or self.mode != "auto":
            return
        self._probing = True
        self._calibrating = True

        def run():
            try:
                res = probe_device()
                ok = bool(res["ok"])
                if ok:
                    log.info("device data plane active: %s", res["platform"])
                    # seed BOTH backends' throughput samples with
                    # synthetic batches OFF the request path, so the
                    # first production batch is routed on data instead
                    # of paying a cold device trial inline. The device
                    # calls run in a nested watchdog thread: a hung
                    # tunnel (the failure mode _BATCH_TIMEOUT guards on
                    # the batch path) disables the device; a transient
                    # error merely penalizes it so _EXPLORE_EVERY can
                    # re-discover a recovered device later.
                    cal = threading.Thread(target=self._calibrate,
                                           daemon=True,
                                           name="feeder-calibrate")
                    cal.start()
                    cal.join(_BATCH_TIMEOUT)
                    if cal.is_alive():
                        log.error("device calibration stuck >%ss; "
                                  "disabling device path", _BATCH_TIMEOUT)
                        ok = False
                        poison_probe_cache(
                            f"calibration stuck >{_BATCH_TIMEOUT}s "
                            "(device answered probe, hung on work)")
                elif res["error"]:
                    log.info("device probe failed, host data plane: %s",
                             res["error"])
                self._device_ok = ok
            finally:
                self._calibrating = False
                self._probing = False

        threading.Thread(target=run, daemon=True,
                         name="feeder-probe").start()

    def _calibrate(self) -> None:
        from ..utils import data as _data

        blob = bytes(np.random.default_rng(0).integers(
            0, 256, 1 << 20, dtype=np.uint8))
        batch = [blob] * 4
        for backend in ("host", "device"):
            try:
                # blake2 hashing never runs on device — recording a
                # host timing under the device key would fabricate a
                # backend that never ran
                if _data._content_algo == "blake3" or backend == "host":
                    t0 = time.perf_counter()
                    self._do_hash(batch, backend)
                    self._record("hash", backend, len(batch) << 20,
                                 time.perf_counter() - t0)
                if self.codec is not None:
                    t0 = time.perf_counter()
                    self._do_encode(batch, backend)
                    self._record("encode", backend, len(batch) << 20,
                                 time.perf_counter() - t0)
            except Exception as e:
                # a host-leg failure must not kill the thread silently
                # (the device leg would then never run and the first
                # production batch would pay the cold device trial the
                # calibration exists to avoid)
                log.warning("%s calibration leg failed (%s: %s)",
                            backend, type(e).__name__, e)
                if backend == "device":
                    self._record("hash", "device", 0, 60.0)
                    self._record("encode", "device", 0, 60.0)
        log.info("feeder calibration: %s", self.perf_summary())

    # ---- public async ops ---------------------------------------------

    async def _submit(self, op: str, data, extra=None):
        self._ensure_started()
        if self.mode == "require" and self._device_ok is None:
            await self._require_probe()
            # stop() may have torn down the dispatcher while we sat in
            # the (multi-minute) probe; restart it or the enqueued item
            # below would await a future nothing ever resolves
            self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        await self._q.put(_Item(op, data, fut, extra))
        return await fut

    async def hash(self, data: bytes) -> bytes:
        """Content hash of one block (batched with concurrent callers)."""
        if self._host_inline_ok("hash"):
            from ..utils import data as _data

            if _data._content_algo == "blake3":
                from .. import native

                self.stats["inline_items"] += 1
                t0 = time.perf_counter()
                # lint: ignore[GL10] host-inline fast path is gated to small items; the flagged open chain is the one-time native build, cached for the process lifetime
                out = native.blake3_many([data])[0]
                self._record("hash", "host", len(data),
                             time.perf_counter() - t0)
                return out
        return await self._submit("hash", data)

    async def hash_with_md5(self, data: bytes, md5acc) -> bytes:
        """Content hash + S3-ETag MD5 advance for one block. Rides the
        feeder queue so blocks from CONCURRENT requests form one batch:
        MD5 is a strict serial chain within an object but independent
        across objects, and the native kernel runs up to 8 chains in
        AVX2 lockstep (measured: 0.48 GB/s single -> 2.4 GB/s at 8
        lanes). Host route fuses blake3 into the same call; device
        route batch-advances the MD5s host-side while the content hash
        batches to the accelerator (a serial chain can't ride the
        tree-structured device path)."""
        if getattr(md5acc, "fused", False):
            from ..utils import data as _data

            if _data._content_algo == "blake3":
                if self.active_streams <= 1 \
                        and self._host_inline_ok("hash"):
                    # lone stream: no lanes to gather — the inline
                    # one-pass interleaved kernel beats the queue hop
                    # plus a 1-lane batch
                    self.stats["inline_items"] += 1
                    t0 = time.perf_counter()
                    out = md5acc.update_with_blake3(data)
                    self._record("hash", "host", len(data),
                                 time.perf_counter() - t0)
                    return out
                return await self._submit("hash_md5", (md5acc, data))
        # non-native fallback: hashlib md5 + separate content hash
        if (os.cpu_count() or 1) > 1 and len(data) >= 65536:
            out, _ = await asyncio.gather(
                self.hash(data), asyncio.to_thread(md5acc.update, data))
            return out
        md5acc.update(data)
        return await self.hash(data)


    async def encode(self, packed: bytes) -> list[bytes]:
        """Erasure parts for one packed block (batched)."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        return await self._submit("encode", packed)

    def _host_inline_ok(self, op: str) -> bool:
        """True when the queue+thread hop is pure overhead: the route is
        host anyway and the native kernel (which releases the GIL) can
        run inline on the event loop. The queue path exists to build
        device batches; paying two thread handoffs per item to then run
        host-side was a top cost in the r3 kernel-vs-system gap."""
        from .. import native

        if not native.loaded():
            return False
        if self.mode == "off":
            return True
        if self.mode == "require" or self._device_ok is None:
            return False  # device mandatory / probe still undecided
        if self._device_ok is False:
            return True
        dev_rate, host_rate = self._rates(op)
        if dev_rate is not None and host_rate is not None \
                and dev_rate < host_rate:
            # host is winning on data; still send an occasional call
            # through the queue WITH a forced device trial so a
            # recovered device gets re-discovered
            if self._explore_due(op):
                self._force_device[op] = True
                return False
            return True
        return False

    def _explore_due(self, op: str) -> bool:
        now = time.monotonic()
        if op not in self._last_explore:
            # calibration just measured both backends — the clock starts
            # there, not at zero (else the first production batch pays a
            # pointless trial on the known-slow backend)
            self._last_explore[op] = now
            return False
        # adaptive interval: the wider the measured gap, the rarer the
        # re-probe. A backend losing 8x gets the base 60 s cadence; a
        # tunnel-attached device losing 500x gets probed ~hourly — one
        # trial there costs real seconds of live traffic, and a gap that
        # wide doesn't close without a topology change anyway.
        dev, host = self._rates(op)
        interval = _EXPLORE_SECS
        if dev is not None and host is not None:
            # a 0.0 rate (every byte of that backend's window failed)
            # is the WIDEST gap, not missing data: cap straight to 64x
            if min(dev, host) <= 0.0:
                interval *= 64.0
            else:
                ratio = max(dev, host) / min(dev, host)
                interval *= min(64.0, max(1.0, ratio / 8.0))
        if now - self._last_explore[op] >= interval:
            self._last_explore[op] = now
            return True
        return False

    async def encode_put(self, data: bytes, prefix: bytes = b"") -> list:
        """Erasure parts for one packed block (logical stream
        prefix||data), each framed as a ready-to-send shard payload
        (pack_shard format). The host path is ONE GIL-released native
        call per block (split + parity + crc + headers fused:
        native.rs_encode_packed); the device path batches the parity
        matmul through XLA then packs host-side."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        if self._host_inline_ok("encode"):
            from .. import native
            from ..ops import rs

            self.stats["inline_items"] += 1
            t0 = time.perf_counter()
            # lint: ignore[GL10] host-inline fast path is gated to small items; the flagged open chain is the one-time native build, cached for the process lifetime
            out = native.rs_encode_packed(
                data, self.codec.k, self.codec.m,
                rs.parity_matrix(self.codec.k, self.codec.m), prefix=prefix)
            self._record("encode", "host", len(prefix) + len(data),
                         time.perf_counter() - t0)
            return out
        return await self._submit("encode_put", (prefix, data))

    async def verify_blocks(self, items: list[tuple[bytes, bytes]]
                            ) -> list[bool]:
        """[(hash32, plain)] -> per-item content-hash match (scrub)."""
        if not items:
            return []
        if self._host_inline_ok("hash"):
            from ..utils import data as _data

            if _data._content_algo == "blake3":
                from .. import native

                self.stats["inline_items"] += len(items)
                t0 = time.perf_counter()
                # already batched -> one thread handoff is amortized;
                # running it inline would park the event loop for the
                # whole multi-MiB native call, every scrub batch
                digs = await asyncio.to_thread(
                    native.blake3_many, [d for _, d in items])
                self._record("hash", "host", sum(len(d) for _, d in items),
                             time.perf_counter() - t0)
                return _verify_matches(digs, items)
        futs = [self._submit("verify", (h, d)) for h, d in items]
        return list(await asyncio.gather(*futs))

    async def parity_check(self, stripes: list[list[bytes]]) -> list[bool]:
        """Scrub deep pass: per-stripe cross-shard consistency. Each
        stripe is the full [k data + m parity] shard payload list
        (equal lengths within one stripe). True = the stored parity
        rows equal parity re-derived from the data rows — any single
        corrupt shard flips every parity row (ops/rs.parity_check on
        the device route; native GF matmul compare on the host
        route)."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        if not stripes:
            return []
        if self._host_inline_ok("parity"):
            # already batched; one thread handoff amortized over the
            # whole multi-MiB native call (same shape as verify_blocks)
            self.stats["inline_items"] += len(stripes)
            t0 = time.perf_counter()
            out = await asyncio.to_thread(self._do_parity_check, stripes,
                                          "host")
            self._record("parity", "host",
                         sum(len(b) for s in stripes for b in s),
                         time.perf_counter() - t0)
            return out
        futs = [self._submit("parity_check", s) for s in stripes]
        return list(await asyncio.gather(*futs))

    # ---- dispatcher ----------------------------------------------------

    async def _run(self) -> None:
        while True:
            first = await self._q.get()
            batch = [first]
            try:
                # greedy non-waiting drain: whatever queued while the
                # last batch was on the device becomes the next batch
                while not self._q.empty() \
                        and len(batch) < self.max_batch:
                    batch.append(self._q.get_nowait())
                n_md5 = sum(1 for it in batch if it.op == "hash_md5")
                want = min(self.active_streams, 8)
                if first.op == "hash_md5" and self.active_streams > 1 \
                        and n_md5 < want:
                    # several fused PUT streams are mid-block-loop: a
                    # short async gather window lets their next hash
                    # submissions line up, multiplying the MD5 lane
                    # count. The wait burns no CPU — the event loop
                    # spends it reading the OTHER streams' sockets,
                    # which is exactly what gets them here. Only
                    # hash_md5 items count toward the lane target.
                    loop = asyncio.get_running_loop()
                    deadline = loop.time() + 0.006
                    while n_md5 < want:
                        left = deadline - loop.time()
                        if left <= 0:
                            break
                        try:
                            item = await asyncio.wait_for(
                                self._q.get(), left)
                        except asyncio.TimeoutError:
                            break
                        batch.append(item)
                        if item.op == "hash_md5":
                            n_md5 += 1
                self._maybe_start_probe()
                try:
                    results = await asyncio.wait_for(
                        asyncio.to_thread(self._run_batch, batch),
                        _BATCH_TIMEOUT)
                except asyncio.TimeoutError:
                    # hung device call: the stuck thread is abandoned,
                    # the device path disabled, the batch re-run on the
                    # host (native kernels) in a fresh thread
                    log.error("feeder batch stuck >%ss; disabling device "
                              "path and re-running host-side",
                              _BATCH_TIMEOUT)
                    self._device_ok = False
                    if self.mode != "require":
                        # thread: poison blocks on _probe_lock if a
                        # probe is mid-flight, and this is the loop
                        threading.Thread(
                            target=poison_probe_cache,
                            args=(f"device batch stuck "
                                  f">{_BATCH_TIMEOUT}s",),
                            daemon=True).start()
                    # bounded too: if even the JAX-free host path stalls,
                    # fail this batch instead of wedging the dispatcher
                    results = await asyncio.wait_for(
                        asyncio.to_thread(self._run_batch, batch, True),
                        _BATCH_TIMEOUT)
                for item, res in zip(batch, results):
                    if not item.future.done():
                        if isinstance(res, BaseException):
                            item.future.set_exception(res)
                        else:
                            item.future.set_result(res)
            except BaseException as e:
                for item in batch:
                    if not item.future.done():
                        item.future.set_exception(
                            e if not isinstance(e, asyncio.CancelledError)
                            else RuntimeError("feeder stopped"))
                if isinstance(e, asyncio.CancelledError):
                    raise

    # ---- batch execution (worker thread) -------------------------------

    def _pick_backend(self, op: str, total_bytes: int,
                      n_items: int) -> tuple[str, bool]:
        """-> (backend, trial). trial=True marks an exploration of the
        currently-losing backend: _run_batch caps that slice to
        _TRIAL_MAX_ITEMS and runs the rest on the winner."""
        if self.mode == "require":
            return "device", False  # forced: proof of the device path
        if self._device_ok is not True or self._calibrating:
            return "host", False
        if self._force_device.pop(op, False):
            return "device", True  # inline fast-path escape: re-probe now
        if total_bytes < _DEVICE_MIN_BYTES and n_items < _DEVICE_MIN_ITEMS:
            return "host", False  # tiny batches never amortize a round trip
        dev_rate, host_rate = self._rates(op)
        if dev_rate is None:
            return "device", False  # first sizeable batch: measure it
        if host_rate is None:
            return "host", False
        if self._explore_due(op):
            # periodic re-probe of whichever backend is currently losing
            return ("device" if dev_rate < host_rate else "host"), True
        return ("device" if dev_rate >= host_rate else "host"), False

    def _record(self, op: str, backend: str, nbytes: int, dt: float) -> None:
        with self._perf_lock:  # inline paths record from the loop thread
            ent = self._perf.setdefault((op, backend), [0.0, 0.0])
            # exponential forgetting so old (cold-compile) samples fade
            if ent[1] > 30.0:
                ent[0] *= 0.5
                ent[1] *= 0.5
            ent[0] += nbytes
            ent[1] += max(dt, 1e-6)

    def _run_batch(self, batch: list[_Item], force_host: bool = False
                   ) -> list:
        self.stats["batches"] += 1
        self.stats["items"] += len(batch)
        self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
        results: list = [None] * len(batch)
        by_op: dict[str, list[int]] = {}
        for i, item in enumerate(batch):
            by_op.setdefault(item.op, []).append(i)
        for op, idxs in by_op.items():
            if op in ("verify", "encode_put", "hash_md5"):  # 2-tuples
                total = sum(len(batch[i].data[1]) for i in idxs)
            elif op == "parity_check":  # item = one stripe (shard list)
                total = sum(len(b) for i in idxs for b in batch[i].data)
            else:
                total = sum(len(batch[i].data) for i in idxs
                            if isinstance(batch[i].data,
                                          (bytes, bytearray)))
            perf_op = ("hash" if op in ("verify", "hash_md5") else
                       "encode" if op == "encode_put" else
                       "parity" if op == "parity_check" else op)
            host_only = force_host
            if perf_op == "hash":
                from ..utils import data as _data

                if _data._content_algo != "blake3":
                    host_only = True  # blake2 never runs on device
            if host_only:
                backend, trial = "host", False
            else:
                backend, trial = self._pick_backend(perf_op, total,
                                                    len(idxs))
            cut = self._trial_cut(op, batch, idxs) if trial else len(idxs)
            if cut < len(idxs):
                # exploration of the losing backend: one small timing
                # sample there, the bulk stays on the winner
                other = "host" if backend == "device" else "device"
                self._exec_group(op, perf_op, batch, idxs[:cut], backend,
                                 results)
                self._exec_group(op, perf_op, batch, idxs[cut:], other,
                                 results)
            else:
                self._exec_group(op, perf_op, batch, idxs, backend,
                                 results)
        return results

    @staticmethod
    def _trial_cut(op: str, batch: list, idxs: list) -> int:
        """Items in the exploration slice: at least _TRIAL_MAX_ITEMS,
        growing to _TRIAL_ITEMS_CAP while under _TRIAL_MAX_BYTES."""
        cut, size = 0, 0
        for i in idxs:
            if cut >= _TRIAL_MAX_ITEMS and (
                    size >= _TRIAL_MAX_BYTES or cut >= _TRIAL_ITEMS_CAP):
                break
            d = batch[i].data
            if op in ("verify", "encode_put", "hash_md5"):
                d = d[1]
            if op == "parity_check":
                size += sum(len(b) for b in d)
            else:
                size += len(d) if isinstance(d, (bytes, bytearray,
                                                 memoryview)) else 0
            cut += 1
        return cut

    def _exec_group(self, op: str, perf_op: str, batch: list,
                    idxs: list, backend: str, results: list) -> None:
        blobs = [batch[i].data for i in idxs]
        if op in ("verify", "encode_put", "hash_md5"):  # 2-tuples
            total = sum(len(b) for _, b in blobs)
        elif op == "parity_check":
            total = sum(len(b) for s in blobs for b in s)
        else:
            total = sum(len(b) for b in blobs
                        if isinstance(b, (bytes, bytearray)))
        t0 = time.perf_counter()
        try:
            try:
                out = self._do_op(op, blobs, backend)
            except Exception as e:
                if backend != "device":
                    raise
                # a failing device (dead tunnel, OOM, XLA error) must
                # not fail requests while the host path works: retry
                # host-side and penalize the device in calibration
                log.warning("device %s batch failed (%s: %s); "
                            "falling back to host", op,
                            type(e).__name__, e)
                self._record(perf_op, "device", 0, 60.0)
                backend = "host"
                t0 = time.perf_counter()
                out = self._do_op(op, blobs, backend)
            for i, o in zip(idxs, out):
                results[i] = o
            self._record(perf_op, backend, total,
                         time.perf_counter() - t0)
            if backend == "device":
                self.stats["device_batches"] += 1
                self.stats["device_items"] += len(idxs)
        except Exception as e:
            for i in idxs:
                results[i] = e

    def _do_op(self, op: str, blobs: list, backend: str) -> list:
        if op == "hash":
            return self._do_hash(blobs, backend)
        if op == "hash_md5":
            from .. import native

            if backend == "device":
                # content hash batches to the device FIRST: if it
                # raises (dead tunnel), the host retry re-runs this op
                # from scratch, and MD5 state must not have advanced
                # yet or the retry double-counts the bytes into the
                # ETag chain. Only then batch-advance the MD5s host-
                # side (8-way across items).
                out = self._do_hash([d for _, d in blobs], backend)
                native.md5_update_many(list(blobs))
                return out
            return native.b3_md5_many(list(blobs))
        if op == "verify":
            digs = self._do_hash([b for _, b in blobs], backend)
            return _verify_matches(digs, blobs)
        if op == "encode":
            return self._do_encode(blobs, backend)
        if op == "encode_put":
            return self._do_encode_put(blobs, backend)
        if op == "parity_check":
            return self._do_parity_check(blobs, backend)
        raise RuntimeError(f"unknown feeder op {op!r}")

    def _do_hash(self, blobs: list[bytes], backend: str) -> list[bytes]:
        from ..utils import data as _data

        if _data._content_algo != "blake3":
            return [_data.content_hash(b) for b in blobs]
        if backend == "device":
            from ..ops import treehash

            return treehash.blake3_many(blobs)
        try:
            from .. import native

            if native.available():
                return native.blake3_many(blobs)
        except Exception:
            # lint: ignore[GL05] native backend optional; pure-python fallback follows
            pass
        from ..utils.data import blake3sum

        return [blake3sum(b) for b in blobs]

    def _do_encode_put(self, items: list[tuple[bytes, bytes]], backend: str
                       ) -> list[list]:
        """items = [(prefix, data)]; like _do_encode but each part is a
        complete shard payload (pack_shard framing, crc32c). Host+native
        is the PUT hot path."""
        from .manager import pack_shard

        codec = self.codec
        if backend != "device":
            try:
                from .. import native

                if native.available():
                    from ..ops import rs

                    pmat = rs.parity_matrix(codec.k, codec.m)
                    return [native.rs_encode_packed(d, codec.k, codec.m,
                                                    pmat, prefix=p)
                            for p, d in items]
            except Exception:
                # lint: ignore[GL05] native backend optional; _do_encode fallback follows
                pass
        # device, or host without native: delegate the encode itself to
        # _do_encode (single source of truth) and wrap with pack_shard
        blocks = [p + d for p, d in items]
        parts_lists = (codec.encode_batch(blocks) if backend == "device"
                       else self._do_encode(blocks, backend))
        return [[pack_shard(pp, len(b)) for pp in parts]
                for b, parts in zip(blocks, parts_lists)]

    def _do_encode(self, blocks: list[bytes], backend: str
                   ) -> list[list[bytes]]:
        from ..ops import rs

        codec = self.codec
        if backend == "device":
            return codec.encode_batch(blocks)
        try:
            from .. import native

            if native.available():
                out = []
                for b in blocks:
                    shards = rs.split_stripe(b, codec.k)
                    parity = native.gf_matmul(
                        rs.parity_matrix(codec.k, codec.m), shards)
                    out.append([bytes(s) for s in shards]
                               + [bytes(p) for p in parity])
                return out
        except Exception:
            # lint: ignore[GL05] native backend optional; numpy fallback follows
            pass
        # last resort: pure numpy — NEVER codec.encode here, whose JAX
        # path would re-enter the possibly-dead backend this host branch
        # exists to avoid
        out = []
        for b in blocks:
            shards = rs.split_stripe(b, codec.k)
            parity = rs.encode_np(codec.k, codec.m, shards)
            out.append([bytes(s) for s in shards]
                       + [bytes(p) for p in parity])
        return out

    def _do_parity_check(self, stripes: list[list[bytes]], backend: str
                         ) -> list[bool]:
        """stripes = [[k data + m parity shard payloads]] -> per-stripe
        consistency verdicts. Device: one padded (B, k+m, S) batch
        through the encode bit-matmul + compare (zero padding is safe:
        the code is linear). Host: native GF matmul per stripe, numpy
        as last resort — same no-JAX-on-host rule as _do_encode."""
        from ..ops import rs

        codec = self.codec
        k, m = codec.k, codec.m
        if backend == "device":
            smax = max(len(s[0]) for s in stripes)
            arr = np.zeros((len(stripes), k + m, smax), dtype=np.uint8)
            for i, s in enumerate(stripes):
                for j, b in enumerate(s):
                    arr[i, j, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            return [bool(v) for v in np.asarray(rs.parity_check(k, m, arr))]
        pmat = rs.parity_matrix(k, m)
        native_mod = None
        try:
            from .. import native

            if native.available():
                native_mod = native
        except Exception:
            # lint: ignore[GL05] native backend optional; numpy path handles it
            pass
        out = []
        for s in stripes:
            data = np.stack(
                [np.frombuffer(b, dtype=np.uint8) for b in s[:k]])
            parity = (native_mod.gf_matmul(pmat, data)
                      if native_mod is not None
                      else rs.encode_np(k, m, data))
            out.append(all(bytes(parity[j]) == bytes(s[k + j])
                           for j in range(m)))
        return out
