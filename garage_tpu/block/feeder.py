"""DeviceFeeder: batches block math from concurrent requests onto the TPU.

The reference does its per-block CPU work (hashing, compression) inline
in each request task (src/api/s3/put.rs:413-477 spawn_blocking, one
block at a time). A TPU earns its keep only on *batches* — so the data
path here funnels every block-math request (content hash, RS encode,
RS decode/repair, scrub verify) through one bounded queue. A single
dispatcher drains
whatever has accumulated, groups it by operation and shape, and issues
one batched JAX call per group (ops/treehash.hash_batch_jax,
ops/rs.encode). Under load, concurrent PUTs coalesce into MXU-sized
batches for free; when idle, single requests take the native C path
(garage_tpu/native) which beats a device round-trip for one block.

Backend selection: the `axon` remote-TPU backend can hang indefinitely
on init when the tunnel is down (observed: jax.devices() blocked >500 s)
— so device use is gated behind a subprocess probe with a timeout,
cached in /tmp. Until the probe succeeds, everything runs host-side;
the data path never blocks on a dead tunnel.

Once the device is up, the feeder CALIBRATES rather than assumes: it
tracks observed bytes/s per (op, backend) and routes each batch to the
faster one, re-probing the loser periodically. On a real TPU host
(PCIe/DMA) the batched device path wins by an order of magnitude; on a
tunneled dev chip where host<->device moves at tens of MB/s the native C
kernels win — measured, not guessed (a fixed threshold was wrong on both
ends: this box's tunnel does ~300 MB/s h2d but ~15 MB/s d2h).

The device route is a STAGED PIPELINE (block/device_backend.py): each
batch flows h2d -> compute -> d2h through three dedicated worker
threads, and the dispatcher keeps up to `[tpu] inflight_batches`
(default 2) batches in flight — while batch N computes, batch N+1's
bytes are already moving h2d and batch N-1's results are reading back,
and the event loop spends the meantime draining the queue and forming
the next batch instead of idling on one blocking hop. Launch shapes
are padded to a small bucket set so XLA compiles a handful of programs
(`feeder_pad_waste_bytes` / `feeder_recompiles` price that trade), and
batches of >= `[tpu] mesh_min_items` items shard across every visible
chip via parallel/mesh.py. The watchdog covers every in-flight stage:
a hang anywhere abandons the stage threads, disables the device path,
poisons the probe cache, and re-runs ALL in-flight batches host-side —
no caller future is ever lost.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

import numpy as np

from .device_backend import (DEFAULT_PAD_BUCKETS, STAGES, DevicePipeline,
                             JaxDeviceBackend, StubDeviceBackend,
                             group_bytes)

log = logging.getLogger("garage_tpu.block.feeder")

# a (possibly remote) device round trip only pays above these sizes
_DEVICE_MIN_BYTES = 4 << 20
_DEVICE_MIN_ITEMS = 4
# separate floors for the READ-side ops (decode/repair): degraded GETs
# are latency-sensitive, so a lone decode stays host-inline and only
# coalesced bursts (concurrent degraded GETs, scrub/resync rebuild
# waves) pay a device trip
_DEVICE_MIN_DECODE_BYTES = 4 << 20
_DEVICE_MIN_DECODE_ITEMS = 4
# inline decode/repair fast path size ceiling: above this the GF
# matmul runs in a worker thread via the queue (a multi-MiB stripe
# matmul inline would park the event loop for milliseconds per GET)
_INLINE_DECODE_MAX_BYTES = 1 << 20
# re-try the losing backend at most this often (wall clock) so a
# recovered tunnel (or a warmed-up XLA program) gets re-discovered.
# Time-based, not count-based: on a slow tunnel one exploration batch
# can cost ~0.5 s, so a per-N-calls rule taxed busy traffic heavily
# while an idle server never re-probed at all.
_EXPLORE_SECS = 60.0
# exploration trials of the LOSING backend are capped: over a ~2 MB/s
# tunnel a full 8x1 MiB production batch costs seconds, and the
# re-probe only needs one timing sample, not the whole batch. The cap
# is byte-aware — at least 2 items, growing to 8 while the slice is
# under _TRIAL_MAX_BYTES — so a trial of small blobs still amortizes a
# recovered backend's fixed round-trip latency instead of permanently
# under-measuring it. The rest of the batch runs on the winner.
_TRIAL_MAX_ITEMS = 2
_TRIAL_ITEMS_CAP = 8
_TRIAL_MAX_BYTES = 4 << 20
# a batch stuck longer than this means the device backend hung (the
# axon tunnel can block inside XLA calls); the batch re-runs host-side
# and the device path is disabled
_BATCH_TIMEOUT = 300.0

# ops whose per-stream cadence makes a short gather window pay: each PUT
# stream keeps at most one of these in flight per block, so lanes only
# line up if the dispatcher lingers for the other streams' submissions
# ([tpu] batch_linger_ms)
_LINGER_OPS = ("hash_md5", "hash", "encode_put", "sha256")

PROBE_TIMEOUT = 60.0


def _probe_cache_path() -> str:
    # per-uid (a shared /tmp name would let another local user pin the
    # verdict for everyone) AND per-platform-pin: a JAX_PLATFORMS=cpu
    # test process probing "cpu" must not poison the cache consulted by
    # an unpinned server on the same box
    uid = os.getuid() if hasattr(os, "getuid") else 0
    pin = os.environ.get("JAX_PLATFORMS", "auto") or "auto"
    pin = "".join(c if c.isalnum() else "_" for c in pin)[:16]
    return os.path.join(tempfile.gettempdir(),
                        f"garage_tpu_device_probe.{uid}.{pin}.json")


_PROBE_TTL = 600.0

_probe_lock = threading.Lock()
_probe_result: Optional[dict] = None


def _read_probe_cache() -> Optional[dict]:
    """Disk-cached verdict if fresh, else None. Caller holds no lock."""
    try:
        with open(_probe_cache_path()) as f:
            cached = json.load(f)
        age = time.time() - cached.get("at", 0)
        if 0 <= age < _PROBE_TTL:  # reject future timestamps
            return cached
    except Exception:
        # lint: ignore[GL05] stale/corrupt probe cache is the same as no cache
        pass
    return None


def _write_probe_cache(res: dict) -> None:
    try:
        cache = _probe_cache_path()
        with open(cache + ".tmp", "w") as f:
            json.dump(res, f)
        os.replace(cache + ".tmp", cache)
    except OSError:
        pass


def probe_device(timeout: float = PROBE_TIMEOUT, force: bool = False) -> dict:
    """Subprocess-probe the default JAX backend. Returns
    {"ok": bool, "platform": str, "error": str}. Cached in-process and in
    /tmp (TTL 10 min) so a dead tunnel costs one timeout, not one per
    worker."""
    global _probe_result
    with _probe_lock:
        if _probe_result is not None and not force:
            return _probe_result
        if not force:
            cached = _read_probe_cache()
            if cached is not None:
                _probe_result = cached
                return cached
        res = {"ok": False, "platform": "cpu", "error": "", "at": time.time()}
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=timeout, capture_output=True, text=True,
            )
            if r.returncode == 0:
                plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "cpu"
                res["platform"] = plat
                res["ok"] = plat not in ("cpu",)
            else:
                res["error"] = (r.stderr or "")[-500:]
        except subprocess.TimeoutExpired:
            res["error"] = f"jax.devices() did not return within {timeout}s"
        except OSError as e:
            res["error"] = str(e)
        if force and res["ok"]:
            cached = _read_probe_cache()
            if cached is not None and cached.get("hung"):
                # the poison marks a device that ANSWERS probes but hangs
                # on real work — this probe-only success proves nothing
                # new, so the forced caller gets its result while the
                # shared verdict stays poisoned until the TTL expires
                return res
        _probe_result = res
        _write_probe_cache(res)
        return res


def poison_probe_cache(error: str) -> None:
    """Record a negative device verdict (in-process + /tmp TTL cache)
    with the `hung` marker. Used when the device answered the probe but
    then HUNG in real work (calibration/batch): without this every
    co-located feeder re-reads the stale positive probe and pays the
    full watchdog timeout itself. mode="require" still force-re-probes
    and proceeds on its own result, but a probe-only success does NOT
    clear the hung marker for auto feeders (only the TTL does).

    May block up to a probe timeout on _probe_lock — call from a worker
    thread, never the event loop."""
    global _probe_result
    res = {"ok": False, "platform": "cpu", "error": error,
           "at": time.time(), "hung": True}
    with _probe_lock:
        _probe_result = res
        _write_probe_cache(res)


def _verify_matches(digs: list, items: list) -> list[bool]:
    """Per-item content-hash verdicts; one copy of the match rule
    (digest equality, legacy-algo fallback) for the inline fast path
    and the batch-queue path alike."""
    from ..utils.data import content_hash_matches

    return [dg == h or content_hash_matches(d, h)
            for dg, (h, d) in zip(digs, items)]


class _DeviceHang(Exception):
    """A device pipeline stage hung (or a sibling batch's stage did and
    aborted the generation): re-run the affected legs host-side."""


class _Item:
    __slots__ = ("op", "data", "future", "extra")

    def __init__(self, op: str, data, future, extra=None):
        self.op = op
        self.data = data
        self.future = future
        self.extra = extra


class DeviceFeeder:
    """One per BlockManager. mode: "auto" (probe, then use device when
    batches are big enough), "off" (host only), "require" (device always;
    raise if probe fails — bench/test use)."""

    def __init__(self, codec=None, mode: str = "auto",
                 max_batch: int = 256, tpu_cfg=None, backend=None):
        self.codec = codec
        # greedy-drain cap: blocks per device batch ([tpu] batch_blocks)
        self.max_batch = max(1, int(max_batch))

        # [tpu] knobs (utils/config.py TpuConfig); the module constants
        # stay as defaults so direct-constructed feeders (tests, bench)
        # behave exactly as before. Runtime-tunable via the admin
        # GET/POST /v1/s3/tuning endpoint like the s3 knobs.
        def knob(name, default):
            v = getattr(tpu_cfg, name, None) if tpu_cfg is not None else None
            return default if v is None else v

        self.device_min_bytes = int(knob("device_min_bytes",
                                         _DEVICE_MIN_BYTES))
        self.device_min_items = int(knob("device_min_items",
                                         _DEVICE_MIN_ITEMS))
        self.device_min_decode_bytes = int(knob("device_min_decode_bytes",
                                                _DEVICE_MIN_DECODE_BYTES))
        self.device_min_decode_items = int(knob("device_min_decode_items",
                                                _DEVICE_MIN_DECODE_ITEMS))
        self.trial_max_items = int(knob("trial_max_items",
                                        _TRIAL_MAX_ITEMS))
        self.trial_items_cap = int(knob("trial_items_cap",
                                        _TRIAL_ITEMS_CAP))
        self.trial_max_bytes = int(knob("trial_max_bytes",
                                        _TRIAL_MAX_BYTES))
        # staged-pipeline depth: batches concurrently in flight through
        # the h2d/compute/d2h stages. A batch's dispatch slot is held
        # until its d2h readback drains, so depth 2 (double buffering)
        # leaves h2d idle whenever compute+d2h of the batch ahead
        # outlast its own h2d — matching the depth to the THREE stages
        # keeps the transfer engine fed (bench_put_path: ~0.80 -> 0.86
        # frontend_efficiency at pinned stub rates)
        self.inflight_batches = max(1, int(knob("inflight_batches", 3)))
        self.pad_buckets = tuple(
            int(b) for b in knob("pad_buckets", DEFAULT_PAD_BUCKETS))
        self.mesh_min_items = int(knob("mesh_min_items", 8))
        # per-batch watchdog budget, instance-level so tests can shrink
        # it without patching every co-located feeder
        self.batch_timeout = float(knob("batch_timeout_s", _BATCH_TIMEOUT))
        # [tpu] batch_linger_ms: gather-window budget for same-op PUT
        # lanes (hash/md5/sha256/encode). 0 disables the linger — every
        # batch ships with whatever the greedy drain found.
        self.batch_linger = max(
            0.0, float(knob("batch_linger_ms", 6.0))) / 1000.0
        # device backend: "jax" (real accelerator), "stub"
        # (deterministic latency emulator — CI), or a ready object
        if backend is None:
            backend = (os.environ.get("GARAGE_TPU_DEVICE_BACKEND")
                       or knob("device_backend", "jax"))
        self._backend_sel = backend
        self._backend = None
        self._backend_lock = threading.Lock()

        env_mode = os.environ.get("GARAGE_TPU_DEVICE")
        if mode == "auto" and env_mode == "off":
            # test/CI kill-switch: never probe, never spawn calibration
            # threads (a probed tunnel leaves C++ threads that abort on
            # interpreter teardown — the r3 rc=134)
            mode = "off"
        elif mode == "auto" and env_mode == "require":
            # bench override: force every batch through the device even
            # where auto-calibration would route to the host (the live
            # S3-path device proof, bench.py bench_s3_put(device=True))
            mode = "require"
        self.mode = mode
        self._q: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        self._require_lock: Optional[asyncio.Lock] = None
        self._require_err: Optional[tuple[float, str]] = None
        self._device_ok: Optional[bool] = None
        self._probing = False
        self._calibrating = False
        self.stats = {"batches": 0, "items": 0, "device_batches": 0,
                      "device_items": 0, "device_bytes": 0,
                      "inline_items": 0, "max_batch": 0,
                      "pad_waste_bytes": 0, "recompiles": 0,
                      "mesh_batches": 0,
                      # read-side (decode + repair) engagement counters:
                      # total items through the feeder, items/bytes that
                      # ran on the device path (the degraded-GET /
                      # rebuild twin of device_items)
                      "decode_items": 0, "decode_device_items": 0,
                      "decode_device_bytes": 0}
        # staged pipeline state: the current executor generation, the
        # batches in flight, per-stage busy seconds and the wall-clock
        # union of windows with >= 1 device leg in flight (overlap
        # efficiency = sum(busy) / wall; > 1.0 means stages overlap)
        self._pl: Optional[DevicePipeline] = None
        self._pl_busy: dict[str, float] = {s: 0.0 for s in STAGES}
        self._pl_wall = 0.0
        self._win_open = 0
        self._win_t0 = 0.0
        self._inflight_tasks: set = set()
        # PUT streams currently inside read_and_put_blocks: sizes the
        # hash_md5 gather window (one block hash in flight per stream)
        self.active_streams = 0
        # calibration: (op, backend) -> [bytes, seconds]; routing picks
        # the backend with the best observed bytes/s, exploring the
        # other every _EXPLORE_EVERY batches
        self._perf: dict[tuple[str, str], list[float]] = {}
        self._perf_lock = threading.Lock()  # inline (loop) vs worker thread
        self._last_explore: dict[str, float] = {}
        self._force_device: dict[str, bool] = {}

    def perf_summary(self) -> dict[str, float]:
        """Observed MB/s per (op, backend) — /metrics + bench surface."""
        with self._perf_lock:
            return {f"{op}/{be}": round(b / t / 1e6, 1)
                    for (op, be), (b, t) in self._perf.items() if t > 0}

    def _rates(self, op: str):
        """(device_rate|None, host_rate|None) under the lock — readers
        on the loop thread race _record in the worker thread."""
        with self._perf_lock:
            dev = self._perf.get((op, "device"))
            host = self._perf.get((op, "host"))
            return (dev[0] / dev[1] if dev else None,
                    host[0] / host[1] if host else None)

    # ---- lifecycle ----------------------------------------------------

    def _ensure_started(self) -> None:
        if self._task is None or self._task.done():
            self._q = asyncio.Queue()
            self._task = asyncio.create_task(self._run(), name="device-feeder")
            # supervised by stop(): not a leak at loop teardown
            self._task._garage_background = True
        if self.mode == "off":
            self._device_ok = False
        elif self._device_ok is None and self._backend_is_stub():
            # the stub emulator needs no probe (there is no tunnel to
            # hang on) — the device verdict is immediately positive
            self._device_ok = True

    def _backend_is_stub(self) -> bool:
        sel = self._backend_sel
        return (sel == "stub" if isinstance(sel, str)
                else getattr(sel, "name", "") == "stub")

    def _get_backend(self):
        """The staged device backend, built lazily from a pipeline
        worker thread (jax import / device discovery never run on the
        event loop, and both sit under the batch watchdog)."""
        with self._backend_lock:
            if self._backend is None:
                sel = self._backend_sel
                if not isinstance(sel, str):
                    self._backend = sel
                    if getattr(sel, "feeder", False) is None:
                        sel.feeder = self  # test-built stubs wire back
                elif sel == "stub":
                    self._backend = StubDeviceBackend(self)
                else:
                    self._backend = JaxDeviceBackend(
                        codec=self.codec, pad_buckets=self.pad_buckets,
                        mesh_min_items=self.mesh_min_items,
                        stats=self.stats)
            return self._backend

    async def _require_probe(self) -> None:
        """Resolve the device verdict for mode="require" WITHOUT
        blocking the event loop: the probe is a jax subprocess that can
        take ~2 min cold, and running it inline wedged every other
        connection past its client timeout (first r5 live capture)."""
        if self._require_lock is None:
            self._require_lock = asyncio.Lock()
        async with self._require_lock:
            if self._device_ok is not None:
                return
            if self._backend_is_stub():
                self._device_ok = True
                return
            if self._require_err is not None:
                # fail fast on a recent verdict: without this, every
                # request on a dead tunnel pays the full forced-probe
                # chain while serialized behind this lock. TTL must
                # exceed that chain's cost (up to 4 × PROBE_TIMEOUT)
                # or steady traffic spends most wall time re-probing.
                ts, msg = self._require_err
                if time.monotonic() - ts < 5 * PROBE_TIMEOUT:
                    raise RuntimeError(msg)
                self._require_err = None
            res = await asyncio.to_thread(probe_device)
            if not res["ok"]:
                # A negative verdict may be a stale cache entry or a
                # transient co-tenant fallback (unpinned jax discovery
                # degrades to cpu under load); "require" exists for
                # proof runs, so pay one forced re-probe before
                # failing — with a longer leash, since a congested
                # tunnel can hold jax.devices() past the default.
                res = await asyncio.to_thread(
                    probe_device, 3 * PROBE_TIMEOUT, True)
            if not res["ok"]:
                msg = (f"device required but probe failed: "
                       f"{res['error'] or res['platform']}")
                self._require_err = (time.monotonic(), msg)
                raise RuntimeError(msg)
            self._device_ok = True

    async def stop(self) -> None:
        # snapshot-and-clear EVERYTHING this stop owns BEFORE awaiting
        # (GL12): stop() yields while the cancelled dispatcher
        # unwinds, and a concurrent _submit()'s _ensure_started() can
        # legitimately respawn a new dispatcher (with a NEW queue)
        # into self._task during that window. The old code nulled
        # self._task after the await — orphaning the live respawn —
        # and drained self._q, which by then was the RESPAWN's queue:
        # a fresh submission got a spurious "feeder stopped" while the
        # feeder was running. Only the snapshots are touched below.
        t, self._task = self._task, None
        q = self._q  # snapshot only: the unwinding dispatcher still
        # reads self._q between suspension points; a respawn swaps in
        # a fresh queue object, so draining the snapshot can never
        # touch the respawn's submissions
        inflight = list(self._inflight_tasks)
        self._inflight_tasks.clear()
        if t is not None:
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        # cancel every in-flight pipelined batch THIS stop snapshotted:
        # each _finish_batch fails its items' futures on the way out,
        # so no caller hangs on a batch that was mid-stage
        for bt in inflight:
            bt.cancel()
            try:
                await bt
            except (asyncio.CancelledError, Exception):
                pass
        # fail anything still queued on the OLD queue so no caller
        # awaits forever
        if q is not None:
            while not q.empty():
                item = q.get_nowait()
                if not item.future.done():
                    item.future.set_exception(RuntimeError("feeder stopped"))

    def _maybe_start_probe(self) -> None:
        """Kick the backend probe in a thread; host path until it lands."""
        if self._backend_is_stub():
            if self._device_ok is None:
                self._device_ok = True  # no tunnel, no probe needed
            return  # but a watchdog-disabled stub stays disabled
        if self._device_ok is not None or self._probing or self.mode != "auto":
            return
        self._probing = True
        self._calibrating = True

        def run():
            try:
                res = probe_device()
                ok = bool(res["ok"])
                if ok:
                    log.info("device data plane active: %s", res["platform"])
                    # seed BOTH backends' throughput samples with
                    # synthetic batches OFF the request path, so the
                    # first production batch is routed on data instead
                    # of paying a cold device trial inline. The device
                    # calls run in a nested watchdog thread: a hung
                    # tunnel (the failure mode _BATCH_TIMEOUT guards on
                    # the batch path) disables the device; a transient
                    # error merely penalizes it so _EXPLORE_EVERY can
                    # re-discover a recovered device later.
                    cal = threading.Thread(target=self._calibrate,
                                           daemon=True,
                                           name="feeder-calibrate")
                    cal.start()
                    cal.join(_BATCH_TIMEOUT)
                    if cal.is_alive():
                        log.error("device calibration stuck >%ss; "
                                  "disabling device path", _BATCH_TIMEOUT)
                        ok = False
                        poison_probe_cache(
                            f"calibration stuck >{_BATCH_TIMEOUT}s "
                            "(device answered probe, hung on work)")
                elif res["error"]:
                    log.info("device probe failed, host data plane: %s",
                             res["error"])
                self._device_ok = ok
            finally:
                self._calibrating = False
                self._probing = False

        threading.Thread(target=run, daemon=True,
                         name="feeder-probe").start()

    def _calibrate(self) -> None:
        from ..utils import data as _data

        blob = bytes(np.random.default_rng(0).integers(
            0, 256, 1 << 20, dtype=np.uint8))
        batch = [blob] * 4
        dec_items = None
        if self.codec is not None and self.codec.m >= 1:
            # read-side seed: a degraded stripe (shard 0 lost, first
            # parity standing in) — without it the first production
            # decode wave pays a cold device trial inline, exactly what
            # calibration exists to avoid on the PUT ops
            k = self.codec.k
            present = tuple(range(1, k + 1))
            stripes = self._do_encode(batch, "host")
            dec_items = [(present, [st[i] for i in present], len(blob))
                         for st in stripes]
        for backend in ("host", "device"):
            try:
                # blake2 hashing never runs on device — recording a
                # host timing under the device key would fabricate a
                # backend that never ran
                if _data._content_algo == "blake3" or backend == "host":
                    t0 = time.perf_counter()
                    self._do_hash(batch, backend)
                    self._record("hash", backend, len(batch) << 20,
                                 time.perf_counter() - t0)
                if self.codec is not None:
                    t0 = time.perf_counter()
                    self._do_encode(batch, backend)
                    self._record("encode", backend, len(batch) << 20,
                                 time.perf_counter() - t0)
                if dec_items is not None:
                    t0 = time.perf_counter()
                    self._do_decode(dec_items, backend)
                    self._record("decode", backend,
                                 sum(len(b) for it in dec_items
                                     for b in it[1]),
                                 time.perf_counter() - t0)
            except Exception as e:
                # a host-leg failure must not kill the thread silently
                # (the device leg would then never run and the first
                # production batch would pay the cold device trial the
                # calibration exists to avoid)
                log.warning("%s calibration leg failed (%s: %s)",
                            backend, type(e).__name__, e)
                if backend == "device":
                    self._record("hash", "device", 0, 60.0)
                    self._record("encode", "device", 0, 60.0)
                    self._record("decode", "device", 0, 60.0)
        log.info("feeder calibration: %s", self.perf_summary())

    # ---- public async ops ---------------------------------------------

    async def _submit(self, op: str, data, extra=None):
        self._ensure_started()
        if self.mode == "require" and self._device_ok is None:
            await self._require_probe()
            # stop() may have torn down the dispatcher while we sat in
            # the (multi-minute) probe; restart it or the enqueued item
            # below would await a future nothing ever resolves
            self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        await self._q.put(_Item(op, data, fut, extra))
        return await fut

    async def hash(self, data: bytes) -> bytes:
        """Content hash of one block (batched with concurrent callers)."""
        if self._host_inline_ok("hash"):
            from ..utils import data as _data

            if _data._content_algo == "blake3":
                from .. import native

                self.stats["inline_items"] += 1
                t0 = time.perf_counter()
                # lint: ignore[GL10] host-inline fast path is gated to small items; the flagged open chain is the one-time native build, cached for the process lifetime
                out = native.blake3_many([data])[0]
                self._record("hash", "host", len(data),
                             time.perf_counter() - t0)
                return out
        return await self._submit("hash", data)

    async def hash_with_md5(self, data: bytes, md5acc) -> bytes:
        """Content hash + S3-ETag MD5 advance for one block. Rides the
        feeder queue so blocks from CONCURRENT requests form one batch:
        MD5 is a strict serial chain within an object but independent
        across objects, and the native kernel runs up to 8 chains in
        AVX2 lockstep (measured: 0.48 GB/s single -> 2.4 GB/s at 8
        lanes). Host route fuses blake3 into the same call; device
        route batch-advances the MD5s host-side while the content hash
        batches to the accelerator (a serial chain can't ride the
        tree-structured device path)."""
        if getattr(md5acc, "fused", False):
            from ..utils import data as _data

            if _data._content_algo == "blake3":
                if self.active_streams <= 1 \
                        and self._host_inline_ok("hash"):
                    # lone stream: no lanes to gather — the inline
                    # one-pass interleaved kernel beats the queue hop
                    # plus a 1-lane batch
                    self.stats["inline_items"] += 1
                    t0 = time.perf_counter()
                    out = md5acc.update_with_blake3(data)
                    self._record("hash", "host", len(data),
                                 time.perf_counter() - t0)
                    return out
                return await self._submit("hash_md5", (md5acc, data))
        # non-native fallback: hashlib md5 + separate content hash
        if (os.cpu_count() or 1) > 1 and len(data) >= 65536:
            out, _ = await asyncio.gather(
                self.hash(data), asyncio.to_thread(md5acc.update, data))
            return out
        md5acc.update(data)
        return await self.hash(data)


    async def sha256_hex(self, data) -> str:
        """SigV4 chunk-signature SHA-256 (hex). Chunk digests are
        independent across streams (the signature chain, not the hash,
        carries continuity), so concurrent PUTs batch into one device
        launch. A lone stream skips the queue: hashlib in a worker
        thread beats a 1-row device round trip and keeps the event loop
        free for the socket."""
        from ..ops import sha256 as _sha

        if self.active_streams <= 1 or self.mode == "off":
            t0 = time.perf_counter()
            out = await asyncio.to_thread(_sha.sha256_hex_py, data)
            self._record("sha256", "host", _sha.part_len(data),
                         time.perf_counter() - t0)
            return out
        return await self._submit("sha256", data)

    async def encode(self, packed: bytes) -> list[bytes]:
        """Erasure parts for one packed block (batched)."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        return await self._submit("encode", packed)

    def _host_inline_ok(self, op: str) -> bool:
        """True when the queue+thread hop is pure overhead: the route is
        host anyway and the native kernel (which releases the GIL) can
        run inline on the event loop. The queue path exists to build
        device batches; paying two thread handoffs per item to then run
        host-side was a top cost in the r3 kernel-vs-system gap."""
        from .. import native

        if not native.loaded():
            return False
        if self.mode == "off":
            return True
        if self.mode == "require" or self._device_ok is None:
            return False  # device mandatory / probe still undecided
        if self._device_ok is False:
            return True
        dev_rate, host_rate = self._rates(op)
        if dev_rate is not None and host_rate is not None \
                and dev_rate < host_rate:
            # host is winning on data; still send an occasional call
            # through the queue WITH a forced device trial so a
            # recovered device gets re-discovered
            if self._explore_due(op):
                self._force_device[op] = True
                return False
            return True
        return False

    def _explore_due(self, op: str) -> bool:
        now = time.monotonic()
        if op not in self._last_explore:
            # calibration just measured both backends — the clock starts
            # there, not at zero (else the first production batch pays a
            # pointless trial on the known-slow backend)
            self._last_explore[op] = now
            return False
        # adaptive interval: the wider the measured gap, the rarer the
        # re-probe. A backend losing 8x gets the base 60 s cadence; a
        # tunnel-attached device losing 500x gets probed ~hourly — one
        # trial there costs real seconds of live traffic, and a gap that
        # wide doesn't close without a topology change anyway.
        dev, host = self._rates(op)
        interval = _EXPLORE_SECS
        if dev is not None and host is not None:
            # a 0.0 rate (every byte of that backend's window failed)
            # is the WIDEST gap, not missing data: cap straight to 64x
            if min(dev, host) <= 0.0:
                interval *= 64.0
            else:
                ratio = max(dev, host) / min(dev, host)
                interval *= min(64.0, max(1.0, ratio / 8.0))
        if now - self._last_explore[op] >= interval:
            self._last_explore[op] = now
            return True
        return False

    async def encode_put(self, data: bytes, prefix: bytes = b"") -> list:
        """Erasure parts for one packed block (logical stream
        prefix||data), each framed as a ready-to-send shard payload
        (pack_shard format). The host path is ONE GIL-released native
        call per block (split + parity + crc + headers fused:
        native.rs_encode_packed); the device path batches the parity
        matmul through XLA then packs host-side."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        lease = data if hasattr(data, "stripe") else None
        if self._host_inline_ok("encode"):
            from .. import native
            from ..ops import rs

            self.stats["inline_items"] += 1
            t0 = time.perf_counter()
            pmat = rs.parity_matrix(self.codec.k, self.codec.m)
            if lease is not None:
                # zero-copy ingest lease: body is already resident in
                # the pool buffer; hand the native kernel the view
                # (scheme byte travels as the prefix, same framing)
                # lint: ignore[GL10] host-inline fast path is gated to small items; the flagged open chain is the one-time native build, cached for the process lifetime
                out = native.rs_encode_packed(
                    lease.view(), self.codec.k, self.codec.m, pmat,
                    prefix=bytes([lease.buf[0]]))
                self._record("encode", "host", lease.total_len,
                             time.perf_counter() - t0)
                return out
            out = native.rs_encode_packed(
                data, self.codec.k, self.codec.m, pmat, prefix=prefix)
            self._record("encode", "host", len(prefix) + len(data),
                         time.perf_counter() - t0)
            return out
        if lease is not None:
            # the lease itself is the queue item: the device stage
            # reads its stripe() rows without re-packing, and the host
            # route slices the view — release stays with the PUT task,
            # which awaits this call before letting go
            return await self._submit("encode_put", lease)
        return await self._submit("encode_put", (prefix, data))

    async def verify_blocks(self, items: list[tuple[bytes, bytes]]
                            ) -> list[bool]:
        """[(hash32, plain)] -> per-item content-hash match (scrub)."""
        if not items:
            return []
        if self._host_inline_ok("hash"):
            from ..utils import data as _data

            if _data._content_algo == "blake3":
                from .. import native

                self.stats["inline_items"] += len(items)
                t0 = time.perf_counter()
                # already batched -> one thread handoff is amortized;
                # running it inline would park the event loop for the
                # whole multi-MiB native call, every scrub batch
                digs = await asyncio.to_thread(
                    native.blake3_many, [d for _, d in items])
                self._record("hash", "host", sum(len(d) for _, d in items),
                             time.perf_counter() - t0)
                return _verify_matches(digs, items)
        futs = [self._submit("verify", (h, d)) for h, d in items]
        return list(await asyncio.gather(*futs))

    async def parity_check(self, stripes: list[list[bytes]]) -> list[bool]:
        """Scrub deep pass: per-stripe cross-shard consistency. Each
        stripe is the full [k data + m parity] shard payload list
        (equal lengths within one stripe). True = the stored parity
        rows equal parity re-derived from the data rows — any single
        corrupt shard flips every parity row (ops/rs.parity_check on
        the device route; native GF matmul compare on the host
        route)."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        if not stripes:
            return []
        if self._host_inline_ok("parity"):
            # already batched; one thread handoff amortized over the
            # whole multi-MiB native call (same shape as verify_blocks)
            self.stats["inline_items"] += len(stripes)
            t0 = time.perf_counter()
            out = await asyncio.to_thread(self._do_parity_check, stripes,
                                          "host")
            self._record("parity", "host",
                         sum(len(b) for s in stripes for b in s),
                         time.perf_counter() - t0)
            return out
        futs = [self._submit("parity_check", s) for s in stripes]
        return list(await asyncio.gather(*futs))

    def _check_stripe(self, present, shards, k: int, width: int) -> tuple:
        """Shared validation for the read-side ops, BEFORE the queue:
        a malformed item must fail its own caller, never poison the
        group-mates it would have batched with (one _exec_group
        exception fails the whole leg)."""
        present = tuple(present)
        if len(present) != k or len(shards) != k:
            raise ValueError(
                f"need exactly k={k} present shards, got "
                f"{len(present)} indices / {len(shards)} payloads")
        if len(set(present)) != k or any(
                not 0 <= int(i) < width for i in present):
            raise ValueError(
                f"present indices must be {k} distinct values in "
                f"[0, {width}); got {present}")
        slen = len(shards[0])
        if any(len(s) != slen for s in shards):
            raise ValueError("unequal shard lengths in decode/repair "
                             "stripe (corrupt or misplaced shard)")
        return present

    async def decode(self, present, shards: list, plain_len: int) -> bytes:
        """Erasure decode of one stripe: `shards` are the surviving
        payloads in ascending `present`-index order; -> the packed
        block bytes (join_stripe at plain_len). Batched with every
        concurrent caller, so degraded GETs and rebuild waves coalesce
        into one pattern-as-data device launch. The all-systematic case
        is the CALLER's fast path (pure concat, no math) — everything
        submitted here pays a real matmul somewhere."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        codec = self.codec
        present = self._check_stripe(present, shards, codec.k,
                                     codec.k + codec.m)
        total = sum(len(s) for s in shards)
        if total <= _INLINE_DECODE_MAX_BYTES \
                and self._host_inline_ok("decode"):
            from .. import native
            from ..ops import rs

            self.stats["inline_items"] += 1
            self.stats["decode_items"] += 1
            t0 = time.perf_counter()
            st = np.stack([np.frombuffer(s, dtype=np.uint8)
                           for s in shards])
            # lint: ignore[GL10] host-inline fast path is gated to <= _INLINE_DECODE_MAX_BYTES stripes; the flagged open chain is the one-time native build, cached for the process lifetime
            data = native.gf_matmul(
                rs.decode_matrix(codec.k, codec.m, present), st)
            out = rs.join_stripe(data, plain_len)
            self._record("decode", "host", total,
                         time.perf_counter() - t0)
            return out
        return await self._submit("decode", (present, list(shards),
                                             plain_len))

    async def repair(self, present, missing, shards: list) -> dict:
        """Rebuild the `missing` shard payloads of one stripe from the
        k `present` ones -> {missing_index: payload}. The resync /
        scrub rebuild twin of decode — concurrent rebuilds across a
        wave batch into one launch (grouped by len(missing), since one
        launch needs a uniform output row count)."""
        if self.codec is None:
            raise RuntimeError("feeder has no codec")
        codec = self.codec
        width = codec.k + codec.m
        present = self._check_stripe(present, shards, codec.k, width)
        missing = tuple(missing)
        if not missing:
            return {}
        if any(not 0 <= int(i) < width for i in missing):
            raise ValueError(
                f"missing indices must be in [0, {width}); got {missing}")
        total = sum(len(s) for s in shards)
        if total <= _INLINE_DECODE_MAX_BYTES \
                and self._host_inline_ok("decode"):
            from .. import native
            from ..ops import rs

            self.stats["inline_items"] += 1
            self.stats["decode_items"] += 1
            t0 = time.perf_counter()
            st = np.stack([np.frombuffer(s, dtype=np.uint8)
                           for s in shards])
            # lint: ignore[GL10] host-inline fast path is gated to <= _INLINE_DECODE_MAX_BYTES stripes; the flagged open chain is the one-time native build, cached for the process lifetime
            out = native.gf_matmul(
                rs.repair_matrix(codec.k, codec.m, present, missing), st)
            self._record("decode", "host", total,
                         time.perf_counter() - t0)
            return {mi: bytes(out[j]) for j, mi in enumerate(missing)}
        return await self._submit("repair", (present, missing,
                                             list(shards)))

    # ---- dispatcher ----------------------------------------------------

    async def _run(self) -> None:
        while True:
            first = await self._q.get()
            batch = [first]
            try:
                # greedy non-waiting drain: whatever queued while the
                # last batch was on the device becomes the next batch
                while not self._q.empty() \
                        and len(batch) < self.max_batch:
                    batch.append(self._q.get_nowait())
                n_same = sum(1 for it in batch if it.op == first.op)
                want = min(self.active_streams, 8)
                if first.op in _LINGER_OPS and self.batch_linger > 0 \
                        and self.active_streams > 1 and n_same < want:
                    # several PUT streams are mid-block-loop: a short
                    # async gather window lets their next submissions
                    # line up, multiplying the batch lane count (MD5
                    # AVX lanes, SHA-256 device rows, encode stripes).
                    # The wait burns no CPU — the event loop spends it
                    # reading the OTHER streams' sockets, which is
                    # exactly what gets them here. Only items matching
                    # the head op count toward the lane target; budget
                    # is [tpu] batch_linger_ms.
                    loop = asyncio.get_running_loop()
                    deadline = loop.time() + self.batch_linger
                    while n_same < want:
                        left = deadline - loop.time()
                        if left <= 0:
                            break
                        try:
                            item = await asyncio.wait_for(
                                self._q.get(), left)
                        except asyncio.TimeoutError:
                            break
                        batch.append(item)
                        if item.op == first.op:
                            n_same += 1
                self._maybe_start_probe()
                # bounded in-flight depth: the dispatcher hands the
                # batch to the staged pipeline and goes straight back
                # to draining the queue / forming the next batch —
                # while batch N computes, batch N+1 stages h2d and
                # batch N-1 reads back. Depth is live-tunable
                # ([tpu] inflight_batches via /v1/s3/tuning).
                while len(self._inflight_tasks) >= max(
                        1, self.inflight_batches):
                    await asyncio.wait(self._inflight_tasks,
                                       return_when=asyncio.FIRST_COMPLETED)
                t = asyncio.create_task(self._finish_batch(batch),
                                        name="feeder-batch")
                self._inflight_tasks.add(t)
                t.add_done_callback(self._inflight_tasks.discard)
            except BaseException as e:
                for item in batch:
                    if not item.future.done():
                        item.future.set_exception(
                            e if not isinstance(e, asyncio.CancelledError)
                            else RuntimeError("feeder stopped"))
                if isinstance(e, asyncio.CancelledError):
                    raise

    async def _finish_batch(self, batch: list) -> None:
        """Run one batch through plan + execution and resolve every
        item future — the one owner of a batch's futures, whatever the
        route (host thread, staged device pipeline, hang fallback)."""
        try:
            results = await self._run_batch_staged(batch)
            for item, res in zip(batch, results):
                if not item.future.done():
                    if isinstance(res, BaseException):
                        item.future.set_exception(res)
                    else:
                        item.future.set_result(res)
        except BaseException as e:
            for item in batch:
                if not item.future.done():
                    item.future.set_exception(
                        e if not isinstance(e, asyncio.CancelledError)
                        else RuntimeError("feeder stopped"))
            if isinstance(e, asyncio.CancelledError):
                raise

    async def _run_batch_staged(self, batch: list) -> list:
        """Plan the batch, then execute host legs in a worker thread
        and device legs through the staged pipeline, concurrently."""
        self.stats["batches"] += 1
        self.stats["items"] += len(batch)
        self.stats["decode_items"] += sum(
            1 for it in batch if it.op in ("decode", "repair"))
        self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
        results: list = [None] * len(batch)
        legs = self._plan_batch(batch)
        host_legs = [leg for leg in legs if leg[3] != "device"]
        device_legs = [leg for leg in legs if leg[3] == "device"]
        if not device_legs:
            # pure host batch: exactly the pre-pipeline behavior (one
            # thread hop), still bounded so a stalled host path fails
            # the batch instead of wedging a pipeline slot forever
            await asyncio.wait_for(
                asyncio.to_thread(self._exec_legs, batch, legs, results),
                self.batch_timeout)
            return results
        tasks = [asyncio.create_task(
            self._exec_device_leg(op, perf_op, batch, idxs, results))
            for op, perf_op, idxs, _b in device_legs]
        if host_legs:
            tasks.append(asyncio.create_task(asyncio.wait_for(
                asyncio.to_thread(self._exec_legs, batch, host_legs,
                                  results),
                self.batch_timeout)))
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        return results

    async def _exec_device_leg(self, op: str, perf_op: str, batch: list,
                               idxs: list, results: list) -> None:
        """One device-routed op group: staged h2d -> compute -> d2h
        with the watchdog over ALL stages; a hang disables the device
        path and re-runs this group host-side, a plain device failure
        (dead tunnel, OOM, XLA error) falls back to the host with a
        calibration penalty — either way every item gets a result."""
        blobs = [batch[i].data for i in idxs]
        total = group_bytes(op, blobs)
        self._window_open()
        try:
            try:
                out, busy = await asyncio.wait_for(
                    self._staged_op(op, blobs), self.batch_timeout)
            except (asyncio.TimeoutError, _DeviceHang):
                # hung device stage (the axon tunnel can block inside
                # XLA calls): abandon the stuck stage threads, disable
                # the device path, and re-run EVERY in-flight batch's
                # device legs host-side — the abort event makes
                # sibling batches take this same branch immediately
                # instead of each waiting out its own watchdog.
                self._on_device_hang()
                await asyncio.wait_for(
                    asyncio.to_thread(self._exec_group, op, perf_op,
                                      batch, idxs, "host", results),
                    self.batch_timeout)
                return
            except Exception as e:
                log.warning("device %s batch failed (%s: %s); "
                            "falling back to host", op,
                            type(e).__name__, e)
                self._record(perf_op, "device", 0, 60.0)
                await asyncio.wait_for(
                    asyncio.to_thread(self._exec_group, op, perf_op,
                                      batch, idxs, "host", results),
                    self.batch_timeout)
                return
            for i, o in zip(idxs, out):
                results[i] = o
            # calibration records the EXCLUSIVE stage execution time,
            # not this coroutine's wall: wall includes queue wait
            # behind sibling batches in the single-thread stage
            # executors, which would understate device throughput by
            # up to the in-flight depth and flip routing back to host
            # precisely because pipelining engaged
            self._record(perf_op, "device", total, busy)
            self.stats["device_batches"] += 1
            self.stats["device_items"] += len(idxs)
            self.stats["device_bytes"] += total
            if op in ("decode", "repair"):
                self.stats["decode_device_items"] += len(idxs)
                self.stats["decode_device_bytes"] += total
        finally:
            self._window_close()

    async def _staged_op(self, op: str, blobs: list) -> tuple[list, float]:
        """h2d -> compute -> d2h through the current pipeline
        generation's stage threads; -> (results, exclusive device
        seconds). Each stage of THIS batch runs serially, but the
        single-thread-per-stage executors let a different batch occupy
        every other stage at the same time."""
        pl = self._pipeline()
        be = self._get_backend
        busy: list[float] = []
        staged = await self._stage_call(
            pl, "h2d", lambda: be().stage(op, blobs), busy)
        handle = await self._stage_call(
            pl, "compute", lambda: be().compute(op, staged), busy)
        out = await self._stage_call(
            pl, "d2h", lambda: be().readback(op, handle), busy)
        return out, sum(busy)

    async def _stage_call(self, pl: DevicePipeline, stage: str, fn,
                          busy: list):
        if pl.dead:
            raise _DeviceHang("pipeline aborted")
        loop = asyncio.get_running_loop()
        job = pl.submit(stage, loop, fn)
        abort = asyncio.create_task(pl.aborted.wait())
        try:
            await asyncio.wait({job.fut, abort},
                               return_when=asyncio.FIRST_COMPLETED)
            if not job.fut.done() and job.claimed:
                # the stage thread is ALREADY EXECUTING this job (the
                # hung job never yields its thread, so ours is live):
                # wait it out instead of racing a host re-run against
                # its side effects — d2h advances the serial MD5 ETag
                # chains, and abandoning it mid-flight would apply
                # them twice. Still bounded by the caller's watchdog.
                await asyncio.wait({job.fut})
            if job.fut.done():
                busy.append(job.busy)
                return job.fut.result()
            raise _DeviceHang("pipeline aborted by a sibling batch hang")
        finally:
            abort.cancel()
            if not job.fut.done():
                # abandon: a queued job is skipped outright by the
                # stage thread (never executed), a claimed one
                # completes silently with its delivery dropped
                job.fut.cancel()

    # ---- pipeline lifecycle + overlap accounting (loop thread) ---------

    def _pipeline(self) -> DevicePipeline:
        if self._pl is None or self._pl.dead:
            self._pl = DevicePipeline(self._pl_busy)
        return self._pl

    def _on_device_hang(self) -> None:
        """First watchdog to fire wins: mark the generation dead (the
        stuck daemon threads are abandoned, never joined), wake every
        sibling batch via the abort event, disable the device path and
        poison the shared probe cache so co-located feeders don't each
        pay the full watchdog timeout themselves."""
        pl = self._pl
        if pl is None or pl.dead:
            return  # a sibling already handled this hang
        pl.dead = True
        pl.aborted.set()
        log.error("feeder batch stuck >%ss; disabling device "
                  "path and re-running host-side", self.batch_timeout)
        self._device_ok = False
        if self.mode != "require":
            # thread: poison blocks on _probe_lock if a probe is
            # mid-flight, and this is the loop
            threading.Thread(
                target=poison_probe_cache,
                args=(f"device batch stuck >{self.batch_timeout}s",),
                daemon=True).start()

    def _window_open(self) -> None:
        if self._win_open == 0:
            self._win_t0 = time.monotonic()
        self._win_open += 1

    def _window_close(self) -> None:
        self._win_open -= 1
        if self._win_open == 0:
            self._pl_wall += time.monotonic() - self._win_t0

    def pipeline_stats(self) -> dict:
        """Overlap observability (admin /metrics + bench): per-stage
        busy seconds, the wall-clock union of in-flight windows, and
        busy/wall — > 1.0 means stages of different batches really ran
        concurrently (the double-buffering proof)."""
        busy = {k: round(v, 6) for k, v in self._pl_busy.items()}
        wall = self._pl_wall
        if self._win_open > 0:
            wall += time.monotonic() - self._win_t0
        total = sum(self._pl_busy.values())
        return {"busy_s": busy, "wall_s": round(wall, 6),
                "overlap_efficiency": round(total / wall, 3) if wall > 0
                else 0.0,
                "inflight": len(self._inflight_tasks)}

    # ---- batch execution (worker thread) -------------------------------

    def _pick_backend(self, op: str, total_bytes: int,
                      n_items: int) -> tuple[str, bool]:
        """-> (backend, trial). trial=True marks an exploration of the
        currently-losing backend: _run_batch caps that slice to
        _TRIAL_MAX_ITEMS and runs the rest on the winner."""
        if self.mode == "require":
            return "device", False  # forced: proof of the device path
        if self._device_ok is not True or self._calibrating:
            return "host", False
        if self._force_device.pop(op, False):
            return "device", True  # inline fast-path escape: re-probe now
        if op == "decode":
            # the read-side floors ([tpu] device_min_decode_*): degraded
            # GETs are latency-sensitive, so lone decodes stay host
            min_bytes, min_items = (self.device_min_decode_bytes,
                                    self.device_min_decode_items)
        else:
            min_bytes, min_items = (self.device_min_bytes,
                                    self.device_min_items)
        if total_bytes < min_bytes and n_items < min_items:
            return "host", False  # tiny batches never amortize a round trip
        dev_rate, host_rate = self._rates(op)
        if dev_rate is None:
            return "device", False  # first sizeable batch: measure it
        if host_rate is None:
            return "host", False
        if self._explore_due(op):
            # periodic re-probe of whichever backend is currently losing
            return ("device" if dev_rate < host_rate else "host"), True
        return ("device" if dev_rate >= host_rate else "host"), False

    def _record(self, op: str, backend: str, nbytes: int, dt: float) -> None:
        with self._perf_lock:  # inline paths record from the loop thread
            ent = self._perf.setdefault((op, backend), [0.0, 0.0])
            # exponential forgetting so old (cold-compile) samples fade
            if ent[1] > 30.0:
                ent[0] *= 0.5
                ent[1] *= 0.5
            ent[0] += nbytes
            ent[1] += max(dt, 1e-6)

    def _plan_batch(self, batch: list[_Item], force_host: bool = False
                    ) -> list[tuple]:
        """-> [(op, perf_op, idxs, backend)] legs, trial splits applied
        — the routing brain shared by the staged pipeline (async) and
        the synchronous host paths (hang re-run, direct callers)."""
        by_op: dict[str, list[int]] = {}
        for i, item in enumerate(batch):
            by_op.setdefault(item.op, []).append(i)
        legs: list[tuple] = []
        for op, idxs in by_op.items():
            total = group_bytes(op, [batch[i].data for i in idxs])
            perf_op = ("hash" if op in ("verify", "hash_md5") else
                       "encode" if op == "encode_put" else
                       "parity" if op == "parity_check" else
                       "decode" if op == "repair" else op)
            host_only = force_host
            if perf_op == "hash":
                from ..utils import data as _data

                if _data._content_algo != "blake3":
                    host_only = True  # blake2 never runs on device
            if host_only:
                backend, trial = "host", False
            else:
                backend, trial = self._pick_backend(perf_op, total,
                                                    len(idxs))
            cut = self._trial_cut(op, batch, idxs) if trial else len(idxs)
            if cut < len(idxs):
                # exploration of the losing backend: one small timing
                # sample there, the bulk stays on the winner
                other = "host" if backend == "device" else "device"
                legs.append((op, perf_op, idxs[:cut], backend))
                legs.append((op, perf_op, idxs[cut:], other))
            else:
                legs.append((op, perf_op, idxs, backend))
        return legs

    def _exec_legs(self, batch: list, legs: list, results: list) -> None:
        for op, perf_op, idxs, backend in legs:
            self._exec_group(op, perf_op, batch, idxs, backend, results)

    def _run_batch(self, batch: list[_Item], force_host: bool = False
                   ) -> list:
        """Synchronous (worker-thread) batch execution — the hang
        fallback and direct test/bench entry point. The live dispatcher
        routes through _run_batch_staged instead."""
        self.stats["batches"] += 1
        self.stats["items"] += len(batch)
        self.stats["decode_items"] += sum(
            1 for it in batch if it.op in ("decode", "repair"))
        self.stats["max_batch"] = max(self.stats["max_batch"], len(batch))
        results: list = [None] * len(batch)
        self._exec_legs(batch, self._plan_batch(batch, force_host), results)
        return results

    def _trial_cut(self, op: str, batch: list, idxs: list) -> int:
        """Items in the exploration slice: at least trial_max_items,
        growing to trial_items_cap while under trial_max_bytes."""
        cut, size = 0, 0
        for i in idxs:
            if cut >= self.trial_max_items and (
                    size >= self.trial_max_bytes
                    or cut >= self.trial_items_cap):
                break
            d = batch[i].data
            if op in ("verify", "encode_put", "hash_md5") \
                    and isinstance(d, tuple):
                d = d[1]
            if hasattr(d, "total_len"):
                size += d.total_len
                cut += 1
                continue
            if op == "parity_check":
                size += sum(len(b) for b in d)
            elif op == "sha256" and isinstance(d, (list, tuple)):
                size += sum(len(b) for b in d)  # span-list message
            elif op == "decode":
                size += sum(len(b) for b in d[1])
            elif op == "repair":
                size += sum(len(b) for b in d[2])
            else:
                size += len(d) if isinstance(d, (bytes, bytearray,
                                                 memoryview)) else 0
            cut += 1
        return cut

    def _exec_group(self, op: str, perf_op: str, batch: list,
                    idxs: list, backend: str, results: list) -> None:
        blobs = [batch[i].data for i in idxs]
        total = group_bytes(op, blobs)
        t0 = time.perf_counter()
        try:
            try:
                out = self._do_op(op, blobs, backend)
            except Exception as e:
                if backend != "device":
                    raise
                # a failing device (dead tunnel, OOM, XLA error) must
                # not fail requests while the host path works: retry
                # host-side and penalize the device in calibration
                log.warning("device %s batch failed (%s: %s); "
                            "falling back to host", op,
                            type(e).__name__, e)
                self._record(perf_op, "device", 0, 60.0)
                backend = "host"
                t0 = time.perf_counter()
                out = self._do_op(op, blobs, backend)
            for i, o in zip(idxs, out):
                results[i] = o
            self._record(perf_op, backend, total,
                         time.perf_counter() - t0)
            if backend == "device":
                self.stats["device_batches"] += 1
                self.stats["device_items"] += len(idxs)
                if op in ("decode", "repair"):
                    self.stats["decode_device_items"] += len(idxs)
                    self.stats["decode_device_bytes"] += total
        except Exception as e:
            for i in idxs:
                results[i] = e

    def _do_op(self, op: str, blobs: list, backend: str) -> list:
        if op == "hash":
            return self._do_hash(blobs, backend)
        if op == "hash_md5":
            from .. import native

            if backend == "device":
                # content hash batches to the device FIRST: if it
                # raises (dead tunnel), the host retry re-runs this op
                # from scratch, and MD5 state must not have advanced
                # yet or the retry double-counts the bytes into the
                # ETag chain. Only then batch-advance the MD5s host-
                # side (8-way across items).
                out = self._do_hash([d for _, d in blobs], backend)
                native.md5_update_many(list(blobs))
                return out
            return native.b3_md5_many(list(blobs))
        if op == "sha256":
            return self._do_sha256(blobs, backend)
        if op == "verify":
            digs = self._do_hash([b for _, b in blobs], backend)
            return _verify_matches(digs, blobs)
        if op == "encode":
            return self._do_encode(blobs, backend)
        if op == "encode_put":
            return self._do_encode_put(blobs, backend)
        if op == "parity_check":
            return self._do_parity_check(blobs, backend)
        if op == "decode":
            return self._do_decode(blobs, backend)
        if op == "repair":
            return self._do_repair(blobs, backend)
        raise RuntimeError(f"unknown feeder op {op!r}")

    def _do_hash(self, blobs: list[bytes], backend: str) -> list[bytes]:
        from ..utils import data as _data

        if _data._content_algo != "blake3":
            return [_data.content_hash(b) for b in blobs]
        if backend == "device":
            from ..ops import treehash

            return treehash.blake3_many(blobs)
        try:
            from .. import native

            if native.available():
                return native.blake3_many(blobs)
        except Exception:
            # lint: ignore[GL05] native backend optional; pure-python fallback follows
            pass
        from ..utils.data import blake3sum

        return [blake3sum(b) for b in blobs]

    def _do_sha256(self, blobs: list, backend: str) -> list[str]:
        """SigV4 chunk digests (hex) — independent across items, so the
        whole group is one device launch (ops/sha256) or a host loop."""
        from ..ops import sha256 as _sha

        if backend == "device":
            return _sha.sha256_hex_many(blobs)
        return [_sha.sha256_hex_py(b) for b in blobs]

    def _do_encode_put(self, items: list, backend: str
                       ) -> list[list]:
        """items = [(prefix, data)] or ingest leases (scheme byte + body
        resident in one pool buffer); like _do_encode but each part is a
        complete shard payload (pack_shard framing, crc32c). Host+native
        is the PUT hot path."""
        from .manager import pack_shard

        codec = self.codec
        if backend != "device":
            try:
                from .. import native

                if native.available():
                    from ..ops import rs

                    pmat = rs.parity_matrix(codec.k, codec.m)
                    out = []
                    for it in items:
                        if hasattr(it, "stripe"):
                            out.append(native.rs_encode_packed(
                                it.view(), codec.k, codec.m, pmat,
                                prefix=bytes([it.buf[0]])))
                        else:
                            out.append(native.rs_encode_packed(
                                it[1], codec.k, codec.m, pmat,
                                prefix=it[0]))
                    return out
            except Exception:
                # lint: ignore[GL05] native backend optional; _do_encode fallback follows
                pass
        # device, or host without native: delegate the encode itself to
        # _do_encode (single source of truth) and wrap with pack_shard.
        # Leases materialize here — the non-native fallback is off the
        # perf path, and _do_encode wants plain byte blocks.
        blocks = [bytes(it.buf[:it.total_len]) if hasattr(it, "total_len")
                  else it[0] + it[1] for it in items]
        parts_lists = (codec.encode_batch(blocks) if backend == "device"
                       else self._do_encode(blocks, backend))
        return [[pack_shard(pp, len(b)) for pp in parts]
                for b, parts in zip(blocks, parts_lists)]

    def _do_encode(self, blocks: list[bytes], backend: str
                   ) -> list[list[bytes]]:
        from ..ops import rs

        codec = self.codec
        if backend == "device":
            return codec.encode_batch(blocks)
        try:
            from .. import native

            if native.available():
                out = []
                for b in blocks:
                    shards = rs.split_stripe(b, codec.k)
                    parity = native.gf_matmul(
                        rs.parity_matrix(codec.k, codec.m), shards)
                    out.append([bytes(s) for s in shards]
                               + [bytes(p) for p in parity])
                return out
        except Exception:
            # lint: ignore[GL05] native backend optional; numpy fallback follows
            pass
        # last resort: pure numpy — NEVER codec.encode here, whose JAX
        # path would re-enter the possibly-dead backend this host branch
        # exists to avoid
        out = []
        for b in blocks:
            shards = rs.split_stripe(b, codec.k)
            parity = rs.encode_np(codec.k, codec.m, shards)
            out.append([bytes(s) for s in shards]
                       + [bytes(p) for p in parity])
        return out

    def _do_parity_check(self, stripes: list[list[bytes]], backend: str
                         ) -> list[bool]:
        """stripes = [[k data + m parity shard payloads]] -> per-stripe
        consistency verdicts. Device: one padded (B, k+m, S) batch
        through the encode bit-matmul + compare (zero padding is safe:
        the code is linear). Host: native GF matmul per stripe, numpy
        as last resort — same no-JAX-on-host rule as _do_encode."""
        from ..ops import rs

        codec = self.codec
        k, m = codec.k, codec.m
        if backend == "device":
            smax = max(len(s[0]) for s in stripes)
            arr = np.zeros((len(stripes), k + m, smax), dtype=np.uint8)
            for i, s in enumerate(stripes):
                for j, b in enumerate(s):
                    arr[i, j, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            return [bool(v) for v in np.asarray(rs.parity_check(k, m, arr))]
        pmat = rs.parity_matrix(k, m)
        native_mod = None
        try:
            from .. import native

            if native.available():
                native_mod = native
        except Exception:
            # lint: ignore[GL05] native backend optional; numpy path handles it
            pass
        out = []
        for s in stripes:
            data = np.stack(
                [np.frombuffer(b, dtype=np.uint8) for b in s[:k]])
            parity = (native_mod.gf_matmul(pmat, data)
                      if native_mod is not None
                      else rs.encode_np(k, m, data))
            out.append(all(bytes(parity[j]) == bytes(s[k + j])
                           for j in range(m)))
        return out

    @staticmethod
    def _native_or_none():
        """The optional native kernel module, or None — one copy of the
        guarded import the host legs share."""
        try:
            from .. import native

            if native.available():
                return native
        except Exception:
            # lint: ignore[GL05] native backend optional; numpy path handles it
            pass
        return None

    def _do_decode(self, items: list[tuple], backend: str) -> list[bytes]:
        """items = [(present, shards, plain_len)] -> packed block bytes
        per item. Device: the batched pattern-as-data launch (one
        compiled program per shape — the per-item decode matrices ride
        as data). Host: native GF matmul per stripe, numpy as last
        resort — same no-JAX-on-host rule as _do_encode."""
        from ..ops import rs

        codec = self.codec
        k, m = codec.k, codec.m
        if backend == "device":
            return self._device_gf_batched("decode", items)
        native_mod = self._native_or_none()
        out = []
        for present, shards, plain_len in items:
            present = tuple(present)
            st = np.stack([np.frombuffer(s, dtype=np.uint8)
                           for s in shards])
            if all(i < k for i in present):
                data = st  # all-systematic: no math needed
            elif native_mod is not None:
                data = native_mod.gf_matmul(
                    rs.decode_matrix(k, m, present), st)
            else:
                data = rs.decode_np(k, m, present, st)
            out.append(rs.join_stripe(data, plain_len))
        return out

    def _device_gf_batched(self, op: str, items: list[tuple]) -> list:
        """Synchronous-path device decode/repair: ONE padded
        pattern-as-data launch per output-row group (the calibration /
        sync-_run_batch twin of the backend's _stage_gf). Shapes pad up
        the same bucket ladder, so the compiled programs are shared
        with the staged route instead of jitting one B=1 program per
        distinct shard length and paying N serial round-trips."""
        from .device_backend import bucket_items, bucket_len
        from ..ops import rs

        codec = self.codec
        k, m = codec.k, codec.m
        shards_of = ((lambda it: it[1]) if op == "decode"
                     else (lambda it: it[2]))
        groups: dict[int, list[int]] = {}
        for i, it in enumerate(items):
            rows = k if op == "decode" else len(it[1])
            groups.setdefault(rows, []).append(i)
        results: list = [None] * len(items)
        for rows, idxs in groups.items():
            slens = [len(shards_of(items[i])[0]) for i in idxs]
            smax = bucket_len(max(slens))
            bpad = bucket_items(len(idxs), self.pad_buckets)
            batch = np.zeros((bpad, k, smax), dtype=np.uint8)
            mats = np.zeros((bpad, 8 * k, 8 * rows), dtype=np.int8)
            for row, i in enumerate(idxs):
                it = items[i]
                present = tuple(it[0])
                for j, s in enumerate(shards_of(it)):
                    batch[row, j, : len(s)] = np.frombuffer(
                        s, dtype=np.uint8)
                mats[row] = (rs.decode_bitmat_t(k, m, present)
                             if op == "decode"
                             else rs.repair_bitmat_t(k, m, present,
                                                     tuple(it[1])))
            out = np.asarray(rs.gf_apply_batched(mats, batch))
            for row, i in enumerate(idxs):
                sl = slens[row]
                if op == "decode":
                    results[i] = rs.join_stripe(out[row, :, :sl],
                                                items[i][2])
                else:
                    results[i] = {
                        mi: bytes(out[row, j, :sl])
                        for j, mi in enumerate(tuple(items[i][1]))}
        return results

    def _do_repair(self, items: list[tuple], backend: str) -> list[dict]:
        """items = [(present, missing, shards)] -> {missing_index:
        payload} per item (the resync/scrub rebuild op)."""
        from ..ops import rs

        codec = self.codec
        k, m = codec.k, codec.m
        if backend == "device":
            return self._device_gf_batched("repair", items)
        out = []
        native_mod = self._native_or_none()
        for present, missing, shards in items:
            present, missing = tuple(present), tuple(missing)
            st = np.stack([np.frombuffer(s, dtype=np.uint8)
                           for s in shards])
            rows = (native_mod.gf_matmul(
                        rs.repair_matrix(k, m, present, missing), st)
                    if native_mod is not None
                    else rs.repair_np(k, m, present, missing, st))
            out.append({mi: bytes(rows[j])
                        for j, mi in enumerate(missing)})
        return out
