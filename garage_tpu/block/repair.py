"""Scrub and repair workers for the block store.

Ref parity: src/block/repair.rs. ScrubWorker reads every stored
block/shard, verifies integrity (whole blocks: blake2 of plain content;
shards: header checksum + optional cross-shard parity check through the
TPU RS math), quarantines corrupt files and queues resync. RepairWorker
is the one-shot full pass: every RC-known and every on-disk block gets a
resync examination (used after disasters / layout surgery).

The scrub cursor persists so a restart resumes mid-pass
(ref: repair.rs:169-232 persisted BlockStoreIterator).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import random
import time

from ..utils import migrate
from .block import BLOCK_SUFFIXES, comp_of_path
from ..utils.background import Throttled, Worker, WorkerInfo, WState
from ..utils.persister import Persister

log = logging.getLogger("garage_tpu.block.repair")

SCRUB_INTERVAL = 25 * 86400.0  # ~25 days, ref: repair.rs:24-27


class ScrubState(migrate.Migratable):
    VERSION_MARKER = b"GTscrb01"

    def __init__(self, cursor: bytes = b"", last_completed: float = 0.0,
                 corruptions: int = 0, tranquility: float = 4.0,
                 paused: bool = False):
        self.cursor = cursor
        self.last_completed = last_completed
        self.corruptions = corruptions
        self.tranquility = tranquility
        self.paused = paused

    def pack(self):
        return [self.cursor, self.last_completed, self.corruptions,
                self.tranquility, self.paused]

    @classmethod
    def unpack(cls, o):
        return cls(*o)


class ScrubWorker(Worker):
    BATCH = 16

    def __init__(self, manager, interval: float = SCRUB_INTERVAL):
        self.manager = manager
        self.name = "block scrub"
        self.interval = interval
        self.persister = Persister(manager.system.meta_dir, "scrub_state",
                                   ScrubState)
        self.state = self.persister.load() or ScrubState()
        self._jitter = random.random() * 0.4 + 0.8  # ±20%
        self._iter = None  # live sorted walk; rebuilt from cursor on restart
        self._pending_cmd: str | None = None

    def _due(self) -> bool:
        return (time.time() - self.state.last_completed
                >= self.interval * self._jitter)

    def command(self, cmd: str) -> None:
        """Operator control (CLI `repair scrub <cmd>`). Commands are
        applied at the top of the next work() tick so they can never be
        clobbered by an in-flight batch's cursor save
        (ref: repair.rs ScrubWorkerCommand channel)."""
        if cmd not in ("start", "pause", "resume", "cancel"):
            raise ValueError(f"unknown scrub command {cmd!r}")
        self._pending_cmd = cmd

    def _apply_pending(self) -> None:
        cmd, self._pending_cmd = self._pending_cmd, None
        if cmd is None:
            return
        if cmd == "start":
            self.state.last_completed = 0.0
            self.state.cursor = b""
            self.state.paused = False
            self._iter = None
        elif cmd == "pause":
            self.state.paused = True
        elif cmd == "resume":
            self.state.paused = False
        elif cmd == "cancel":
            self.state.cursor = b""
            self._iter = None
            self.state.last_completed = time.time()
        self.persister.save(self.state)

    async def work(self):
        self._apply_pending()
        if self.state.paused or not self._due():
            return WState.IDLE
        if self._iter is None:
            # single ordered walk per pass; on restart resume after the
            # persisted cursor instead of rescanning from the front
            self._iter = self.manager.iter_local_blocks_sorted(
                self.state.cursor
            )

        def pull_batch():
            batch = []
            for h in self._iter:
                batch.append(h)
                if len(batch) >= self.BATCH:
                    break
            return batch

        try:
            batch = await asyncio.to_thread(pull_batch)
        except Exception:
            self._iter = None  # re-derive from cursor on retry
            raise
        if not batch:
            self._iter = None
            self.state.cursor = b""
            self.state.last_completed = time.time()
            self.persister.save(self.state)
            log.info("scrub pass complete, %d corruptions total",
                     self.state.corruptions)
            return WState.IDLE
        t0 = time.monotonic()
        try:
            bad = await self.scrub_batch(batch)
        except Exception:
            # the live iterator has advanced past this batch; drop it so
            # the retry re-derives the batch from the persisted cursor
            self._iter = None
            raise
        self.state.corruptions += bad
        self.state.cursor = batch[-1]
        self.persister.save(self.state)
        dt = time.monotonic() - t0
        if self.state.tranquility > 0:
            return Throttled(self.state.tranquility * dt / max(len(batch), 1))
        return WState.BUSY

    async def scrub_batch(self, batch: list[bytes]) -> int:
        """Verify a batch; returns number of corrupt blocks.

        Whole blocks verify as ONE batched content-hash pass through the
        device feeder (the TPU replacement for the reference's
        block-at-a-time rehash loop, src/block/repair.rs:169-528);
        erasure shards verify their per-shard header checksums host-side
        (cheap blake2 over the shard file)."""
        m = self.manager
        if m.erasure:
            return await asyncio.to_thread(
                lambda: sum(0 if self._scrub_shards(h) else 1 for h in batch)
            )

        def read_all():
            out = []
            for h in batch:
                p = m._find(h, BLOCK_SUFFIXES)
                if p is None:
                    out.append((h, None, None))
                    continue
                try:
                    with open(p, "rb") as f:
                        raw = f.read()
                    from .block import DataBlock

                    blk = DataBlock(comp_of_path(p), raw)
                    out.append((h, p, blk.plain_bytes()))
                except Exception:
                    out.append((h, p, None))  # unreadable = corrupt
            return out

        reads = await asyncio.to_thread(read_all)
        to_verify = [(h, plain) for h, p, plain in reads if plain is not None]
        oks = await m.feeder.verify_blocks(to_verify)
        ok_of = {h: ok for (h, _), ok in zip(to_verify, oks)}
        bad = 0
        for h, p, plain in reads:
            if plain is None:
                if p is not None:
                    await asyncio.to_thread(m._quarantine, p, h)
                    bad += 1
                # p is None: block not stored here (moved) — not corrupt
            elif not ok_of.get(h, False):
                await asyncio.to_thread(m._quarantine, p, h)
                bad += 1
        return bad

    def _scrub_shards(self, hash32: bytes) -> bool:
        m = self.manager
        ok = True
        for part in m.local_parts(hash32):
            if m.read_local_shard(hash32, part) is None:
                ok = False
        return ok

    async def wait_for_work(self):
        await asyncio.sleep(60.0)

    def info(self):
        from ..utils.background import WorkerInfo

        return WorkerInfo(
            name=self.name,
            progress=self.state.cursor[:4].hex() if self.state.cursor else "-",
            tranquility=int(self.state.tranquility),
        )


class RebalanceWorker(Worker):
    """One-shot: move every stored block/shard file whose primary data
    dir changed (multi-HDD layout update) to its new primary dir
    (ref: src/block/repair.rs:531-640 RebalanceWorker). Walks all
    candidate dirs; a file found outside its primary location is moved
    (tmp+rename within the target dir); duplicate copies left by an
    interrupted earlier pass are deduped in favour of the primary."""

    def __init__(self, manager):
        self.manager = manager
        self.name = "block rebalance"
        self._iter = None
        self.moved = 0
        self.freed_bytes = 0

    def _rebalance_batch(self, hashes: list[bytes]) -> None:
        m = self.manager
        lay = m.data_layout
        for h in hashes:
            primary = lay.primary_dir(h)
            for d in lay.candidate_dirs(h):
                if d == primary or not os.path.isdir(d):
                    continue
                pre = h.hex()
                for fn in os.listdir(d):
                    if not fn.startswith(pre) or ".tmp" in fn \
                            or fn.endswith(".corrupted"):
                        continue
                    src = os.path.join(d, fn)
                    dst = os.path.join(primary, fn)
                    try:
                        size = os.path.getsize(src)
                        if os.path.exists(dst):
                            # stray copy: only drop it if the primary
                            # copy is intact (size match) — a crash
                            # mid-copy can leave a truncated dst, and
                            # deleting src then would lose the block
                            if os.path.getsize(dst) == size:
                                os.remove(src)
                                self.freed_bytes += size
                            else:
                                self._copy_over(src, dst)
                                os.remove(src)
                                self.moved += 1
                                self.freed_bytes += size
                            continue
                        os.makedirs(primary, exist_ok=True)
                        # same-FS fast path; cross-FS needs copy+rename
                        try:
                            os.rename(src, dst)
                        except OSError:
                            self._copy_over(src, dst)
                            os.remove(src)
                        self.moved += 1
                        self.freed_bytes += size
                    except OSError as e:
                        log.warning("rebalance of %s failed: %s", src, e)

    def _copy_over(self, src: str, dst: str) -> None:
        """Durable cross-FS copy: tmp + (optional) fsync + rename, the
        same discipline as BlockManager._write_file."""
        tmp = dst + f".tmp-rb{os.getpid()}"
        with open(src, "rb") as fsrc, open(tmp, "wb") as fdst:
            fdst.write(fsrc.read())
            if self.manager.fsync:
                fdst.flush()
                os.fsync(fdst.fileno())
        os.replace(tmp, dst)
        if self.manager.fsync:
            dirfd = os.open(os.path.dirname(dst), os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)

    async def work(self):
        m = self.manager
        if self._iter is None:
            self._iter = m.iter_local_blocks_sorted()
        batch = list(itertools.islice(self._iter, 64))
        if not batch:
            return WState.DONE
        await asyncio.to_thread(self._rebalance_batch, batch)
        return WState.BUSY

    def info(self):
        inf = WorkerInfo(name=self.name)
        inf.progress = (f"moved {self.moved}, "
                        f"freed {self.freed_bytes // (1 << 20)} MiB")
        return inf


class RepairWorker(Worker):
    """One-shot: resync-examine every block we know of
    (ref: repair.rs:35-165)."""

    def __init__(self, manager):
        self.manager = manager
        self.name = "block repair"
        self._phase = 0  # 0: rc table, 1: disk, 2: done
        self._iter = None

    async def work(self):
        m = self.manager
        if self._phase == 0:
            if self._iter is None:
                self._iter = m.rc.all_hashes()
            n = 0
            for h in self._iter:
                m.resync.push_now(h)
                n += 1
                if n >= 256:
                    return WState.BUSY
            self._phase, self._iter = 1, None
            return WState.BUSY
        if self._phase == 1:
            if self._iter is None:
                self._iter = m.iter_local_blocks()
            n = 0
            for h, _ in self._iter:
                m.resync.push_now(h)
                n += 1
                if n >= 256:
                    return WState.BUSY
            self._phase = 2
        return WState.DONE
