"""Scrub and repair workers for the block store.

Ref parity: src/block/repair.rs. ScrubWorker reads every stored
block/shard, verifies integrity (whole blocks: blake2 of plain content;
shards: header checksum + optional cross-shard parity check through the
TPU RS math), quarantines corrupt files and queues resync. RepairWorker
is the one-shot full pass: every RC-known and every on-disk block gets a
resync examination (used after disasters / layout surgery).

The scrub cursor persists so a restart resumes mid-pass
(ref: repair.rs:169-232 persisted BlockStoreIterator).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import random
import time

from ..utils import migrate
from .block import BLOCK_SUFFIXES, comp_of_path
from ..utils.background import Throttled, Worker, WorkerInfo, WState
from ..utils.metrics import registry
from ..utils.persister import Persister

log = logging.getLogger("garage_tpu.block.repair")

SCRUB_INTERVAL = 25 * 86400.0  # ~25 days, ref: repair.rs:24-27


async def gather_bounded(gather, items: list, window: int) -> list:
    """Run `gather(*item)` for every item with at most `window` in
    flight; results in item order. Deep scrub's leader sweep used an
    UNBOUNDED asyncio.gather of k-shard stripe gathers — on a large
    scrub batch that is batch×width concurrent MiB-scale fetches
    spiking RAM and RPC concurrency at once. The feeder batch size is
    the natural window: the parity-check launch downstream can't
    consume more than one batch at a time anyway, so gathering wider
    only buys memory pressure."""
    sem = asyncio.Semaphore(max(1, int(window)))

    async def one(item):
        async with sem:
            return await gather(*item)

    return await asyncio.gather(*[one(it) for it in items])


class ScrubState(migrate.Migratable):
    VERSION_MARKER = b"GTscrb01"

    def __init__(self, cursor: bytes = b"", last_completed: float = 0.0,
                 corruptions: int = 0, tranquility: float = 4.0,
                 paused: bool = False, tranquility_manual: bool = False):
        self.cursor = cursor
        self.last_completed = last_completed
        self.corruptions = corruptions
        self.tranquility = tranquility
        self.paused = paused
        # True after an operator `worker set scrub-tranquility`:
        # PERSISTED, so the qos governor keeps its hands off the knob
        # across restarts until explicitly re-enabled
        self.tranquility_manual = tranquility_manual

    def pack(self):
        return [self.cursor, self.last_completed, self.corruptions,
                self.tranquility, self.paused, self.tranquility_manual]

    @classmethod
    def unpack(cls, o):
        return cls(*o)


class ScrubWorker(Worker):
    BATCH = 16

    def __init__(self, manager, interval: float = SCRUB_INTERVAL):
        self.manager = manager
        self.name = "block scrub"
        self.interval = interval
        self.persister = Persister(manager.system.meta_dir, "scrub_state",
                                   ScrubState)
        self.state = self.persister.load() or ScrubState()
        self._jitter = random.random() * 0.4 + 0.8  # ±20%
        self._iter = None  # live sorted walk; rebuilt from cursor on restart
        self._pending_cmd: str | None = None
        # erasure deep pass toggle (runtime-tunable: `worker set
        # scrub-deep 0` turns off the per-stripe gather on clusters
        # where scrub bandwidth matters more than wrong-shard detection)
        self.deep = True
        self.deep_checked = 0  # stripes parity-checked as leader
        self.deep_repaired = 0  # flagged stripes fully repaired
        self.header_repaired = 0  # shards rewritten for header rot
        # packed-tier ride (ISSUE 18): repair-leg lookups into the
        # packed segment and the hits that skipped stripe localization
        # (bench derives scrub_cache_hit_rate = hits / lookups)
        self.scrub_cache_lookups = 0
        self.scrub_cache_hits = 0

    def _due(self) -> bool:
        return (time.time() - self.state.last_completed
                >= self.interval * self._jitter)

    def command(self, cmd: str) -> None:
        """Operator control (CLI `repair scrub <cmd>`). Commands are
        applied at the top of the next work() tick so they can never be
        clobbered by an in-flight batch's cursor save
        (ref: repair.rs ScrubWorkerCommand channel)."""
        if cmd not in ("start", "pause", "resume", "cancel"):
            raise ValueError(f"unknown scrub command {cmd!r}")
        self._pending_cmd = cmd

    def _apply_pending(self) -> None:
        cmd, self._pending_cmd = self._pending_cmd, None
        if cmd is None:
            return
        if cmd == "start":
            self.state.last_completed = 0.0
            self.state.cursor = b""
            self.state.paused = False
            self._iter = None
        elif cmd == "pause":
            self.state.paused = True
        elif cmd == "resume":
            self.state.paused = False
        elif cmd == "cancel":
            self.state.cursor = b""
            self._iter = None
            self.state.last_completed = time.time()
        self.persister.save(self.state)

    async def work(self):
        self._apply_pending()
        if self.state.paused or not self._due():
            return WState.IDLE
        if self._iter is None:
            # single ordered walk per pass; on restart resume after the
            # persisted cursor instead of rescanning from the front
            self._iter = self.manager.iter_local_blocks_sorted(
                self.state.cursor
            )

        def pull_batch():
            batch = []
            for h in self._iter:
                batch.append(h)
                if len(batch) >= self.BATCH:
                    break
            return batch

        try:
            batch = await asyncio.to_thread(pull_batch)
        except Exception:
            self._iter = None  # re-derive from cursor on retry
            raise
        if not batch:
            self._iter = None
            self.state.cursor = b""
            self.state.last_completed = time.time()
            self.persister.save(self.state)
            log.info("scrub pass complete, %d corruptions total",
                     self.state.corruptions)
            return WState.IDLE
        t0 = time.monotonic()
        try:
            bad = await self.scrub_batch(batch)
        except Exception:
            # the live iterator has advanced past this batch; drop it so
            # the retry re-derives the batch from the persisted cursor
            self._iter = None
            raise
        self.state.corruptions += bad
        self.state.cursor = batch[-1]
        self.persister.save(self.state)
        dt = time.monotonic() - t0
        if self.state.tranquility > 0:
            return Throttled(self.state.tranquility * dt / max(len(batch), 1))
        return WState.BUSY

    async def scrub_batch(self, batch: list[bytes]) -> int:
        """Verify a batch; returns number of corrupt blocks.

        Whole blocks verify as ONE batched content-hash pass through the
        device feeder (the TPU replacement for the reference's
        block-at-a-time rehash loop, src/block/repair.rs:169-528).
        Erasure blocks get two passes: per-shard header checksums
        host-side (cheap, catches local bit rot), then the cross-shard
        DEEP pass (_deep_scrub) — stripes gathered by their scrub
        leader and parity-checked in feeder batches, which catches a
        shard that is internally consistent but WRONG, the class of
        corruption the reference's whole-block rehash would see and
        per-shard checksums cannot."""
        m = self.manager
        if m.erasure:
            bad = await asyncio.to_thread(
                lambda: sum(0 if self._scrub_shards(h) else 1 for h in batch)
            )
            if self.deep:
                bad += await self._deep_scrub(batch)
            return bad

        def read_all():
            out = []
            for h in batch:
                p = m._find(h, BLOCK_SUFFIXES)
                if p is None:
                    out.append((h, None, None))
                    continue
                try:
                    with open(p, "rb") as f:
                        raw = f.read()
                    from .block import DataBlock, MissingCodec

                    blk = DataBlock(comp_of_path(p), raw)
                    try:
                        out.append((h, p, blk.plain_bytes()))
                    except MissingCodec:
                        # codec wheel absent, data not corrupt: skip,
                        # never quarantine (block.py MissingCodec)
                        out.append((h, None, None))
                except Exception:
                    out.append((h, p, None))  # unreadable = corrupt
            return out

        reads = await asyncio.to_thread(read_all)
        to_verify = [(h, plain) for h, p, plain in reads if plain is not None]
        oks = await m.feeder.verify_blocks(to_verify)
        ok_of = {h: ok for (h, _), ok in zip(to_verify, oks)}
        bad = 0
        for h, p, plain in reads:
            if plain is None:
                if p is not None:
                    await asyncio.to_thread(m._quarantine, p, h)
                    bad += 1
                # p is None: block not stored here (moved) — not corrupt
            elif not ok_of.get(h, False):
                await asyncio.to_thread(m._quarantine, p, h)
                bad += 1
        return bad

    def _scrub_shards(self, hash32: bytes) -> bool:
        m = self.manager
        ok = True
        for part in m.local_parts(hash32):
            if m.read_local_shard(hash32, part) is None:
                ok = False
        return ok

    async def _deep_scrub(self, batch: list[bytes]) -> int:
        """Cross-shard parity detect + repair for erasure stripes.

        Per-shard header checksums only certify each shard file against
        itself; a shard that passes its own checksum but holds the
        wrong bytes (aborted overwrite, misplaced file, buggy writer)
        silently poisons a future decode. The stripe's scrub LEADER —
        first node of its placement, so exactly one node pays the
        gather per pass — fetches all width shards and batches them
        through feeder.parity_check: parity re-derivation (the encode
        bit-matmul) flags any inconsistent stripe in one device pass.
        Localization + repair run host-side only on flagged stripes
        (_repair_stripe). Blocks with missing shards are skipped here:
        absence is resync/repair's job, and parity over a partial
        stripe cannot tell loss from corruption."""
        from .codec import shard_nodes_of

        m = self.manager
        me = m.system.id
        v = m.system.layout_helper.current()
        leaders = []
        for h in batch:
            placement = shard_nodes_of(v, h, m.codec.width)
            if placement and placement[0] == me:
                leaders.append((h, placement))
        if not leaders:
            return 0
        # stripe gathers are independent: run them concurrently so a
        # slow holder costs the batch max(latency), not the sum — but
        # WINDOWED at the feeder batch size (gather_bounded) so a big
        # scrub batch can't fan out batch×width shard fetches at once
        gathered = await gather_bounded(
            lambda h, p: m._gather_parts(h, p, m.codec.width),
            leaders, getattr(m.feeder, "max_batch", self.BATCH))
        stripes, metas, flagged, clean = [], [], [], []
        for (h, placement), got in zip(leaders, gathered):
            if got is None:
                continue
            parts, len_candidates, lens_by_idx = got
            packed_len = len_candidates[0]  # majority vote
            self.deep_checked += 1
            stripe = [parts[i] for i in range(m.codec.width)]
            if len({len(s) for s in stripe}) != 1:
                # unequal shard lengths ARE the inconsistency (e.g. a
                # misplaced shard of another block): flag straight to
                # repair — stacking them would crash parity_check and a
                # deterministic raise here would wedge the scrub cursor
                # on this batch forever
                flagged.append((h, parts, packed_len, placement,
                                lens_by_idx))
                continue
            stripes.append(stripe)
            metas.append((h, parts, packed_len, placement, lens_by_idx))
        if stripes:
            oks = await m.feeder.parity_check(stripes)
            flagged.extend(meta for ok, meta in zip(oks, metas) if not ok)
            clean = [meta for ok, meta in zip(oks, metas) if ok]
        bad = 0
        for h, parts, packed_len, placement, lens in flagged:
            bad += 1
            repaired = await self._repair_stripe(h, parts, packed_len,
                                                 placement, lens)
            self.deep_repaired += bool(repaired)
            log.warning("deep scrub: stripe %s inconsistent (%s)",
                        h.hex()[:16],
                        "repaired" if repaired else "NOT repaired")
        # header-rot pass over parity-CLEAN stripes (ADVICE r5): the
        # packed_len field sits outside the shard checksum, so a rotted
        # header passes every local check AND the cross-shard parity
        # check (parity covers payload bytes only) — yet it poisons any
        # future decode that lands on the wrong length. Rewrite each
        # disagreeing shard (same payload, corrected header) on its
        # holder. Flagged stripes are excluded on purpose: their
        # payloads are suspect, and _repair_stripe's re-encode already
        # regenerates correct headers for everything it pushes.
        for h, parts, packed_len, placement, lens in clean:
            bad_idx = [i for i, v in lens.items() if v != packed_len]
            if not bad_idx:
                continue
            votes = sum(1 for v in lens.values() if v == packed_len)
            if votes * 2 <= len(lens):
                # no strict majority: rewriting could spread the rotted
                # value instead of fixing it — leave for the read
                # path's try-every-candidate logic and the operator
                log.warning("deep scrub: stripe %s packed_len vote tied "
                            "(%s); headers left untouched",
                            h.hex()[:16], sorted(set(lens.values())))
                continue
            # bind first, then add (GL12): `x += await ...` reads the
            # counter BEFORE the (multi-RPC) await and stores after it,
            # so a concurrent repair wave's increments in that window
            # would be lost
            repaired = await self._repair_headers(
                h, parts, packed_len, placement, bad_idx)
            self.header_repaired += repaired
        return bad

    async def _repair_headers(self, hash32: bytes, parts: dict[int, bytes],
                              packed_len: int, placement: list[bytes],
                              bad_idx: list[int]) -> int:
        """Push a rewritten shard (held payload, majority packed_len
        header) to every holder whose header disagreed; -> shards
        fixed."""
        from ..net.message import PRIO_BACKGROUND
        from .manager import pack_shard

        fixed = 0
        for i in bad_idx:
            try:
                await self.manager.endpoint.call(
                    placement[i],
                    {"op": "put", "hash": hash32, "part": i,
                     # lint: ignore[GL10] pack_shard's crc is native-C microseconds; the flagged open/cc chain is the one-time kernel build, cached for the process lifetime
                     "data": pack_shard(parts[i], packed_len)},
                    PRIO_BACKGROUND, timeout=60.0)
                fixed += 1
                log.warning("deep scrub: rewrote rotted header of shard "
                            "%d of %s (packed_len -> %d)", i,
                            hash32.hex()[:16], packed_len)
            except Exception as e:
                log.warning("deep scrub: header rewrite of shard %d of "
                            "%s failed (%s)", i, hash32.hex()[:16], e)
        return fixed

    async def _repair_stripe(self, hash32: bytes, parts: dict[int, bytes],
                             packed_len: int, placement: list[bytes],
                             lens: dict[int, int] | None = None) -> bool:
        """Find + fix the corrupt shard(s) of a parity-inconsistent
        stripe. Ground truth is the block's content address: a decode
        from a candidate k-subset is right iff the unpacked block
        hashes to hash32. Tries the packed-bytes tier first (ISSUE 18
        — the cached image IS the stripe's source, re-verified here
        because scrub trusts nothing), then the all-systematic subset,
        then each single-data-shard exclusion (covers any single
        corrupt shard, the overwhelmingly likely case); the corrected
        stripe is re-encoded and every differing shard pushed to its
        holder through the normal shard-put path (validate +
        tmp/rename replace). Only this REPAIR leg rides the cache —
        the detect pass keeps touching the disks it exists to check."""
        from ..net.message import PRIO_BACKGROUND
        from .block import DataBlock
        from .manager import unpack_shard

        m = self.manager
        codec = m.codec
        k, w = codec.k, codec.width

        def try_subset(idx: tuple[int, ...]):
            # decode stays host-side (numpy) on purpose: localization
            # runs inside the scrub worker and a dead device must never
            # wedge it — the batched detect above already rides the
            # feeder's watchdogs
            import numpy as _np

            from ..ops import rs

            try:
                if all(i < k for i in idx):
                    packed = codec.decode({i: parts[i] for i in idx},
                                          packed_len)  # pure concat
                else:
                    shards = _np.stack(
                        [_np.frombuffer(parts[i], dtype=_np.uint8)
                         for i in idx])
                    data = rs.decode_np(k, codec.m, idx, shards)
                    packed = rs.join_stripe(data, packed_len)
                blk = DataBlock.unpack(packed)
                blk.verify(hash32)
                return packed
            except Exception as e:
                log.debug("decode candidate %s rejected: %s", idx, e)
                return None

        candidates = [tuple(range(k))]
        # one corrupt data shard, substituted by EACH parity shard in
        # turn: trying every parity keeps a simultaneously-corrupt
        # parity shard from blocking the substitution, so a
        # data+parity double corruption still localizes (re-encode then
        # fixes both). Two corrupt DATA shards stay out of scope — the
        # pair search is combinatorial and the reference repairs
        # nothing in this class at all.
        # parity-OUTER order: the full single-corruption sweep with
        # parity k runs first (the common case succeeds within k+1
        # candidates), and only then the other parities sweep for the
        # data+parity double-corruption case
        for p in range(k, w):
            for drop in range(k):
                candidates.append(tuple(i for i in range(k) if i != drop)
                                  + (p,))
        def verify_cached(packed) -> bytes | None:
            try:
                blk = DataBlock.unpack(packed)
                blk.verify(hash32)
                return bytes(packed)
            except Exception as e:
                log.debug("scrub cached packed bytes for %s failed "
                          "re-verification: %s", hash32[:4].hex(), e)
                return None

        good_packed = None
        self.scrub_cache_lookups += 1
        cached = await m.packed_from_tier(hash32)
        if cached is not None:
            # scrub paranoia: re-verify even admission-checked bytes —
            # the repair leg is about to OVERWRITE shards with them
            good_packed = await asyncio.to_thread(verify_cached, cached)
            if good_packed is not None:
                self.scrub_cache_hits += 1
                registry().inc("cache_packed_scrub_hit")
        for idx in candidates:
            if good_packed is not None:
                break
            good_packed = await asyncio.to_thread(try_subset, idx)
        if good_packed is None:
            # >1 corrupt shard (or corrupt beyond what single-exclusion
            # finds): leave the files for operator repair; the count is
            # already in the scrub stats
            return False
        framed = await m.feeder.encode_put(good_packed)
        fixed = True
        for i, node in enumerate(placement[:w]):
            raw = bytes(framed[i])
            # lint: ignore[GL10] shard crc is native-C microseconds; the flagged open/cc chain is the one-time kernel build, cached for the process lifetime
            good_payload, good_len = unpack_shard(raw)
            if good_payload == parts[i] and (
                    lens is None or lens.get(i) == good_len):
                # payload AND header both right on this holder; a
                # payload-identical shard with a rotted header must
                # still be pushed or the rot survives the repair
                continue
            try:
                await m.endpoint.call(
                    node, {"op": "put", "hash": hash32, "part": i,
                           "data": raw},
                    PRIO_BACKGROUND, timeout=60.0)
            except Exception as e:
                log.warning("deep scrub: pushing repaired shard %d of %s "
                            "to %s failed (%s)", i, hash32.hex()[:16],
                            node.hex()[:8], e)
                fixed = False
        return fixed

    async def wait_for_work(self):
        # 1 s polling tick, not one 60 s sleep: an operator `repair
        # scrub start` must take effect promptly, not after the tail of
        # an idle minute (ref: repair.rs reacts to its command channel
        # immediately)
        for _ in range(60):
            if self._pending_cmd is not None:
                return
            await asyncio.sleep(1.0)

    def info(self):
        from ..utils.background import WorkerInfo

        cursor = self.state.cursor[:4].hex() if self.state.cursor else "-"
        if self.manager.erasure and self.deep:
            cursor += (f" deep:{self.deep_checked}"
                       f"/{self.deep_repaired} repaired")
            if self.header_repaired:
                cursor += f" hdr:{self.header_repaired}"
        return WorkerInfo(
            name=self.name,
            progress=cursor,
            tranquility=int(self.state.tranquility),
        )


class RebalanceWorker(Worker):
    """One-shot: move every stored block/shard file whose primary data
    dir changed (multi-HDD layout update) to its new primary dir
    (ref: src/block/repair.rs:531-640 RebalanceWorker). Walks all
    candidate dirs; a file found outside its primary location is moved
    (tmp+rename within the target dir); duplicate copies left by an
    interrupted earlier pass are deduped in favour of the primary."""

    def __init__(self, manager):
        self.manager = manager
        self.name = "block rebalance"
        self._iter = None
        self.moved = 0
        self.freed_bytes = 0

    def _rebalance_batch(self, hashes: list[bytes]) -> None:
        m = self.manager
        lay = m.data_layout
        for h in hashes:
            primary = lay.primary_dir(h)
            for d in lay.candidate_dirs(h):
                if d == primary or not os.path.isdir(d):
                    continue
                pre = h.hex()
                for fn in os.listdir(d):
                    if not fn.startswith(pre) or ".tmp" in fn \
                            or fn.endswith(".corrupted"):
                        continue
                    src = os.path.join(d, fn)
                    dst = os.path.join(primary, fn)
                    try:
                        size = os.path.getsize(src)
                        if os.path.exists(dst):
                            # stray copy: only drop it if the primary
                            # copy is intact (size match) — a crash
                            # mid-copy can leave a truncated dst, and
                            # deleting src then would lose the block
                            if os.path.getsize(dst) == size:
                                os.remove(src)
                                self.freed_bytes += size
                            else:
                                self._copy_over(src, dst)
                                os.remove(src)
                                self.moved += 1
                                self.freed_bytes += size
                            continue
                        os.makedirs(primary, exist_ok=True)
                        # same-FS fast path; cross-FS needs copy+rename
                        try:
                            os.rename(src, dst)
                        except OSError:
                            self._copy_over(src, dst)
                            os.remove(src)
                        self.moved += 1
                        self.freed_bytes += size
                    except OSError as e:
                        log.warning("rebalance of %s failed: %s", src, e)

    def _copy_over(self, src: str, dst: str) -> None:
        """Durable cross-FS copy: tmp + (optional) fsync + rename, the
        same discipline as BlockManager._write_file."""
        tmp = dst + f".tmp-rb{os.getpid()}"
        with open(src, "rb") as fsrc, open(tmp, "wb") as fdst:
            fdst.write(fsrc.read())
            if self.manager.fsync:
                fdst.flush()
                os.fsync(fdst.fileno())
        os.replace(tmp, dst)
        if self.manager.fsync:
            dirfd = os.open(os.path.dirname(dst), os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)

    async def work(self):
        m = self.manager
        if self._iter is None:
            self._iter = m.iter_local_blocks_sorted()
        batch = list(itertools.islice(self._iter, 64))
        if not batch:
            return WState.DONE
        await asyncio.to_thread(self._rebalance_batch, batch)
        return WState.BUSY

    def info(self):
        inf = WorkerInfo(name=self.name)
        inf.progress = (f"moved {self.moved}, "
                        f"freed {self.freed_bytes // (1 << 20)} MiB")
        return inf


class RepairWorker(Worker):
    """One-shot: resync-examine every block we know of
    (ref: repair.rs:35-165)."""

    def __init__(self, manager):
        self.manager = manager
        self.name = "block repair"
        self._phase = 0  # 0: rc table, 1: disk, 2: done
        self._iter = None

    async def work(self):
        m = self.manager
        if self._phase == 0:
            if self._iter is None:
                self._iter = m.rc.all_hashes()
            batch = list(itertools.islice(self._iter, 256))
            if batch:
                # one thread hop per 256 queue inserts (GL10)
                await asyncio.to_thread(
                    lambda: [m.resync.push_now(h) for h in batch])
                return WState.BUSY
            self._phase, self._iter = 1, None
            return WState.BUSY
        if self._phase == 1:
            if self._iter is None:
                self._iter = m.iter_local_blocks()
            batch = [h for h, _ in itertools.islice(self._iter, 256)]
            if batch:
                await asyncio.to_thread(
                    lambda: [m.resync.push_now(h) for h in batch])
                return WState.BUSY
            self._phase = 2
        return WState.DONE
