"""Native host kernels (C, built on first import, loaded via ctypes).

The framework's compute path is JAX/XLA on the accelerator; the runtime
around it keeps native-code hot spots on the host: BLAKE3 content
hashing and GF(2^8) RS math for when blocks are handled one at a time
(server PUT fallback, shard checksum verify, offline tools). Mirrors the
reference's use of native code for its data path (the reference is Rust
end to end; here C serves the same role behind a Python runtime).

Build: one `gcc -O3 -shared` invocation, cached by source hash under
_build/. If no toolchain is available the callers fall back to the pure
Python / numpy implementations (ops/treehash.py, ops/gf256.py) — slower
but identical results. Set GARAGE_TPU_NO_NATIVE=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "b3gf.c")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _build_and_load() -> Optional[ctypes.CDLL]:
    if os.environ.get("GARAGE_TPU_NO_NATIVE"):
        return None
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
    except OSError:
        return None
    tag = hashlib.sha256(src).hexdigest()[:16]
    build_dir = os.path.join(_HERE, "_build")
    so_path = os.path.join(build_dir, f"b3gf-{tag}.so")
    if not os.path.exists(so_path):
        os.makedirs(build_dir, exist_ok=True)
        tmp = so_path + f".tmp{os.getpid()}"
        for cc in ("cc", "gcc", "g++"):
            try:
                r = subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.TimeoutExpired):
                continue
            if r.returncode == 0:
                os.replace(tmp, so_path)
                break
        else:
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.b3_hash.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.b3_hash_many.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.gf256_matmul.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.crc32c_update.argtypes = [ctypes.c_uint32, ctypes.c_void_p,
                                  ctypes.c_uint64]
    lib.crc32c_update.restype = ctypes.c_uint32
    lib.crc64nvme_update.argtypes = [ctypes.c_uint64, ctypes.c_void_p,
                                     ctypes.c_uint64]
    lib.crc64nvme_update.restype = ctypes.c_uint64
    lib.rs_encode_block_packed.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.gt_md5_state_size.restype = ctypes.c_int
    lib.gt_md5_init.argtypes = [ctypes.c_void_p]
    lib.gt_md5_update.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64]
    lib.gt_md5_final_copy.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.gt_b3_md5_block.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_void_p, ctypes.c_char_p]
    lib.gt_md5_update_many.argtypes = [
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.gt_b3_md5_many.argtypes = [
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_char_p]
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if not _tried:
        with _lock:
            if not _tried:
                _lib = _build_and_load()
                _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def loaded() -> bool:
    """True if the library is ALREADY built and loaded — never triggers
    a build (callers on latency-sensitive paths gate on this)."""
    return _lib is not None


def warm_async() -> None:
    """Kick the build/load in a daemon thread (server startup)."""
    threading.Thread(target=available, daemon=True,
                     name="native-build").start()


def _as_cdata(data):
    """Adapt a hash/encode input for a c_char_p parameter WITHOUT
    copying: bytes pass through; a writable buffer (a leased ingest
    view on the zero-copy PUT path, ISSUE 17) wraps as a ctypes char
    array over the same memory (pointer argtypes accept char arrays);
    a readonly non-bytes buffer falls back to one materialization."""
    if isinstance(data, bytes):
        return data
    mv = memoryview(data)
    if mv.readonly or mv.nbytes == 0:
        return mv.tobytes()
    return (ctypes.c_char * mv.nbytes).from_buffer(mv)


def blake3(data) -> bytes:
    """32-byte BLAKE3 digest (native; raises if the library is absent —
    use utils.data.blake3sum for the auto-fallback entry point).
    Accepts bytes or any contiguous buffer (hashing never copies)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out = ctypes.create_string_buffer(32)
    c = _as_cdata(data)
    lib.b3_hash(c, len(c), out)
    return out.raw


def blake3_many(blobs: list[bytes]) -> list[bytes]:
    """Hash many messages in one native call (GIL released throughout)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(blobs)
    if n == 0:
        return []
    offs = np.zeros(n, dtype=np.int64)
    lens = np.array([len(b) for b in blobs], dtype=np.int64)
    if n > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    joined = b"".join(blobs)
    buf = (np.frombuffer(joined, dtype=np.uint8) if joined
           else np.zeros(1, dtype=np.uint8))
    out = np.empty((n, 32), dtype=np.uint8)
    lib.b3_hash_many(
        buf.ctypes.data, n, offs.ctypes.data, lens.ctypes.data,
        out.ctypes.data,
    )
    return [out[i].tobytes() for i in range(n)]


class Md5:
    """Streaming MD5 (S3 ETag chain) that can FUSE with the BLAKE3
    content hash: update_with_blake3() advances the MD5 state and
    returns the block's blake3 digest from ONE interleaved native pass
    (the PUT path otherwise walks every block twice). Falls back to
    hashlib when the native library is absent; duck-types the hashlib
    surface the PUT path uses (update/hexdigest)."""

    __slots__ = ("_st", "_h")

    def __init__(self):
        lib = _get()
        if lib is not None:
            self._st = ctypes.create_string_buffer(lib.gt_md5_state_size())
            lib.gt_md5_init(self._st)
            self._h = None
        else:
            self._st = None
            self._h = hashlib.md5()

    @property
    def fused(self) -> bool:
        return self._st is not None

    def update(self, data) -> None:
        if self._h is not None:
            self._h.update(data)
        else:
            c = _as_cdata(data)
            _lib.gt_md5_update(self._st, c, len(c))

    def update_with_blake3(self, data) -> bytes:
        """MD5-advance by `data` AND return blake3(data), single pass.
        Only valid when `fused` is True. Accepts bytes or a buffer
        view (the zero-copy PUT path hashes the leased buffer in
        place)."""
        out = ctypes.create_string_buffer(32)
        c = _as_cdata(data)
        _lib.gt_b3_md5_block(c, len(c), self._st, out)
        return out.raw

    def hexdigest(self) -> str:
        if self._h is not None:
            return self._h.hexdigest()
        out = ctypes.create_string_buffer(16)
        _lib.gt_md5_final_copy(self._st, out)
        return out.raw.hex()


def _md5_batch_args(items: list[tuple["Md5", bytes]]):
    """Items may carry bytes OR buffer views (leased ingest slices).
    Returns a keepalive list the caller MUST hold through the native
    call — it owns the char arrays the pointer array aims at."""
    n = len(items)
    keep = [_as_cdata(d) for _, d in items]
    ps = (ctypes.c_void_p * n)(*[
        ctypes.cast(ctypes.c_char_p(c) if isinstance(c, bytes) else c,
                    ctypes.c_void_p)
        for c in keep])
    lens = (ctypes.c_int64 * n)(*[len(c) for c in keep])
    sts = (ctypes.c_void_p * n)(
        *[ctypes.addressof(m._st) for m, _ in items])
    return n, ps, lens, sts, keep


def md5_update_many(items: list[tuple["Md5", bytes]]) -> None:
    """Advance many independent Md5 accumulators in one native call —
    8 AVX2 lanes in lockstep across items (multi-buffer MD5: the serial
    per-object ETag chain vectorizes ACROSS concurrent requests)."""
    if not items:
        return
    n, ps, lens, sts, keep = _md5_batch_args(items)
    _lib.gt_md5_update_many(n, ps, lens, sts)
    del keep


def b3_md5_many(items: list[tuple["Md5", bytes]]) -> list[bytes]:
    """Batched fused op: advance each accumulator (8-way across items)
    AND return each item's blake3 content hash."""
    if not items:
        return []
    n, ps, lens, sts, keep = _md5_batch_args(items)
    out = ctypes.create_string_buffer(32 * n)
    _lib.gt_b3_md5_many(n, ps, lens, sts, out)
    del keep
    return [out.raw[32 * i:32 * (i + 1)] for i in range(n)]


def _make_crc_table(poly: int, width: int) -> list:
    mask = (1 << width) - 1
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc & mask)
    return table


_CRC32C_TABLE = _make_crc_table(0x82F63B78, 32)
_CRC64NVME_TABLE = _make_crc_table(0x9A6C9329AC4BC9B5, 64)


def crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-Python fallback (slow; last resort when no toolchain)."""
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def crc64nvme_py(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFFFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC64NVME_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFFFFFFFFFF


def crc32c(data, crc: int = 0) -> int:
    """Accepts bytes OR any buffer (memoryview over a shard payload —
    the validate path checksums without copying)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if isinstance(data, (bytes, bytearray)):
        return lib.crc32c_update(crc, data, len(data))
    a = np.frombuffer(data, dtype=np.uint8)
    return lib.crc32c_update(crc, a.ctypes.data if len(a) else None,
                             len(a))


def crc64nvme(data, crc: int = 0) -> int:
    """Accepts bytes OR any buffer (same contract as crc32c)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if isinstance(data, (bytes, bytearray)):
        return lib.crc64nvme_update(crc, data, len(data))
    a = np.frombuffer(data, dtype=np.uint8)
    return lib.crc64nvme_update(crc, a.ctypes.data if len(a) else None,
                                len(a))


SHARD_HDR_LEN = 16  # [magic 4][block_len u64 BE][crc32c u32 BE]


def rs_encode_packed(block: bytes, k: int, m: int, pmat: np.ndarray,
                     prefix: bytes = b"") -> list[memoryview]:
    """One GIL-released call: split the logical stream prefix||block into
    k shards, compute m parity shards (pmat = (m, k) GF(2^8) parity
    matrix), and return the k+m ready-to-send shard payloads in the
    block store's shard file format (crc32c flavor) as zero-copy views
    over one buffer. `prefix` carries the tiny DataBlock header so the
    caller never concatenates it onto the megabyte payload."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native library unavailable")
    cblock = _as_cdata(block)
    total = len(prefix) + len(cblock)
    shard_len = (total + k - 1) // k
    stride = SHARD_HDR_LEN + shard_len
    pmat = np.ascontiguousarray(pmat, dtype=np.uint8)
    out = np.empty((k + m) * stride, dtype=np.uint8)
    lib.rs_encode_block_packed(prefix, len(prefix), cblock, len(cblock),
                               k, m, pmat.ctypes.data, shard_len,
                               out.ctypes.data)
    view = memoryview(out.data).cast("B")
    return [view[i * stride:(i + 1) * stride] for i in range(k + m)]


def gf_matmul(mat: np.ndarray, x: np.ndarray) -> np.ndarray:
    """(r, s) @ (s, n) over GF(2^8) -> (r, n); native table kernel."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native library unavailable")
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    x = np.ascontiguousarray(x, dtype=np.uint8)
    r, s = mat.shape
    s2, n = x.shape
    if s != s2:
        raise ValueError(f"shape mismatch {mat.shape} @ {x.shape}")
    out = np.empty((r, n), dtype=np.uint8)
    lib.gf256_matmul(mat.ctypes.data, r, s, x.ctypes.data, n, out.ctypes.data)
    return out
