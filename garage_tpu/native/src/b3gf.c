/* Native host kernels for the block data path: BLAKE3 hashing and
 * GF(2^8) matrix application (Reed-Solomon encode/decode).
 *
 * Role: the CPU-side twin of the TPU data plane (ops/treehash.py,
 * ops/gf256.py). The TPU path batches whole stripes through XLA; this
 * library serves the host-resident cases — single-block hashing on the
 * PUT path when no accelerator is attached, shard checksum verification,
 * and RS fallback math — at native speed instead of pure Python.
 *
 * BLAKE3 is implemented from the public spec (portable, no SIMD
 * intrinsics; gcc auto-vectorizes the compression rounds well enough
 * for a host fallback). Only the default 32-byte hash mode is needed.
 *
 * The reference stores hash blocks with sequential blake2
 * (src/util/data.rs:124-132); this framework's content hash is BLAKE3
 * so device and host agree on one tree-structured function.
 */

#include <stdint.h>
#include <stddef.h>
#include <stdlib.h>
#include <string.h>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define GT_X86 1
static int cpu_sse42 = -1;
static int cpu_avx2 = -1;
#endif

/* ================= BLAKE3 ================= */

static const uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};

static const uint8_t MSG_PERM[16] = {2, 6, 3, 10, 7, 0, 4, 13,
                                     1, 11, 12, 5, 9, 14, 15, 8};

enum {
    CHUNK_START = 1 << 0,
    CHUNK_END = 1 << 1,
    PARENT = 1 << 2,
    ROOT = 1 << 3,
};

#define CHUNK_LEN 1024
#define BLOCK_LEN 64

static inline uint32_t rotr32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

static inline void gmix(uint32_t *v, int a, int b, int c, int d,
                        uint32_t mx, uint32_t my) {
    v[a] = v[a] + v[b] + mx;
    v[d] = rotr32(v[d] ^ v[a], 16);
    v[c] = v[c] + v[d];
    v[b] = rotr32(v[b] ^ v[c], 12);
    v[a] = v[a] + v[b] + my;
    v[d] = rotr32(v[d] ^ v[a], 8);
    v[c] = v[c] + v[d];
    v[b] = rotr32(v[b] ^ v[c], 7);
}

static void compress(const uint32_t cv[8], const uint32_t block[16],
                     uint64_t counter, uint32_t block_len, uint32_t flags,
                     uint32_t out[8]) {
    uint32_t v[16];
    uint32_t m[16], t[16];
    memcpy(v, cv, 32);
    v[8] = IV[0];
    v[9] = IV[1];
    v[10] = IV[2];
    v[11] = IV[3];
    v[12] = (uint32_t)counter;
    v[13] = (uint32_t)(counter >> 32);
    v[14] = block_len;
    v[15] = flags;
    memcpy(m, block, 64);
    for (int r = 0;; r++) {
        gmix(v, 0, 4, 8, 12, m[0], m[1]);
        gmix(v, 1, 5, 9, 13, m[2], m[3]);
        gmix(v, 2, 6, 10, 14, m[4], m[5]);
        gmix(v, 3, 7, 11, 15, m[6], m[7]);
        gmix(v, 0, 5, 10, 15, m[8], m[9]);
        gmix(v, 1, 6, 11, 12, m[10], m[11]);
        gmix(v, 2, 7, 8, 13, m[12], m[13]);
        gmix(v, 3, 4, 9, 14, m[14], m[15]);
        if (r == 6)
            break;
        for (int i = 0; i < 16; i++)
            t[i] = m[MSG_PERM[i]];
        memcpy(m, t, 64);
    }
    for (int i = 0; i < 8; i++)
        out[i] = v[i] ^ v[i + 8];
}

static void load_words(const uint8_t *p, size_t len, uint32_t out[16]) {
    uint8_t buf[BLOCK_LEN];
    if (len < BLOCK_LEN) {
        memset(buf, 0, BLOCK_LEN);
        memcpy(buf, p, len);
        p = buf;
    }
    for (int i = 0; i < 16; i++)
        out[i] = (uint32_t)p[4 * i] | ((uint32_t)p[4 * i + 1] << 8) |
                 ((uint32_t)p[4 * i + 2] << 16) | ((uint32_t)p[4 * i + 3] << 24);
}

static void chunk_cv(const uint8_t *chunk, size_t len, uint64_t counter,
                     int root, uint32_t cv[8]) {
    size_t nblocks = len == 0 ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
    memcpy(cv, IV, 32);
    for (size_t b = 0; b < nblocks; b++) {
        size_t blen = (b == nblocks - 1) ? len - BLOCK_LEN * b : BLOCK_LEN;
        uint32_t m[16];
        load_words(chunk + BLOCK_LEN * b, blen, m);
        uint32_t flags = 0;
        if (b == 0)
            flags |= CHUNK_START;
        if (b == nblocks - 1) {
            flags |= CHUNK_END;
            if (root)
                flags |= ROOT;
        }
        compress(cv, m, counter, (uint32_t)blen, flags, cv);
    }
}

static void parent_cv(const uint32_t l[8], const uint32_t r[8], int root,
                      uint32_t out[8]) {
    uint32_t m[16];
    memcpy(m, l, 32);
    memcpy(m + 8, r, 32);
    compress(IV, m, 0, BLOCK_LEN, PARENT | (root ? ROOT : 0), out);
}

/* ============ AVX2 8-way: vectorize ACROSS chunks/parents ============
 * The standard BLAKE3 SIMD formulation from the public spec: eight
 * independent compressions run in lockstep, one 32-bit word per lane.
 * Used for full non-root chunks (identical flags across lanes) and for
 * batches of parent nodes; everything else takes the portable path. */

#ifdef GT_X86

#define ROTR8(v, n) _mm256_or_si256(_mm256_srli_epi32(v, n), \
                                    _mm256_slli_epi32(v, 32 - (n)))

__attribute__((target("avx2")))
static inline void g8(__m256i v[16], int a, int b, int c, int d,
                      __m256i mx, __m256i my) {
    v[a] = _mm256_add_epi32(_mm256_add_epi32(v[a], v[b]), mx);
    v[d] = ROTR8(_mm256_xor_si256(v[d], v[a]), 16);
    v[c] = _mm256_add_epi32(v[c], v[d]);
    v[b] = ROTR8(_mm256_xor_si256(v[b], v[c]), 12);
    v[a] = _mm256_add_epi32(_mm256_add_epi32(v[a], v[b]), my);
    v[d] = ROTR8(_mm256_xor_si256(v[d], v[a]), 8);
    v[c] = _mm256_add_epi32(v[c], v[d]);
    v[b] = ROTR8(_mm256_xor_si256(v[b], v[c]), 7);
}

/* one compression over 8 lanes; m = 16 message-word vectors (mutated:
 * physically permuted between rounds — an indexed schedule was tried
 * and measured SLOWER, it forces m into memory instead of registers) */
__attribute__((target("avx2")))
static void compress8(__m256i cv[8], __m256i m[16], __m256i t0,
                      uint32_t block_len, uint32_t flags,
                      __m256i out[8]) {
    __m256i v[16];
    for (int i = 0; i < 8; i++)
        v[i] = cv[i];
    v[8] = _mm256_set1_epi32((int)IV[0]);
    v[9] = _mm256_set1_epi32((int)IV[1]);
    v[10] = _mm256_set1_epi32((int)IV[2]);
    v[11] = _mm256_set1_epi32((int)IV[3]);
    v[12] = t0;
    v[13] = _mm256_setzero_si256(); /* chunk counters < 2^32 */
    v[14] = _mm256_set1_epi32((int)block_len);
    v[15] = _mm256_set1_epi32((int)flags);
    __m256i t[16];
    for (int r = 0;; r++) {
        g8(v, 0, 4, 8, 12, m[0], m[1]);
        g8(v, 1, 5, 9, 13, m[2], m[3]);
        g8(v, 2, 6, 10, 14, m[4], m[5]);
        g8(v, 3, 7, 11, 15, m[6], m[7]);
        g8(v, 0, 5, 10, 15, m[8], m[9]);
        g8(v, 1, 6, 11, 12, m[10], m[11]);
        g8(v, 2, 7, 8, 13, m[12], m[13]);
        g8(v, 3, 4, 9, 14, m[14], m[15]);
        if (r == 6)
            break;
        for (int i = 0; i < 16; i++)
            t[i] = m[MSG_PERM[i]];
        for (int i = 0; i < 16; i++)
            m[i] = t[i];
    }
    for (int i = 0; i < 8; i++)
        out[i] = _mm256_xor_si256(v[i], v[i + 8]);
}

/* little-endian word load without alignment/aliasing UB (compiles to
 * one mov on x86) */
static inline uint32_t ldw(const uint8_t *p) {
    uint32_t w;
    memcpy(&w, p, 4);
    return w;
}

/* transpose: load word j of one 64-byte block from 8 streams */
__attribute__((target("avx2")))
static inline void load_words8(const uint8_t *const p[8], size_t off,
                               __m256i m[16]) {
    for (int j = 0; j < 16; j++)
        m[j] = _mm256_set_epi32(
            (int)ldw(p[7] + off + 4 * j), (int)ldw(p[6] + off + 4 * j),
            (int)ldw(p[5] + off + 4 * j), (int)ldw(p[4] + off + 4 * j),
            (int)ldw(p[3] + off + 4 * j), (int)ldw(p[2] + off + 4 * j),
            (int)ldw(p[1] + off + 4 * j), (int)ldw(p[0] + off + 4 * j));
}

/* 8 FULL non-root chunks -> 8 CVs (row-major: out[lane][word]) */
__attribute__((target("avx2")))
static void chunks8_cv(const uint8_t *const p[8], uint64_t counter0,
                       uint32_t out[8][8]) {
    __m256i cv[8], m[16];
    for (int i = 0; i < 8; i++)
        cv[i] = _mm256_set1_epi32((int)IV[i]);
    __m256i t0 = _mm256_set_epi32(
        (int)(uint32_t)(counter0 + 7), (int)(uint32_t)(counter0 + 6),
        (int)(uint32_t)(counter0 + 5), (int)(uint32_t)(counter0 + 4),
        (int)(uint32_t)(counter0 + 3), (int)(uint32_t)(counter0 + 2),
        (int)(uint32_t)(counter0 + 1), (int)(uint32_t)(counter0));
    for (int b = 0; b < CHUNK_LEN / BLOCK_LEN; b++) {
        uint32_t flags = 0;
        if (b == 0)
            flags |= CHUNK_START;
        if (b == CHUNK_LEN / BLOCK_LEN - 1)
            flags |= CHUNK_END;
        load_words8(p, (size_t)b * BLOCK_LEN, m);
        compress8(cv, m, t0, BLOCK_LEN, flags, cv);
    }
    uint32_t tmp[8][8]; /* tmp[word][lane] */
    for (int i = 0; i < 8; i++)
        _mm256_storeu_si256((__m256i *)tmp[i], cv[i]);
    for (int l = 0; l < 8; l++)
        for (int i = 0; i < 8; i++)
            out[l][i] = tmp[i][l];
}

/* 8 non-root parents: cvs[2*i], cvs[2*i+1] -> out[i] (row-major) */
__attribute__((target("avx2")))
static void parents8_cv(const uint32_t cvs[16][8], uint32_t out[8][8]) {
    __m256i cv[8], m[16];
    for (int i = 0; i < 8; i++)
        cv[i] = _mm256_set1_epi32((int)IV[i]);
    for (int j = 0; j < 8; j++) {
        m[j] = _mm256_set_epi32(
            (int)cvs[14][j], (int)cvs[12][j], (int)cvs[10][j],
            (int)cvs[8][j], (int)cvs[6][j], (int)cvs[4][j],
            (int)cvs[2][j], (int)cvs[0][j]);
        m[8 + j] = _mm256_set_epi32(
            (int)cvs[15][j], (int)cvs[13][j], (int)cvs[11][j],
            (int)cvs[9][j], (int)cvs[7][j], (int)cvs[5][j],
            (int)cvs[3][j], (int)cvs[1][j]);
    }
    __m256i o[8];
    compress8(cv, m, _mm256_setzero_si256(), BLOCK_LEN, PARENT, o);
    uint32_t tmp[8][8];
    for (int i = 0; i < 8; i++)
        _mm256_storeu_si256((__m256i *)tmp[i], o[i]);
    for (int l = 0; l < 8; l++)
        for (int i = 0; i < 8; i++)
            out[l][i] = tmp[i][l];
}

#endif /* GT_X86 */

/* Spec tree: left subtree = largest power of two of chunks strictly
 * less than the total. Recursion depth <= 54 for 64-bit lengths. */
static void subtree_cv(const uint8_t *data, uint64_t len, uint64_t counter0,
                       int root, uint32_t cv[8]);

#ifdef GT_X86
/* Whole-subtree CVs for a run of FULL chunks, 8-way where possible.
 * `nchunks` must be a power of two >= 8 and the subtree non-root;
 * returns the subtree's CV. */
__attribute__((target("avx2")))
static void subtree_cv_avx2(const uint8_t *data, uint64_t nchunks,
                            uint64_t counter0, uint32_t cv[8]) {
    /* hash all chunks 8 at a time. CV scratch is up to 128 KiB — heap,
     * not alloca: worker threads on some libcs get ~128 KiB stacks. */
    uint32_t (*cvs)[8] = malloc(sizeof(uint32_t[8]) * (size_t)nchunks);
    if (!cvs) { /* fallback: caller's scalar path via recursion */
        uint64_t half = nchunks / 2;
        uint32_t l[8], r[8];
        subtree_cv(data, half * CHUNK_LEN, counter0, 0, l);
        subtree_cv(data + half * CHUNK_LEN, half * CHUNK_LEN,
                   counter0 + half, 0, r);
        parent_cv(l, r, 0, cv);
        return;
    }
    for (uint64_t c = 0; c < nchunks; c += 8) {
        const uint8_t *p[8];
        for (int l = 0; l < 8; l++)
            p[l] = data + (size_t)(c + l) * CHUNK_LEN;
        chunks8_cv(p, counter0 + c, &cvs[c]);
    }
    /* pairwise parent reduction, 8 parents at a time */
    uint64_t n = nchunks;
    while (n > 1) {
        uint64_t half = n / 2;
        uint64_t i = 0;
        for (; i + 8 <= half; i += 8)
            parents8_cv((const uint32_t(*)[8]) & cvs[2 * i], &cvs[i]);
        for (; i < half; i++)
            parent_cv(cvs[2 * i], cvs[2 * i + 1], 0, cvs[i]);
        n = half;
    }
    memcpy(cv, cvs[0], 32);
    free(cvs);
}
#endif

static void subtree_cv(const uint8_t *data, uint64_t len, uint64_t counter0,
                       int root, uint32_t cv[8]) {
    uint64_t nchunks = len == 0 ? 1 : (len + CHUNK_LEN - 1) / CHUNK_LEN;
    if (nchunks == 1) {
        chunk_cv(data, (size_t)len, counter0, root, cv);
        return;
    }
#ifdef GT_X86
    if (cpu_avx2 < 0)
        cpu_avx2 = __builtin_cpu_supports("avx2") ? 1 : 0;
    /* power-of-two run of full chunks, non-root: whole subtree 8-way.
     * cap at 2^12 chunks (4 MiB data, 128 KiB heap CV scratch);
     * bigger subtrees recurse first. */
    if (cpu_avx2 && !root && nchunks >= 8 && nchunks <= (1u << 12) &&
        (nchunks & (nchunks - 1)) == 0 &&
        len == nchunks * (uint64_t)CHUNK_LEN &&
        counter0 + nchunks <= 0xFFFFFFFFu /* compress8 pins t1=0 */) {
        subtree_cv_avx2(data, nchunks, counter0, cv);
        return;
    }
#endif
    uint64_t left = 1;
    while (left * 2 < nchunks)
        left *= 2;
    uint32_t l[8], r[8];
    subtree_cv(data, left * CHUNK_LEN, counter0, 0, l);
    subtree_cv(data + left * CHUNK_LEN, len - left * CHUNK_LEN,
               counter0 + left, 0, r);
    parent_cv(l, r, root, cv);
}

void b3_hash(const uint8_t *data, uint64_t len, uint8_t out[32]) {
    uint32_t cv[8];
    subtree_cv(data, len, 0, 1, cv);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)cv[i];
        out[4 * i + 1] = (uint8_t)(cv[i] >> 8);
        out[4 * i + 2] = (uint8_t)(cv[i] >> 16);
        out[4 * i + 3] = (uint8_t)(cv[i] >> 24);
    }
}

/* n messages at data + offs[i], length lens[i]; digests to out + 32*i. */
void b3_hash_many(const uint8_t *data, int64_t n, const int64_t *offs,
                  const int64_t *lens, uint8_t *out) {
    for (int64_t i = 0; i < n; i++)
        b3_hash(data + offs[i], (uint64_t)lens[i], out + 32 * i);
}

/* ================= MD5 (RFC 1321) ================= */
/* S3 ETags are MD5, so the PUT path pays a serial MD5 over every byte
 * on top of the BLAKE3 content hash. gt_b3_md5_block below runs both
 * digests in ONE interleaved pass (r5: the two separate walks over a
 * 1 MiB block were the single largest CPU cost on the S3 PUT path of
 * a one-core node). Streaming state lives in a caller-owned struct so
 * the chain threads across blocks of the object. */

typedef struct {
    uint32_t h[4];
    uint64_t nbytes;
    uint32_t buflen;
    uint8_t buf[64];
} gt_md5;

static const uint32_t MD5K[64] = {
    0xd76aa478u, 0xe8c7b756u, 0x242070dbu, 0xc1bdceeeu,
    0xf57c0fafu, 0x4787c62au, 0xa8304613u, 0xfd469501u,
    0x698098d8u, 0x8b44f7afu, 0xffff5bb1u, 0x895cd7beu,
    0x6b901122u, 0xfd987193u, 0xa679438eu, 0x49b40821u,
    0xf61e2562u, 0xc040b340u, 0x265e5a51u, 0xe9b6c7aau,
    0xd62f105du, 0x02441453u, 0xd8a1e681u, 0xe7d3fbc8u,
    0x21e1cde6u, 0xc33707d6u, 0xf4d50d87u, 0x455a14edu,
    0xa9e3e905u, 0xfcefa3f8u, 0x676f02d9u, 0x8d2a4c8au,
    0xfffa3942u, 0x8771f681u, 0x6d9d6122u, 0xfde5380cu,
    0xa4beea44u, 0x4bdecfa9u, 0xf6bb4b60u, 0xbebfbc70u,
    0x289b7ec6u, 0xeaa127fau, 0xd4ef3085u, 0x04881d05u,
    0xd9d4d039u, 0xe6db99e5u, 0x1fa27cf8u, 0xc4ac5665u,
    0xf4292244u, 0x432aff97u, 0xab9423a7u, 0xfc93a039u,
    0x655b59c3u, 0x8f0ccc92u, 0xffeff47du, 0x85845dd1u,
    0x6fa87e4fu, 0xfe2ce6e0u, 0xa3014314u, 0x4e0811a1u,
    0xf7537e82u, 0xbd3af235u, 0x2ad7d2bbu, 0xeb86d391u};

static const uint8_t MD5R[64] = {
    7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
    5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20, 5, 9, 14, 20,
    4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
    6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21};

static inline uint32_t rotl32(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
}

static void md5_compress(uint32_t h[4], const uint8_t p[64]) {
    uint32_t M[16];
    for (int i = 0; i < 16; i++)
        M[i] = (uint32_t)p[4 * i] | ((uint32_t)p[4 * i + 1] << 8) |
               ((uint32_t)p[4 * i + 2] << 16) |
               ((uint32_t)p[4 * i + 3] << 24);
    uint32_t A = h[0], B = h[1], C = h[2], D = h[3];
    /* four unrolled 16-step rounds (the i/16 branch per step costs
     * ~15% when left to the compiler) */
    int i = 0;
    for (; i < 16; i++) {
        uint32_t F = (B & C) | (~B & D);
        F += A + MD5K[i] + M[i];
        A = D; D = C; C = B;
        B += rotl32(F, MD5R[i]);
    }
    for (; i < 32; i++) {
        uint32_t F = (D & B) | (~D & C);
        F += A + MD5K[i] + M[(5 * i + 1) & 15];
        A = D; D = C; C = B;
        B += rotl32(F, MD5R[i]);
    }
    for (; i < 48; i++) {
        uint32_t F = B ^ C ^ D;
        F += A + MD5K[i] + M[(3 * i + 5) & 15];
        A = D; D = C; C = B;
        B += rotl32(F, MD5R[i]);
    }
    for (; i < 64; i++) {
        uint32_t F = C ^ (B | ~D);
        F += A + MD5K[i] + M[(7 * i) & 15];
        A = D; D = C; C = B;
        B += rotl32(F, MD5R[i]);
    }
    h[0] += A; h[1] += B; h[2] += C; h[3] += D;
}

int gt_md5_state_size(void) { return (int)sizeof(gt_md5); }

void gt_md5_init(gt_md5 *m) {
    m->h[0] = 0x67452301u; m->h[1] = 0xefcdab89u;
    m->h[2] = 0x98badcfeu; m->h[3] = 0x10325476u;
    m->nbytes = 0;
    m->buflen = 0;
}

void gt_md5_update(gt_md5 *m, const uint8_t *p, uint64_t n) {
    m->nbytes += n;
    if (m->buflen) {
        uint32_t take = 64 - m->buflen;
        if (take > n) take = (uint32_t)n;
        memcpy(m->buf + m->buflen, p, take);
        m->buflen += take;
        p += take; n -= take;
        if (m->buflen == 64) {
            md5_compress(m->h, m->buf);
            m->buflen = 0;
        }
    }
    while (n >= 64) {
        md5_compress(m->h, p);
        p += 64; n -= 64;
    }
    if (n) {
        memcpy(m->buf, p, n);
        m->buflen = (uint32_t)n;
    }
}

/* Finalize WITHOUT mutating the stream state (hexdigest() mid-stream,
 * like hashlib's). */
void gt_md5_final_copy(const gt_md5 *src, uint8_t out[16]) {
    gt_md5 m = *src;
    uint64_t bits = m.nbytes * 8;
    uint8_t pad = 0x80;
    gt_md5_update(&m, &pad, 1);
    static const uint8_t zeros[64] = {0};
    while (m.buflen != 56)
        gt_md5_update(&m, zeros, m.buflen < 56 ? 56 - m.buflen
                                               : 64 - m.buflen + 56);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++)
        lenb[i] = (uint8_t)(bits >> (8 * i));
    gt_md5_update(&m, lenb, 8);
    for (int i = 0; i < 4; i++) {
        out[4 * i] = (uint8_t)m.h[i];
        out[4 * i + 1] = (uint8_t)(m.h[i] >> 8);
        out[4 * i + 2] = (uint8_t)(m.h[i] >> 16);
        out[4 * i + 3] = (uint8_t)(m.h[i] >> 24);
    }
}

/* ---- 8-way multi-buffer MD5 (AVX2) ----
 * MD5 is a strict serial chain WITHIN one object, but concurrent PUT
 * requests are independent chains: running 8 of them in lockstep, one
 * 32-bit word per lane (same formulation as compress8 above), turns
 * the ETag MD5 from ~0.55 GB/s into a batched multi-GB/s op whenever
 * the feeder queue holds blocks from several requests. */

#ifdef GT_X86

#define ROTL8V(v, n) _mm256_or_si256(_mm256_slli_epi32(v, n), \
                                     _mm256_srli_epi32(v, 32 - (n)))

__attribute__((target("avx2")))
static void md5_compress8(__m256i h[4], const uint8_t *const p[8],
                          size_t off) {
    __m256i M[16];
    for (int j = 0; j < 16; j++)
        M[j] = _mm256_set_epi32(
            (int)ldw(p[7] + off + 4 * j), (int)ldw(p[6] + off + 4 * j),
            (int)ldw(p[5] + off + 4 * j), (int)ldw(p[4] + off + 4 * j),
            (int)ldw(p[3] + off + 4 * j), (int)ldw(p[2] + off + 4 * j),
            (int)ldw(p[1] + off + 4 * j), (int)ldw(p[0] + off + 4 * j));
    __m256i A = h[0], B = h[1], C = h[2], D = h[3];
    int i = 0;
#define MD5STEP8(Fexpr, g, r)                                         \
    do {                                                              \
        __m256i F = Fexpr;                                            \
        F = _mm256_add_epi32(F, _mm256_add_epi32(A,                   \
                _mm256_add_epi32(_mm256_set1_epi32((int)MD5K[i]),     \
                                 M[g])));                             \
        A = D; D = C; C = B;                                          \
        B = _mm256_add_epi32(B, ROTL8V(F, r));                        \
        i++;                                                          \
    } while (0)
    for (int q = 0; q < 4; q++) {
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(B, C),
                                 _mm256_andnot_si256(B, D)), i, 7);
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(B, C),
                                 _mm256_andnot_si256(B, D)), i, 12);
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(B, C),
                                 _mm256_andnot_si256(B, D)), i, 17);
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(B, C),
                                 _mm256_andnot_si256(B, D)), i, 22);
    }
    for (int q = 0; q < 4; q++) {
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(D, B),
                                 _mm256_andnot_si256(D, C)),
                 (5 * i + 1) & 15, 5);
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(D, B),
                                 _mm256_andnot_si256(D, C)),
                 (5 * i + 1) & 15, 9);
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(D, B),
                                 _mm256_andnot_si256(D, C)),
                 (5 * i + 1) & 15, 14);
        MD5STEP8(_mm256_or_si256(_mm256_and_si256(D, B),
                                 _mm256_andnot_si256(D, C)),
                 (5 * i + 1) & 15, 20);
    }
    for (int q = 0; q < 4; q++) {
        MD5STEP8(_mm256_xor_si256(_mm256_xor_si256(B, C), D),
                 (3 * i + 5) & 15, 4);
        MD5STEP8(_mm256_xor_si256(_mm256_xor_si256(B, C), D),
                 (3 * i + 5) & 15, 11);
        MD5STEP8(_mm256_xor_si256(_mm256_xor_si256(B, C), D),
                 (3 * i + 5) & 15, 16);
        MD5STEP8(_mm256_xor_si256(_mm256_xor_si256(B, C), D),
                 (3 * i + 5) & 15, 23);
    }
    __m256i ones = _mm256_set1_epi32(-1);
    for (int q = 0; q < 4; q++) {
        MD5STEP8(_mm256_xor_si256(C, _mm256_or_si256(B,
                     _mm256_xor_si256(D, ones))), (7 * i) & 15, 6);
        MD5STEP8(_mm256_xor_si256(C, _mm256_or_si256(B,
                     _mm256_xor_si256(D, ones))), (7 * i) & 15, 10);
        MD5STEP8(_mm256_xor_si256(C, _mm256_or_si256(B,
                     _mm256_xor_si256(D, ones))), (7 * i) & 15, 15);
        MD5STEP8(_mm256_xor_si256(C, _mm256_or_si256(B,
                     _mm256_xor_si256(D, ones))), (7 * i) & 15, 21);
    }
#undef MD5STEP8
    h[0] = _mm256_add_epi32(h[0], A);
    h[1] = _mm256_add_epi32(h[1], B);
    h[2] = _mm256_add_epi32(h[2], C);
    h[3] = _mm256_add_epi32(h[3], D);
}

/* advance 8 lane states by `nblocks` sequential 64-byte blocks each
 * (lane l reads p[l] + 64*k). Does NOT touch nbytes/buf — callers
 * account for consumed bytes. */
__attribute__((target("avx2")))
static void md5_blocks8(gt_md5 *const st[8], const uint8_t *const p[8],
                        uint64_t nblocks) {
    __m256i h[4];
    for (int w = 0; w < 4; w++)
        h[w] = _mm256_set_epi32(
            (int)st[7]->h[w], (int)st[6]->h[w], (int)st[5]->h[w],
            (int)st[4]->h[w], (int)st[3]->h[w], (int)st[2]->h[w],
            (int)st[1]->h[w], (int)st[0]->h[w]);
    for (uint64_t b = 0; b < nblocks; b++)
        md5_compress8(h, p, (size_t)(64 * b));
    uint32_t tmp[4][8];
    for (int w = 0; w < 4; w++)
        _mm256_storeu_si256((__m256i *)tmp[w], h[w]);
    for (int l = 0; l < 8; l++)
        for (int w = 0; w < 4; w++)
            st[l]->h[w] = tmp[w][l];
}

#endif /* GT_X86 */

/* Advance n independent MD5 states, 8 lanes in lockstep where
 * possible. Items with a partial buffered block or <64 bytes take the
 * scalar path; padding lanes replay lane 0 into a scratch state. */
void gt_md5_update_many(int64_t n, const uint8_t **ps,
                        const int64_t *lens, gt_md5 **sts) {
#ifdef GT_X86
    if (cpu_avx2 < 0)
        cpu_avx2 = __builtin_cpu_supports("avx2") ? 1 : 0;
    if (cpu_avx2 > 0) {
        int64_t i = 0;
        while (i < n) {
            int g = 0;
            int64_t gi[8];
            while (i < n && g < 8) {
                if (sts[i]->buflen == 0 && lens[i] >= 64)
                    gi[g++] = i;
                else
                    gt_md5_update(sts[i], ps[i], (uint64_t)lens[i]);
                i++;
            }
            if (g >= 2) {
                uint64_t minblocks = (uint64_t)lens[gi[0]] / 64;
                for (int j = 1; j < g; j++) {
                    uint64_t nb = (uint64_t)lens[gi[j]] / 64;
                    if (nb < minblocks)
                        minblocks = nb;
                }
                gt_md5 dummy;
                gt_md5_init(&dummy);
                gt_md5 *s8[8];
                const uint8_t *p8[8];
                for (int j = 0; j < 8; j++) {
                    s8[j] = j < g ? sts[gi[j]] : &dummy;
                    p8[j] = ps[gi[j < g ? j : 0]];
                }
                md5_blocks8(s8, p8, minblocks);
                for (int j = 0; j < g; j++) {
                    gt_md5 *st = sts[gi[j]];
                    st->nbytes += 64 * minblocks;
                    uint64_t rem = (uint64_t)lens[gi[j]] - 64 * minblocks;
                    if (rem)
                        gt_md5_update(st, ps[gi[j]] + 64 * minblocks, rem);
                }
            } else if (g == 1) {
                gt_md5_update(sts[gi[0]], ps[gi[0]],
                              (uint64_t)lens[gi[0]]);
            }
        }
        return;
    }
#endif
    for (int64_t i = 0; i < n; i++)
        gt_md5_update(sts[i], ps[i], (uint64_t)lens[i]);
}

/* Batched fused op for the feeder: advance each md5 state (8-way
 * across items) and write blake3(item) to outs + 32*i. */
void gt_b3_md5_many(int64_t n, const uint8_t **ps, const int64_t *lens,
                    gt_md5 **sts, uint8_t *outs) {
    gt_md5_update_many(n, ps, lens, sts);
    for (int64_t i = 0; i < n; i++)
        b3_hash(ps[i], (uint64_t)lens[i], outs + 32 * i);
}

/* ---- fused BLAKE3 + MD5, one pass over the block ---- */

/* Spec-tree reduction over an array of chunk CVs (left subtree = the
 * largest power of two strictly below n). Segments are disjoint, so
 * the 8-way level loop may reduce power-of-two runs in place. */
static void cv_tree_reduce(uint32_t (*cvs)[8], uint64_t n, int root,
                           uint32_t out[8]) {
    if (n == 1) {
        memcpy(out, cvs[0], 32);
        return;
    }
#ifdef GT_X86
    if (cpu_avx2 > 0 && !root && n >= 16 && (n & (n - 1)) == 0) {
        uint64_t w = n;
        while (w > 1) {
            uint64_t half = w / 2, i = 0;
            for (; i + 8 <= half; i += 8)
                parents8_cv((const uint32_t(*)[8]) & cvs[2 * i], &cvs[i]);
            for (; i < half; i++)
                parent_cv(cvs[2 * i], cvs[2 * i + 1], 0, cvs[i]);
            w = half;
        }
        memcpy(out, cvs[0], 32);
        return;
    }
#endif
    uint64_t left = 1;
    while (left * 2 < n)
        left *= 2;
    uint32_t l[8], r[8];
    cv_tree_reduce(cvs, left, 0, l);
    cv_tree_reduce(cvs + left, n - left, 0, r);
    parent_cv(l, r, root, out);
}

/* BLAKE3 digest of data[0..len) AND md5-advance `st` by the same
 * bytes, interleaved in 16 KiB windows so both digests read each
 * window while it is cache-resident: one RAM traversal instead of
 * two. Returns the blake3 digest in out32. */
void gt_b3_md5_block(const uint8_t *data, uint64_t len, gt_md5 *st,
                     uint8_t out32[32]) {
    uint64_t nchunks = len == 0 ? 1 : (len + CHUNK_LEN - 1) / CHUNK_LEN;
    if (nchunks == 1) {
        gt_md5_update(st, data, len);
        uint32_t cv[8];
        chunk_cv(data, (size_t)len, 0, 1, cv);
        for (int i = 0; i < 8; i++) {
            out32[4 * i] = (uint8_t)cv[i];
            out32[4 * i + 1] = (uint8_t)(cv[i] >> 8);
            out32[4 * i + 2] = (uint8_t)(cv[i] >> 16);
            out32[4 * i + 3] = (uint8_t)(cv[i] >> 24);
        }
        return;
    }
    uint32_t (*cvs)[8] = malloc(sizeof(uint32_t[8]) * (size_t)nchunks);
    if (!cvs) { /* degraded two-pass path */
        gt_md5_update(st, data, len);
        b3_hash(data, len, out32);
        return;
    }
#ifdef GT_X86
    if (cpu_avx2 < 0)
        cpu_avx2 = __builtin_cpu_supports("avx2") ? 1 : 0;
#endif
    uint64_t full = len / CHUNK_LEN;       /* # full 1 KiB chunks */
    uint64_t c = 0;
    const uint64_t WIN = 16;               /* chunks per window, 16 KiB */
    while (c < full) {
        uint64_t end = c + WIN < full ? c + WIN : full;
        gt_md5_update(st, data + c * CHUNK_LEN, (end - c) * CHUNK_LEN);
        uint64_t i = c;
#ifdef GT_X86
        if (cpu_avx2 > 0)
            for (; i + 8 <= end && i + 8 <= 0xFFFFFFFFu; i += 8) {
                const uint8_t *p[8];
                for (int l8 = 0; l8 < 8; l8++)
                    p[l8] = data + (size_t)(i + l8) * CHUNK_LEN;
                chunks8_cv(p, i, &cvs[i]);
            }
#endif
        for (; i < end; i++)
            chunk_cv(data + (size_t)i * CHUNK_LEN, CHUNK_LEN, i, 0,
                     cvs[i]);
        c = end;
    }
    if (nchunks > full) {                  /* partial tail chunk */
        gt_md5_update(st, data + full * CHUNK_LEN, len - full * CHUNK_LEN);
        chunk_cv(data + (size_t)full * CHUNK_LEN,
                 (size_t)(len - full * CHUNK_LEN), full, 0, cvs[full]);
    }
    uint32_t cv[8];
    cv_tree_reduce(cvs, nchunks, 1, cv);
    free(cvs);
    for (int i = 0; i < 8; i++) {
        out32[4 * i] = (uint8_t)cv[i];
        out32[4 * i + 1] = (uint8_t)(cv[i] >> 8);
        out32[4 * i + 2] = (uint8_t)(cv[i] >> 16);
        out32[4 * i + 3] = (uint8_t)(cv[i] >> 24);
    }
}

/* ================= GF(2^8), poly 0x11D ================= */

static uint8_t GFMUL[256][256];
static int gf_ready = 0;

static void gf_init(void) {
    uint8_t exp[512];
    int log[256];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        exp[i] = (uint8_t)x;
        log[x] = i;
        x <<= 1;
        if (x & 0x100)
            x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++)
        exp[i] = exp[i - 255];
    for (int a = 0; a < 256; a++) {
        GFMUL[0][a] = 0;
        GFMUL[a][0] = 0;
    }
    for (int a = 1; a < 256; a++)
        for (int b = 1; b < 256; b++)
            GFMUL[a][b] = exp[log[a] + log[b]];
    gf_ready = 1;
}

/* ================= reflected CRCs (slice-by-8) =================
 * crc32c (Castagnoli, poly 0x82F63B78 reflected) and CRC-64/NVME
 * (poly 0x9A6C9329AC4BC9B5 reflected) for the S3 x-amz-checksum-*
 * framework (ref: src/api/common/signature/checksum.rs). */

static uint32_t C32C_T[8][256];
static uint64_t C64_T[8][256];
static int crc_ready = 0;

static void crc_init(void) {
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        uint64_t d = (uint64_t)i;
        for (int k = 0; k < 8; k++) {
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
            d = (d & 1) ? (d >> 1) ^ 0x9A6C9329AC4BC9B5ull : d >> 1;
        }
        C32C_T[0][i] = c;
        C64_T[0][i] = d;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = C32C_T[0][i];
        uint64_t d = C64_T[0][i];
        for (int s = 1; s < 8; s++) {
            c = C32C_T[0][c & 0xFF] ^ (c >> 8);
            d = C64_T[0][d & 0xFF] ^ (d >> 8);
            C32C_T[s][i] = c;
            C64_T[s][i] = d;
        }
    }
    crc_ready = 1;
}

#ifdef GT_X86
/* SSE4.2 CRC32C: the crc32 instruction computes the Castagnoli
 * polynomial directly, ~20x the slice-by-8 table walk. */
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t *p, uint64_t len) {
    uint64_t c = ~crc;
    while (len >= 8) {
        uint64_t w;
        memcpy(&w, p, 8);
        c = _mm_crc32_u64(c, w);
        p += 8;
        len -= 8;
    }
    uint32_t c32 = (uint32_t)c;
    while (len--)
        c32 = _mm_crc32_u8(c32, *p++);
    return ~c32;
}

#endif

uint32_t crc32c_update(uint32_t crc, const uint8_t *p, uint64_t len) {
#ifdef GT_X86
    if (cpu_sse42 < 0)
        cpu_sse42 = __builtin_cpu_supports("sse4.2") ? 1 : 0;
    if (cpu_sse42)
        return crc32c_hw(crc, p, len);
#endif
    if (!crc_ready)
        crc_init();
    crc = ~crc;
    while (len >= 8) {
        uint64_t w;
        memcpy(&w, p, 8);
        w ^= crc; /* little-endian host assumed (x86/arm) */
        crc = C32C_T[7][w & 0xFF] ^ C32C_T[6][(w >> 8) & 0xFF] ^
              C32C_T[5][(w >> 16) & 0xFF] ^ C32C_T[4][(w >> 24) & 0xFF] ^
              C32C_T[3][(w >> 32) & 0xFF] ^ C32C_T[2][(w >> 40) & 0xFF] ^
              C32C_T[1][(w >> 48) & 0xFF] ^ C32C_T[0][(w >> 56) & 0xFF];
        p += 8;
        len -= 8;
    }
    while (len--)
        crc = C32C_T[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

uint64_t crc64nvme_update(uint64_t crc, const uint8_t *p, uint64_t len) {
    if (!crc_ready)
        crc_init();
    crc = ~crc;
    while (len >= 8) {
        uint64_t w;
        memcpy(&w, p, 8);
        w ^= crc;
        crc = C64_T[7][w & 0xFF] ^ C64_T[6][(w >> 8) & 0xFF] ^
              C64_T[5][(w >> 16) & 0xFF] ^ C64_T[4][(w >> 24) & 0xFF] ^
              C64_T[3][(w >> 32) & 0xFF] ^ C64_T[2][(w >> 40) & 0xFF] ^
              C64_T[1][(w >> 48) & 0xFF] ^ C64_T[0][(w >> 56) & 0xFF];
        p += 8;
        len -= 8;
    }
    while (len--)
        crc = C64_T[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

/* Nibble tables for the PSHUFB formulation (ISA-L style): for each
 * coefficient c, NIB[c] holds two 16-byte tables L, H with
 * c*v = L[v & 0xF] ^ H[v >> 4]. 8 KiB total, built with GFMUL. */
static uint8_t NIB[256][32];
static int nib_ready = 0;

static void nib_init(void) {
    if (!gf_ready)
        gf_init();
    for (int c = 0; c < 256; c++) {
        for (int v = 0; v < 16; v++) {
            NIB[c][v] = GFMUL[c][v];
            NIB[c][16 + v] = GFMUL[c][v << 4];
        }
    }
    nib_ready = 1;
}

static void gf_axpy_scalar(uint8_t c, const uint8_t *x, uint8_t *o,
                           int64_t n) {
    const uint8_t *tab = GFMUL[c];
    if (c == 1) {
        for (int64_t t = 0; t < n; t++)
            o[t] ^= x[t];
    } else {
        for (int64_t t = 0; t < n; t++)
            o[t] ^= tab[x[t]];
    }
}

#ifdef GT_X86
/* o[0..n) ^= c * x[0..n) over GF(2^8), 32 bytes per step. */
__attribute__((target("avx2")))
static void gf_axpy_avx2(uint8_t c, const uint8_t *x, uint8_t *o,
                         int64_t n) {
    __m256i lo = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i *)NIB[c]));
    __m256i hi = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i *)(NIB[c] + 16)));
    __m256i mask = _mm256_set1_epi8(0x0F);
    int64_t t = 0;
    for (; t + 32 <= n; t += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i *)(x + t));
        __m256i vl = _mm256_and_si256(v, mask);
        __m256i vh = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(lo, vl),
                                     _mm256_shuffle_epi8(hi, vh));
        __m256i acc = _mm256_loadu_si256((const __m256i *)(o + t));
        _mm256_storeu_si256((__m256i *)(o + t), _mm256_xor_si256(acc, p));
    }
    if (t < n)
        gf_axpy_scalar(c, x + t, o + t, n - t);
}
#endif

static void gf_axpy(uint8_t c, const uint8_t *x, uint8_t *o, int64_t n) {
    if (c == 0)
        return;
#ifdef GT_X86
    if (cpu_avx2 < 0)
        cpu_avx2 = __builtin_cpu_supports("avx2") ? 1 : 0;
    if (cpu_avx2 && c != 1 && n >= 64) {
        gf_axpy_avx2(c, x, o, n);
        return;
    }
#endif
    gf_axpy_scalar(c, x, o, n);
}

/* Tiled r x s GF(2^8) matmul over strided rows. Column tiles sized so
 * the r output tiles stay cache-resident while each input tile is read
 * once from memory (the naive row-major loop re-streams every input row
 * per output row: ~3*r*s*n bytes of traffic vs ~(s+r)*n here). Inner
 * loop order is j-then-i so a just-loaded input tile feeds all r
 * outputs from L1. */
#define GF_TILE 16384
#define GF_MAXROWS 256
static void gf_matmul_tiled(const uint8_t *mat, int64_t r, int64_t s,
                            const uint8_t *const *xrows,
                            uint8_t *const *orows, int64_t n) {
    for (int64_t t0 = 0; t0 < n; t0 += GF_TILE) {
        int64_t tn = n - t0 < GF_TILE ? n - t0 : GF_TILE;
        for (int64_t i = 0; i < r; i++)
            memset(orows[i] + t0, 0, (size_t)tn);
        for (int64_t j = 0; j < s; j++)
            for (int64_t i = 0; i < r; i++)
                gf_axpy(mat[i * s + j], xrows[j] + t0, orows[i] + t0, tn);
    }
}

/* out (r, n) = mat (r, s) @ x (s, n) over GF(2^8); rows contiguous. */
void gf256_matmul(const uint8_t *mat, int64_t r, int64_t s,
                  const uint8_t *x, int64_t n, uint8_t *out) {
    if (!nib_ready)
        nib_init();
    if (r <= GF_MAXROWS && s <= GF_MAXROWS && r > 1) {
        const uint8_t *xr[GF_MAXROWS];
        uint8_t *or_[GF_MAXROWS];
        for (int64_t j = 0; j < s; j++)
            xr[j] = x + j * n;
        for (int64_t i = 0; i < r; i++)
            or_[i] = out + i * n;
        gf_matmul_tiled(mat, r, s, xr, or_, n);
        return;
    }
    for (int64_t i = 0; i < r; i++) {
        uint8_t *o = out + i * n;
        memset(o, 0, (size_t)n);
        for (int64_t j = 0; j < s; j++)
            gf_axpy(mat[i * s + j], x + j * n, o, n);
    }
}

/* ================= one-call packed RS encode =================
 * The PUT hot path: split `block` (block_len bytes) into k shards of
 * shard_len (zero-padded tail), compute m parity shards with pmat
 * (m x k, row-major), and emit k+m ready-to-send shard payloads at
 * out + i*(16 + shard_len), each framed as the block store's shard
 * file format (block/manager.py pack_shard, crc32c flavor):
 *   [magic "GTS2"][block_len u64 BE][crc32c u32 BE][shard bytes]
 * One GIL-released call replaces split_stripe + gf_matmul + per-shard
 * pack_shard/crc (VERDICT r3 task 1: the kernel<->system gap). */
void rs_encode_block_packed(const uint8_t *pfx, int64_t pfx_len,
                            const uint8_t *block, int64_t data_len,
                            int64_t k, int64_t m, const uint8_t *pmat,
                            int64_t shard_len, uint8_t *out) {
    const int64_t stride = 16 + shard_len;
    const int64_t block_len = pfx_len + data_len;
    /* data shards: copy straight from the logical stream pfx||block,
     * zero-padding the tail (pfx is the 1-byte DataBlock header — taking
     * it separately saves the caller a full-block concat copy) */
    for (int64_t i = 0; i < k; i++) {
        uint8_t *dst = out + i * stride + 16;
        int64_t off = i * shard_len;
        int64_t want = shard_len;
        if (off < pfx_len) {
            int64_t n = pfx_len - off < want ? pfx_len - off : want;
            memcpy(dst, pfx + off, (size_t)n);
            dst += n;
            off += n;
            want -= n;
        }
        if (want > 0) {
            int64_t doff = off - pfx_len;
            int64_t have = data_len - doff;
            if (have > want)
                have = want;
            if (have > 0) {
                memcpy(dst, block + doff, (size_t)have);
                dst += have;
                want -= have;
            }
            if (want > 0)
                memset(dst, 0, (size_t)want);
        }
    }
    /* parity shards from the in-place data shards (tiled: each data
     * tile read once, all m parity tiles cache-resident) */
    if (!nib_ready)
        nib_init();
    if (k <= GF_MAXROWS && m <= GF_MAXROWS) {
        const uint8_t *xr[GF_MAXROWS];
        uint8_t *or_[GF_MAXROWS];
        for (int64_t j = 0; j < k; j++)
            xr[j] = out + j * stride + 16;
        for (int64_t i = 0; i < m; i++)
            or_[i] = out + (k + i) * stride + 16;
        gf_matmul_tiled(pmat, m, k, xr, or_, shard_len);
    } else {
        for (int64_t i = 0; i < m; i++) {
            uint8_t *o = out + (k + i) * stride + 16;
            memset(o, 0, (size_t)shard_len);
            for (int64_t j = 0; j < k; j++)
                gf_axpy(pmat[i * k + j], out + j * stride + 16, o,
                        shard_len);
        }
    }
    /* headers */
    for (int64_t i = 0; i < k + m; i++) {
        uint8_t *h = out + i * stride;
        h[0] = 'G'; h[1] = 'T'; h[2] = 'S'; h[3] = '2';
        uint64_t bl = (uint64_t)block_len;
        for (int b = 0; b < 8; b++)
            h[4 + b] = (uint8_t)(bl >> (56 - 8 * b));
        uint32_t ck = crc32c_update(0, h + 16, (uint64_t)shard_len);
        h[12] = (uint8_t)(ck >> 24); h[13] = (uint8_t)(ck >> 16);
        h[14] = (uint8_t)(ck >> 8); h[15] = (uint8_t)ck;
    }
}
