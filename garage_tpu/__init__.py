"""garage_tpu — a TPU-native, S3-compatible, geo-distributed object store.

A from-scratch rebuild of the capabilities of Garage (reference:
/root/reference, Rust) with the block data path — Reed-Solomon GF(2^8)
erasure coding and content hashing — running as JAX/Pallas kernels on TPU.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  utils/     foundation: ids+hashes, config, CRDTs, versioned encoding,
             background workers            (ref: src/util)
  db/        embedded KV facade (sqlite + in-memory engines)
                                           (ref: src/db)
  ops/       the TPU data plane: GF(2^8) linear algebra, RS(k,m) codec,
             batched tree hashing — jnp + Pallas kernels (no ref analogue;
             replaces CPU hashing/zstd hot loops of src/block, src/api/s3/put.rs)
  parallel/  jax.sharding meshes + sharded encode/scrub pipelines for
             multi-chip (replaces nothing in ref; TPU-native scale axis)
  net/       asyncio transport mesh: auth, framing, priorities, streams
                                           (ref: src/net)
  rpc/       membership, cluster layout (max-flow), quorum engine
                                           (ref: src/rpc)
  table/     replicated CRDT table engine with Merkle anti-entropy
                                           (ref: src/table)
  block/     content-addressed block store behind a BlockCodec boundary:
             replicate-N (CPU) and erasure(k,m) (TPU)  (ref: src/block)
  models/    application schemas + composition root    (ref: src/model)
  api/       S3/K2V/admin HTTP frontends               (ref: src/api)
  cli/       operator CLI + server entrypoint          (ref: src/garage)
"""

__version__ = "0.4.0"
