"""HTTP API layer (S3, admin; ref: src/api/)."""

from .http import HttpError, HttpServer, Request, Response
from .signature import verify_request

__all__ = ["HttpError", "HttpServer", "Request", "Response",
           "verify_request"]
