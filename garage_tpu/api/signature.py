"""AWS Signature V4 verification.

Ref parity: src/api/common/signature/ (payload.rs:35-576 header +
presigned auth, streaming.rs aws-chunked per-chunk signatures). Verifies
Authorization-header and presigned-query signatures against the key
table, and wraps `aws-chunked` streaming bodies (signed chunks or
unsigned-with-trailer) so handlers see plain payload bytes.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import os
from typing import Optional
from urllib.parse import quote, unquote

from .http import BodyReader, HttpError, Request

# sha256 releases the GIL; chunks at/above this size hash in a worker
# thread, overlapping with the next read instead of stalling the event
# loop (same threshold discipline as the put pipeline's md5 offload)
_HASH_OFFLOAD_MIN = 64 * 1024
_MULTICORE = (os.cpu_count() or 1) > 1

SERVICE = "s3"
ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_SIGNED = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_SIGNED_TRAILER = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER"
STREAMING_UNSIGNED_TRAILER = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"
MAX_CLOCK_SKEW = 15 * 60


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = SERVICE) -> bytes:
    k = _hmac(b"AWS4" + secret.encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return quote(s, safe=safe)


def canonical_query(raw_pairs: list[tuple[str, str]],
                    skip: tuple[str, ...] = ()) -> str:
    enc = []
    for k, v in raw_pairs:
        dk, dv = unquote(k), unquote(v)
        if dk in skip:
            continue
        enc.append((uri_encode(dk), uri_encode(dv)))
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def canonical_headers(headers: dict[str, str],
                      signed: list[str]) -> tuple[str, str]:
    lines = []
    for name in signed:
        v = headers.get(name)
        if v is None:
            raise HttpError(403, f"signed header {name} missing")
        lines.append(f"{name}:{' '.join(v.split())}\n")
    return "".join(lines), ";".join(signed)


def canonical_request(method: str, raw_path: str,
                      raw_query: list[tuple[str, str]],
                      headers: dict[str, str], signed: list[str],
                      payload_hash: str,
                      skip_query: tuple[str, ...] = ()) -> str:
    ch, sh = canonical_headers(headers, signed)
    return "\n".join([
        method,
        raw_path or "/",
        canonical_query(raw_query, skip_query),
        ch,
        sh,
        payload_hash,
    ])


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join([ALGORITHM, amz_date, scope, _sha256(creq.encode())])


def parse_amz_date(s: str) -> datetime.datetime:
    try:
        return datetime.datetime.strptime(s, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc)
    except ValueError:
        raise HttpError(403, "invalid x-amz-date")


class VerifiedRequest:
    __slots__ = ("key_id", "content_sha256", "signature", "scope_date",
                 "signing_key", "presigned")

    def __init__(self, key_id, content_sha256, signature, scope_date,
                 sk, presigned):
        self.key_id = key_id
        self.content_sha256 = content_sha256  # literal header value
        self.signature = signature
        self.scope_date = scope_date
        self.signing_key = sk
        self.presigned = presigned


def claimed_key_id(req: Request) -> Optional[str]:
    """The key id the request CLAIMS, parsed without any crypto — the
    per-key FAIRNESS identity (qos deficit round-robin) available
    BEFORE SigV4 runs. A forged claim only picks which fair queue the
    request waits in (and a flood under someone else's id shares that
    id's 1/K slice — strictly worse for the attacker than spreading
    out); authorization always uses the VERIFIED identity resolved
    after signature check."""
    auth = req.header("authorization")
    if auth and "Credential=" in auth:
        cred = auth.split("Credential=", 1)[1].split(",", 1)[0]
        kid = cred.strip().split("/", 1)[0]
        return kid or None
    cred = req.query.get("X-Amz-Credential")
    if cred:
        kid = unquote(cred).split("/", 1)[0]
        return kid or None
    return None


async def verify_request(req: Request, region: str, lookup_secret,
                         service: str = SERVICE
                         ) -> Optional[VerifiedRequest]:
    """Check the request signature. `lookup_secret(key_id) -> secret|None`
    (async). Returns None for anonymous (unsigned) requests; raises
    HttpError(403) on bad signatures. `service` is the SigV4 scope
    service — "s3", or "k2v" for the K2V API (ref:
    api/k2v/api_server.rs verify_request). ref: payload.rs:35-200."""
    auth = req.header("authorization")
    if auth is not None:
        return await _verify_header(req, region, lookup_secret, auth,
                                    service)
    if req.query.get("X-Amz-Algorithm") == ALGORITHM:
        return await _verify_presigned(req, region, lookup_secret, service)
    return None


def _parse_credential(cred: str, region: str,
                      service: str = SERVICE) -> tuple[str, str]:
    parts = cred.split("/")
    if len(parts) != 5 or parts[4] != "aws4_request":
        raise HttpError(403, "malformed credential")
    key_id, date, creg, svc = parts[0], parts[1], parts[2], parts[3]
    if creg != region or svc != service:
        raise HttpError(403, f"wrong scope region/service ({creg}/{svc})")
    return key_id, date


def _check_date(amz_date: str, scope_date: str, now=None) -> None:
    t = parse_amz_date(amz_date)
    if t.strftime("%Y%m%d") != scope_date:
        raise HttpError(403, "date mismatch between x-amz-date and scope")
    now = now or datetime.datetime.now(datetime.timezone.utc)
    if abs((now - t).total_seconds()) > MAX_CLOCK_SKEW:
        raise HttpError(403, "request time too skewed")


async def _verify_header(req: Request, region: str, lookup_secret,
                         auth: str,
                         service: str = SERVICE) -> VerifiedRequest:
    if not auth.startswith(ALGORITHM):
        raise HttpError(403, "unsupported auth algorithm")
    fields = {}
    for item in auth[len(ALGORITHM):].split(","):
        k, _, v = item.strip().partition("=")
        fields[k] = v
    try:
        cred = fields["Credential"]
        signed_headers = fields["SignedHeaders"].split(";")
        signature = fields["Signature"]
    except KeyError:
        raise HttpError(403, "malformed authorization header")
    key_id, scope_date = _parse_credential(cred, region, service)
    amz_date = req.header("x-amz-date") or req.header("date") or ""
    _check_date(amz_date, scope_date)
    secret = await lookup_secret(key_id)
    if secret is None:
        raise HttpError(403, "no such key")
    payload_hash = req.header("x-amz-content-sha256") or UNSIGNED_PAYLOAD
    from .http import parse_query

    _, raw_pairs = parse_query(req.raw_query)
    creq = canonical_request(req.method, req.raw_path, raw_pairs,
                             req.headers, signed_headers, payload_hash)
    scope = f"{scope_date}/{region}/{service}/aws4_request"
    sk = signing_key(secret, scope_date, region, service)
    expect = hmac.new(sk, string_to_sign(amz_date, scope, creq).encode(),
                      hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, signature):
        raise HttpError(403, "signature mismatch")
    return VerifiedRequest(key_id, payload_hash, signature, scope_date,
                           sk, False)


async def _verify_presigned(req: Request, region: str, lookup_secret,
                            service: str = SERVICE) -> VerifiedRequest:
    """ref: payload.rs check_presigned_signature."""
    q = req.query
    try:
        cred = q["X-Amz-Credential"]
        amz_date = q["X-Amz-Date"]
        expires = int(q["X-Amz-Expires"])
        signed_headers = q["X-Amz-SignedHeaders"].split(";")
        signature = q["X-Amz-Signature"]
    except (KeyError, ValueError):
        raise HttpError(403, "malformed presigned query")
    key_id, scope_date = _parse_credential(cred, region, service)
    t = parse_amz_date(amz_date)
    now = datetime.datetime.now(datetime.timezone.utc)
    if now > t + datetime.timedelta(seconds=min(expires, 7 * 86400)):
        raise HttpError(403, "presigned URL expired")
    secret = await lookup_secret(key_id)
    if secret is None:
        raise HttpError(403, "no such key")
    from .http import parse_query

    _, raw_pairs = parse_query(req.raw_query)
    creq = canonical_request(req.method, req.raw_path, raw_pairs,
                             req.headers, signed_headers, UNSIGNED_PAYLOAD,
                             skip_query=("X-Amz-Signature",))
    scope = f"{scope_date}/{region}/{service}/aws4_request"
    sk = signing_key(secret, scope_date, region, service)
    expect = hmac.new(sk, string_to_sign(amz_date, scope, creq).encode(),
                      hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, signature):
        raise HttpError(403, "signature mismatch")
    return VerifiedRequest(key_id, UNSIGNED_PAYLOAD, signature, scope_date,
                           sk, True)


# ---- payload body wrappers (ref: signature/streaming.rs) ---------------


class SignedPayloadReader:
    """Whole-body sha256 check for x-amz-content-sha256=<hex> requests.

    MiB-scale chunks hash in a worker thread, and the previous chunk's
    hash runs WHILE the next chunk is read off the socket, so on
    multicore the verification cost overlaps I/O instead of serializing
    with it. Updates stay strictly ordered: the pending hash is awaited
    before the next one is scheduled."""

    def __init__(self, inner: BodyReader, expect_hex: str):
        self.inner = inner
        self.h = hashlib.sha256()
        self.expect = expect_hex
        self._hash_task: Optional[asyncio.Task] = None

    async def readinto1(self, mv: memoryview) -> int:
        """Zero-copy ingest (ISSUE 17): land the next span directly in
        a leased buffer slice and advance the running body hash over
        the view — no per-chunk bytes object. The whole-body digest is
        inherently serial, so the update runs inline (a ≤64 KiB span
        hashes in tens of microseconds; MiB-scale spans never occur —
        the chunker asks for at most one socket read's worth)."""
        if self._hash_task is not None:
            # a prior read()'s off-thread hash must land first to keep
            # update order; mixed read()/readinto1 use is legal
            task, self._hash_task = self._hash_task, None
            await task
        n = await self.inner.readinto1(mv)
        if n:
            self.h.update(mv[:n])
        elif self.h.hexdigest() != self.expect:
            raise HttpError(400, "payload checksum mismatch")
        return n

    async def read(self, n: int = 65536) -> bytes:
        if self._hash_task is not None:
            task, self._hash_task = self._hash_task, None
            chunk, _ = await asyncio.gather(self.inner.read(n), task)
        else:
            chunk = await self.inner.read(n)
        if chunk:
            if _MULTICORE and len(chunk) >= _HASH_OFFLOAD_MIN:
                self._hash_task = asyncio.create_task(
                    asyncio.to_thread(self.h.update, chunk))
            else:
                self.h.update(chunk)
        elif self.h.hexdigest() != self.expect:
            raise HttpError(400, "payload checksum mismatch")
        return chunk

    async def read_all(self, limit: int = 1 << 30) -> bytes:
        out = bytearray()
        while True:
            c = await self.read()
            if not c:
                return bytes(out)
            out.extend(c)
            if len(out) > limit:
                raise HttpError(413)

    async def drain(self):
        if self._hash_task is not None:
            task, self._hash_task = self._hash_task, None
            await task
        await self.inner.drain()


class AwsChunkedReader:
    """Decodes aws-chunked framing, verifying per-chunk signatures when
    the payload is STREAMING-AWS4-HMAC-SHA256-PAYLOAD.

    chunk: <hex size>;chunk-signature=<sig>\r\n <data> \r\n
    chunk signature = HMAC(sk, "AWS4-HMAC-SHA256-PAYLOAD" \n date \n
                      scope \n previous-sig \n sha256("") \n sha256(data))
    ref: streaming.rs.

    Verification is PIPELINED: a returned chunk's sha256 runs in a
    worker thread while the caller processes it and the next chunk is
    read; the signature check settles at the start of the next read()
    (the HMAC chain needs chunk order anyway). A forged chunk therefore
    raises 403 one read later than the strictly-serial decoder did —
    still before the body ever completes, so nothing a handler stores
    can be finalized from a forged stream (the request aborts and the
    upload is tombstoned), but MiB-scale hashing no longer serializes
    with socket reads.
    """

    def __init__(self, inner: BodyReader, verified: VerifiedRequest,
                 region: str, amz_date: str, signed: bool,
                 trailer: bool = False,
                 trailer_algo: Optional[str] = None,
                 feeder=None):
        self.inner = inner
        self.v = verified
        self.region = region
        self.amz_date = amz_date
        self.signed = signed
        self.trailer = trailer
        self.prev_sig = verified.signature
        self._buf = bytearray()
        self._done = False
        # previously returned chunk awaiting verification:
        # (data, sig, hash_task | None)
        self._pending: Optional[tuple] = None
        # cross-connection hash batching (ISSUE 17): when set, whole-
        # chunk sha256 jobs route through the device feeder so
        # concurrent PUT streams' chunk hashes coalesce into one
        # padded launch (the feeder keeps the host path as the small/
        # low-concurrency floor, same routing discipline as decode)
        self._feeder = feeder
        # zero-copy mode state (readinto1): the current chunk's
        # remaining payload bytes, its declared signature, the spans it
        # landed in the CURRENT lease (hashed as one batched feeder
        # message at chunk end), and the host hasher spans fold into
        # when the chunk outlives a lease
        self._chunk_left = 0
        self._chunk_sig: Optional[str] = None
        self._chunk_spans: list = []
        self._chunk_hasher = None
        self._checksummer = None
        if trailer_algo is not None:
            from .checksum import Checksummer

            self._checksummer = Checksummer(trailer_algo)

    async def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            c = await self.inner.read()
            if not c:
                raise HttpError(400, "truncated aws-chunked body")
            self._buf.extend(c)
        i = self._buf.index(b"\r\n")
        line = bytes(self._buf[:i])
        del self._buf[:i + 2]
        return line

    async def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            c = await self.inner.read()
            if not c:
                raise HttpError(400, "truncated aws-chunked body")
            self._buf.extend(c)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def _chunk_string_to_sign(self, data_sha_hex: str) -> str:
        scope = f"{self.v.scope_date}/{self.region}/{SERVICE}/aws4_request"
        return "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", self.amz_date, scope, self.prev_sig,
            _sha256(b""), data_sha_hex,
        ])

    def _start_hash(self, data: bytes):
        if len(data) >= _HASH_OFFLOAD_MIN and self._feeder is not None:
            # feeder lane: concurrent PUT streams' chunk hashes batch
            # into one device launch; the feeder itself falls back to
            # an inline host hash when the stream is alone or the
            # device is losing (routing floor) — either way the task
            # resolves to the hex digest _settle expects
            return asyncio.create_task(self._feeder.sha256_hex(data))
        if _MULTICORE and len(data) >= _HASH_OFFLOAD_MIN:
            return asyncio.create_task(
                asyncio.to_thread(lambda: _sha256(data)))
        return None

    def _verify_chunk_sig(self, data_sha_hex: str, sig: str) -> None:
        """Check one chunk's signature and advance the HMAC chain."""
        expect = hmac.new(self.v.signing_key,
                          self._chunk_string_to_sign(data_sha_hex).encode(),
                          hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, sig):
            raise HttpError(403, "chunk signature mismatch")
        self.prev_sig = expect

    async def _settle(self) -> None:
        """Finish the previous chunk: await its off-thread sha256,
        verify its signature (advancing the HMAC chain), feed the
        trailing checksummer. Chunk order is preserved because at most
        one chunk is ever pending."""
        if self._pending is None:
            return
        data, sig, task = self._pending
        self._pending = None
        sha_hex = (await task) if task is not None else _sha256(data)
        if self.signed:
            self._verify_chunk_sig(sha_hex, sig)
        if self._checksummer is not None:
            self._checksummer.update(data)

    async def read(self, n: int = 1 << 30) -> bytes:
        """Returns one decoded chunk (ignores n except as a hint)."""
        if self._done:
            return b""
        header = await self._read_line()
        size_part, _, ext = header.partition(b";")
        try:
            size = int(size_part, 16)
        except ValueError:
            raise HttpError(400, "bad aws-chunk header")
        sig = None
        if ext.startswith(b"chunk-signature="):
            sig = ext[len(b"chunk-signature="):].decode()
        if self.signed and sig is None:
            raise HttpError(403, "missing chunk signature")
        data = await self._read_exact(size)
        # the previous chunk's hash has been running while we read;
        # settle it now — prev_sig must advance before this chunk's
        # signature can be checked
        await self._settle()
        if size == 0:
            if self.signed:
                self._verify_chunk_sig(_sha256(b""), sig)
            # trailer section follows the final chunk header directly
            # (ref: streaming.rs parse_next — no data CRLF here)
            if self.trailer:
                await self._verify_trailer()
            else:
                await self._read_exact(2)  # final CRLF
            await self.inner.drain()
            self._done = True
            return b""
        await self._read_exact(2)  # CRLF after data
        self._pending = (data, sig, self._start_hash(data))
        return data

    async def readinto1(self, mv: memoryview) -> int:
        """Zero-copy ingest (ISSUE 17): decode the aws-chunked framing
        but land payload bytes directly in `mv` (a leased ingest-buffer
        slice), -> bytes written, 0 at end. A client chunk larger than
        `mv` is consumed across calls; its sha256 accumulates
        incrementally and the signature verifies at the chunk's last
        span — strictly EARLIER than the pipelined read() path settles
        (which is one read later), so the forged-chunk guarantee is
        preserved. Do not interleave with read() mid-chunk."""
        if self._done:
            return 0
        if self._chunk_left == 0:
            await self._settle()  # a prior read()'s pending chunk
            header = await self._read_line()
            size_part, _, ext = header.partition(b";")
            try:
                size = int(size_part, 16)
            except ValueError:
                raise HttpError(400, "bad aws-chunk header")
            sig = None
            if ext.startswith(b"chunk-signature="):
                sig = ext[len(b"chunk-signature="):].decode()
            if self.signed and sig is None:
                raise HttpError(403, "missing chunk signature")
            if size == 0:
                if self.signed:
                    self._verify_chunk_sig(_sha256(b""), sig)
                if self.trailer:
                    await self._verify_trailer()
                else:
                    await self._read_exact(2)  # final CRLF
                await self.inner.drain()
                self._done = True
                return 0
            self._chunk_left = size
            self._chunk_sig = sig
            self._chunk_spans = []
            self._chunk_hasher = None
        want = min(len(mv), self._chunk_left)
        if self._buf:
            # spill: a header-line read overshot into payload; those
            # bytes hop through _buf before landing (bounded by one
            # socket read per chunk — counted so the copy budget in
            # bench_put_path stays honest)
            n = min(want, len(self._buf))
            mv[:n] = self._buf[:n]
            del self._buf[:n]
            from ..utils.metrics import registry

            registry().inc("s3_put_copy_bytes", n, path="spill")
        else:
            n = await self.inner.readinto1(mv[:want])
            if not n:
                raise HttpError(400, "truncated aws-chunked body")
        span = mv[:n]
        if self.signed:
            self._chunk_spans.append(span)
        if self._checksummer is not None:
            self._checksummer.update(span)
        self._chunk_left -= n
        if self._chunk_left == 0:
            await self._read_exact(2)  # CRLF after data
            if self.signed:
                self._verify_chunk_sig(await self._chunk_sha_hex(),
                                       self._chunk_sig)
        elif self.signed and n == len(mv):
            # the destination (a leased block buffer) just filled: the
            # caller hands it to the put pipeline, which recycles it on
            # release — fold its spans into a host hasher NOW, while
            # the bytes are still this chunk's to read
            self._fold_spans()
        return n

    def _fold_spans(self) -> None:
        if self._chunk_hasher is None:
            self._chunk_hasher = hashlib.sha256()
        for s in self._chunk_spans:
            self._chunk_hasher.update(s)
        self._chunk_spans = []

    async def _chunk_sha_hex(self) -> str:
        """Digest of the just-completed chunk. A chunk wholly resident
        in the live lease rides the feeder's batched sha256 lane as its
        span list — concurrent streams' chunk hashes coalesce into one
        device launch with zero host copies (the SHA pad-in IS the h2d
        staging). A chunk that crossed a lease boundary was folded into
        a host hasher at the handoff and finishes there."""
        spans, self._chunk_spans = self._chunk_spans, []
        if self._chunk_hasher is not None:
            h, self._chunk_hasher = self._chunk_hasher, None
            for s in spans:
                h.update(s)
            return h.hexdigest()
        if self._feeder is not None \
                and sum(len(s) for s in spans) >= _HASH_OFFLOAD_MIN:
            return await self._feeder.sha256_hex(spans)
        h = hashlib.sha256()
        for s in spans:
            h.update(s)
        return h.hexdigest()

    async def _verify_trailer(self) -> None:
        """Parse `name:value[\\n]\\r\\n` (+ x-amz-trailer-signature for
        signed mode), check the declared checksum against the payload,
        and verify the trailer signature (ref: streaming.rs
        TrailerChunk::parse_*, compute_streaming_trailer_signature)."""
        line = await self._read_line()
        if not line and self._checksummer is None and not self.signed:
            return  # legitimately empty trailer section: 0\r\n\r\n
        name, sep, value = line.partition(b":")
        if not sep:
            raise HttpError(400, "malformed trailer")
        name = name.strip().decode("latin-1").lower()
        value = value.strip().decode("latin-1")
        if self.signed:
            sig_line = await self._read_line()
            if not sig_line.startswith(b"x-amz-trailer-signature:"):
                raise HttpError(403, "missing x-amz-trailer-signature")
            sig = sig_line.partition(b":")[2].strip().decode()
            trailer_blob = f"{name}:{value}\n".encode()
            scope = (f"{self.v.scope_date}/{self.region}/{SERVICE}"
                     "/aws4_request")
            ok = False
            # AWS documents AWS4-HMAC-SHA256-TRAILER; the reference
            # signs with AWS4-HMAC-SHA256-PAYLOAD — accept either.
            for label in ("AWS4-HMAC-SHA256-TRAILER",
                          "AWS4-HMAC-SHA256-PAYLOAD"):
                sts = "\n".join([label, self.amz_date, scope, self.prev_sig,
                                 _sha256(trailer_blob)])
                expect = hmac.new(self.v.signing_key, sts.encode(),
                                  hashlib.sha256).hexdigest()
                if hmac.compare_digest(expect, sig):
                    ok = True
                    break
            if not ok:
                raise HttpError(403, "trailer signature mismatch")
        if self._checksummer is not None:
            from .checksum import header_algorithm

            if header_algorithm(name) == self._checksummer.algo:
                if value != self._checksummer.b64():
                    raise HttpError(400, "trailing checksum mismatch")
            else:
                raise HttpError(400, f"expected {self._checksummer.algo} "
                                     "trailer checksum")

    async def read_all(self, limit: int = 1 << 30) -> bytes:
        out = bytearray()
        while True:
            c = await self.read()
            if not c:
                return bytes(out)
            out.extend(c)
            if len(out) > limit:
                raise HttpError(413)

    async def drain(self):
        # settle a pending off-thread hash so no task outlives the
        # request (the verdict no longer matters: the body is being
        # discarded, not stored)
        if self._pending is not None:
            _data, _sig, task = self._pending
            self._pending = None
            if task is not None:
                await task
        await self.inner.drain()


def wrap_body(req: Request, verified: Optional[VerifiedRequest],
              region: str, feeder=None):
    """Give the handler a body reader enforcing the payload integrity
    mode the client declared. `feeder` (the block manager's device
    feeder, when the caller has one) lets aws-chunked per-chunk sha256
    jobs batch across concurrent connections (ISSUE 17)."""
    if verified is None:
        return req.body
    cs = verified.content_sha256
    amz_date = req.header("x-amz-date") or ""
    if cs == STREAMING_SIGNED:
        return AwsChunkedReader(req.body, verified, region, amz_date, True,
                                feeder=feeder)
    if cs in (STREAMING_UNSIGNED_TRAILER, STREAMING_SIGNED_TRAILER):
        from .checksum import trailer_algorithm

        try:
            talgo = trailer_algorithm(req.headers)
        except ValueError as e:
            raise HttpError(400, str(e))
        return AwsChunkedReader(req.body, verified, region, amz_date,
                                cs == STREAMING_SIGNED_TRAILER,
                                trailer=True, trailer_algo=talgo,
                                feeder=feeder)
    if cs and cs != UNSIGNED_PAYLOAD:
        return SignedPayloadReader(req.body, cs)
    return req.body
