"""Tiny XML writer + S3 error responses.

Ref parity: src/api/s3/xml.rs + error.rs. S3 responses are small XML
documents; a nested (tag, content) structure is enough.
"""

from __future__ import annotations

from typing import Optional
from xml.sax.saxutils import escape

from ..http import Response

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def xml(tag: str, *children, **attrs) -> tuple:
    return (tag, attrs, list(children))


def render_node(node) -> str:
    if isinstance(node, str):
        return escape(node)
    tag, attrs, children = node
    a = "".join(f' {k}="{escape(str(v))}"' for k, v in attrs.items())
    inner = "".join(render_node(c) for c in children)
    return f"<{tag}{a}>{inner}</{tag}>"


def xml_response(root, status: int = 200,
                 extra_headers: Optional[list] = None) -> Response:
    body = ('<?xml version="1.0" encoding="UTF-8"?>'
            + render_node(root)).encode()
    headers = [("content-type", "application/xml")] + (extra_headers or [])
    return Response(status, headers, body)


class S3Error(Exception):
    """ref: api/s3/error.rs — code + HTTP status + message."""

    def __init__(self, code: str, status: int, message: str = "",
                 resource: str = "", headers: Optional[list] = None):
        self.code = code
        self.status = status
        self.message = message or code
        self.resource = resource
        self.headers = headers or []
        super().__init__(f"{code}: {self.message}")

    def response(self) -> Response:
        return xml_response(
            xml("Error",
                xml("Code", self.code),
                xml("Message", self.message),
                xml("Resource", self.resource),
                xml("Region", "garage")),
            status=self.status,
            extra_headers=self.headers,
        )


def no_such_key(key: str = "") -> S3Error:
    return S3Error("NoSuchKey", 404, "The specified key does not exist.", key)


def no_such_bucket(name: str = "") -> S3Error:
    return S3Error("NoSuchBucket", 404,
                   "The specified bucket does not exist.", name)


def access_denied(msg: str = "Access Denied.") -> S3Error:
    return S3Error("AccessDenied", 403, msg)


def bad_request(msg: str) -> S3Error:
    return S3Error("InvalidRequest", 400, msg)


def slow_down(retry_after_header: str) -> S3Error:
    """Admission-control shed (ref: S3's real overload answer — code
    `SlowDown`, HTTP 503 — plus the standard Retry-After hint)."""
    return S3Error(
        "SlowDown", 503,
        "Please reduce your request rate.",
        headers=[("retry-after", retry_after_header)],
    )
