"""PutObject / CopyObject: the hot write path.

Ref parity: src/api/s3/put.rs:60-640. save_stream chunks the body at
block_size, inlines tiny objects (< 3072 B) into the object row, and
otherwise pipelines: read chunk -> md5+blake2 hash -> put block + meta
(≤ 3 concurrent), exactly the reference's staged pipeline. The TPU batch
plane hooks in at BlockManager (hashing/erasure batching happen below
this layer).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
from typing import Optional

from ...block.manager import INLINE_THRESHOLD
from ...model.s3.block_ref_table import BlockRef
from ...model.s3.object_table import (Object, ObjectVersion,
                                      ObjectVersionData, ObjectVersionMeta,
                                      ObjectVersionState,
                                      object_upload_version)
from ...model.s3.version_table import BACKLINK_OBJECT, Version
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ...utils.metrics import registry
from ..http import Request, Response
from .xml import S3Error, bad_request

log = logging.getLogger("garage_tpu.api.s3.put")

# default concurrent block writes in the put pipeline (ref: put.rs:42);
# the live value comes from `[s3_api] put_blocks_max_parallel`
# (config.s3_put_blocks_max_parallel), runtime-tunable via admin
# POST /v1/s3/tuning so the bench can sweep it
PUT_BLOCKS_MAX_PARALLEL = 3
_MULTICORE = (os.cpu_count() or 1) > 1


def put_parallelism(garage) -> int:
    v = getattr(garage.config, "s3_put_blocks_max_parallel",
                PUT_BLOCKS_MAX_PARALLEL)
    return max(1, int(v or PUT_BLOCKS_MAX_PARALLEL))


class Chunker:
    """Re-chunk a body reader into block_size blocks
    (ref: put.rs StreamChunker). Asking the reader for exactly the
    missing byte count (read() never over-returns) means blocks
    assemble with ONE join copy — zero when a read yields the whole
    block — instead of the old bytearray extend+slice+memmove trio,
    which was a measurable share of the one-core PUT path.

    With `pool` (a hostbuf.HostBufPool — the zero-copy ingest path,
    ISSUE 17), full blocks land DIRECTLY in a leased stripe-layout
    buffer via the reader's readinto1 and next() returns the
    BlockLease; partial tail blocks (and readers without readinto1)
    degrade to bytes through the classic path. The CALLER owns each
    returned lease and must release it."""

    def __init__(self, body, block_size: int, shape=None, pool=None):
        self.body = body
        self.block_size = block_size
        self.eof = False
        # qos byte-shaper (async callable) for bodies whose length was
        # unknown at admission time — see qos.QosEngine.shape_bytes
        self.shape = shape
        self.pool = pool
        self._rest = b""  # overshoot carry (AwsChunkedReader returns
        # whole decoded client chunks, ignoring the requested size)

    async def next(self):
        if self.pool is not None:
            return await self._next_lease()
        return await self._next_bytes()

    async def _next_lease(self):
        """Fill a leased buffer in place. Returns the lease (full
        block), bytes (the sub-block tail — its true shard length
        differs, so it takes the classic staging path), or None."""
        if self.eof and not self._rest:
            return None
        lease = await self.pool.acquire()
        mv = lease.body_mv()
        have = 0
        try:
            if self._rest:
                # a carry only exists after a readinto1-less fallback
                # read over-returned; land it first (counted: it IS a
                # copy into the buffer)
                r = self._rest
                n = min(len(r), self.block_size)
                mv[:n] = r[:n]
                registry().inc("s3_put_copy_bytes", n, path="assemble")
                self._rest = r[n:] if n < len(r) else b""
                have = n
            readinto = getattr(self.body, "readinto1", None)
            while not self.eof and have < self.block_size:
                if readinto is not None:
                    n = await readinto(mv[have:self.block_size])
                    if not n:
                        self.eof = True
                        break
                    have += n
                else:
                    chunk = await self.body.read(self.block_size - have)
                    if not chunk:
                        self.eof = True
                        break
                    fit = min(len(chunk), self.block_size - have)
                    mv[have:have + fit] = chunk[:fit]
                    registry().inc("s3_put_copy_bytes", fit,
                                   path="ingest")
                    if fit < len(chunk):
                        self._rest = chunk[fit:]
                    have += fit
            if not have:
                lease.release()
                return None
            if self.shape is not None:
                await self.shape(have)
            if have == self.block_size:
                lease.length = have
                out, lease = lease, None  # ownership moves to the caller
                return out
            # tail block: materialize once and recycle the buffer
            out = bytes(mv[:have])
            registry().inc("s3_put_copy_bytes", have, path="assemble")
            return out
        finally:
            if lease is not None:
                lease.release()

    async def _next_bytes(self) -> Optional[bytes]:
        chunks: list = []
        have = 0
        if self._rest:
            chunks.append(self._rest)
            have = len(self._rest)
            self._rest = b""
        while not self.eof and have < self.block_size:
            chunk = await self.body.read(self.block_size - have)
            if not chunk:
                self.eof = True
                break
            chunks.append(chunk)
            have += len(chunk)
            # every read() materializes fresh bytes between the socket
            # and the block — the copy the leased path deletes
            registry().inc("s3_put_copy_bytes", len(chunk), path="read")
        if not have:
            return None
        whole = chunks[0] if len(chunks) == 1 else b"".join(chunks)
        if len(chunks) > 1:
            registry().inc("s3_put_copy_bytes", len(whole),
                           path="assemble")
        if have > self.block_size:
            # memoryview carry: the overshoot (an AwsChunkedReader can
            # return a many-MiB client chunk) is carried as a zero-copy
            # view over `whole`; the old bytes-slice pair copied both
            # halves of every oversized chunk. The view is materialized
            # exactly once, when it lands in a returned block below.
            mv = memoryview(whole)
            self._rest = mv[self.block_size:]
            whole = mv[:self.block_size]
        if self.shape is not None:
            await self.shape(len(whole))
        # downstream (hashing, encryption, the block RPC) expects real
        # bytes; a view materializes here — ONE copy per block total
        if not isinstance(whole, bytes):
            registry().inc("s3_put_copy_bytes", len(whole),
                           path="assemble")
            whole = bytes(whole)
        return whole


def extract_metadata_headers(req: Request) -> dict:
    """content-type + x-amz-meta-* + standard overridable headers
    (ref: put.rs get_headers)."""
    out = {}
    for h in ("content-type", "content-encoding", "content-language",
              "content-disposition", "cache-control", "expires"):
        v = req.header(h)
        if v is not None:
            out[h] = v
    for name, v in req.headers.items():
        if name.startswith("x-amz-meta-"):
            out[name] = v
    redir = req.header("x-amz-website-redirect-location")
    if redir is not None:
        # ref: put.rs:681-692 — stored as metadata; the web server
        # serves a 301 to it
        if not redir.startswith(("/", "http://", "https://")):
            raise bad_request(
                "Invalid x-amz-website-redirect-location header")
        out["x-amz-website-redirect-location"] = redir
    return out


def next_timestamp(existing: Optional[Object]) -> int:
    """ref: put.rs next_timestamp — strictly after any existing
    version."""
    now = now_msec()
    if existing is None or not existing.versions:
        return now
    return max(now, max(v.timestamp for v in existing.versions) + 1)


async def get_bucket_quotas(garage, bucket_id: bytes) -> dict:
    bucket = await garage.bucket_table.get(bucket_id, b"")
    params = bucket.params if bucket is not None else None
    return (params.quotas.value if params is not None else None) or {}


async def check_quotas(garage, bucket_id: bytes,
                       size_hint: Optional[int], existing,
                       quotas: Optional[dict] = None) -> None:
    """Reject when this upload would exceed the bucket's quotas
    (ref: src/api/s3/put.rs check_quotas). Every write path (put, copy,
    post_object, multipart complete) enforces the same rule: once early
    with the declared length, and again after streaming with the REAL
    total (a spoofed or missing length header must not bypass the size
    quota). `size_hint` None = unknown: only the object-count quota can
    be checked; replacing an object frees its current size."""
    q = quotas if quotas is not None \
        else await get_bucket_quotas(garage, bucket_id)
    max_size, max_objects = q.get("max_size"), q.get("max_objects")
    if max_size is None and max_objects is None:
        return
    nodes = list(
        garage.system.layout_manager.history.all_nongateway_nodes())
    counters = await garage.object_counter.read(bucket_id, b"", nodes)
    replaced = existing.last_data() if existing is not None else None
    if max_objects is not None and replaced is None:
        if counters.get("objects", 0) + 1 > max_objects:
            raise S3Error("AccessDenied", 403,
                          "Object quota is reached on this bucket")
    if max_size is not None and size_hint is not None:
        freed = replaced.state.data.meta.size if replaced is not None else 0
        if counters.get("bytes", 0) - freed + size_hint > max_size:
            raise S3Error("AccessDenied", 403,
                          "Bucket size quota is reached")


async def save_stream(garage, bucket_id: bytes, key: str, headers: dict,
                      body, content_md5: Optional[str] = None,
                      expected_checksum: Optional[tuple[str, str]] = None,
                      sse_key=None,
                      content_length: Optional[int] = None,
                      quotas: Optional[dict] = None):
    """-> (version_uuid, version_timestamp, etag, total_size).
    ref: put.rs:122-330 save_stream. `expected_checksum` is a declared
    (algo, base64-value) x-amz-checksum-* header to enforce; `sse_key`
    is an SSE-C customer key — blocks (and inline payloads) are stored
    AES-GCM encrypted, metadata records only the key's MD5."""
    checksummer = None
    if expected_checksum is not None:
        from ..checksum import Checksummer

        checksummer = Checksummer(expected_checksum[0])
    if sse_key is not None:
        from .encryption import META_SSEC_ALGO, META_SSEC_MD5

        headers = {**headers, META_SSEC_ALGO: "AES256",
                   META_SSEC_MD5: sse_key.md5_b64}
    if expected_checksum is not None:
        # persist the validated checksum so GET/HEAD can return it
        # under x-amz-checksum-mode: ENABLED (ref: checksum.rs storage)
        headers = {**headers,
                   f"x-garage-checksum-{expected_checksum[0]}":
                       expected_checksum[1]}
    from ...utils.tracing import span

    block_size = garage.config.block_size
    # bodies that declared a length were charged to the qos bytes
    # bucket at admission; unknown-length (chunked) bodies are shaped
    # per-block here instead, so neither path double-charges
    qos = getattr(garage, "qos", None)
    shape = (qos.shape_bytes if qos is not None
             and content_length is None else None)
    # zero-copy ingest pool (ISSUE 17): erasure-mode plaintext PUTs
    # land full blocks straight into stripe-layout lease buffers.
    # SSE-C keeps the classic path — encryption rewrites every byte
    # anyway, so in-place staging buys nothing there.
    pool = None
    if sse_key is None:
        pool = garage.block_manager.ingest_pool(
            block_size, getattr(garage.config, "s3_ingest_buffers", 0))
    chunker = Chunker(body, block_size, shape=shape, pool=pool)
    async with span("s3.put.first_read_and_lookup"):
        first_block, existing = await asyncio.gather(
            chunker.next(), garage.object_table.get(bucket_id, key.encode())
        )
    try:
        if quotas is None:  # callers with a loaded ReqCtx pass them in
            quotas = await get_bucket_quotas(garage, bucket_id)
        await check_quotas(garage, bucket_id, content_length, existing,
                           quotas=quotas)
    except BaseException:
        # a leased first block must go back to the pool on ANY early
        # exit (quota reached, table error) — release is idempotent,
        # so later owners double-releasing is harmless
        if hasattr(first_block, "release"):
            first_block.release()
        raise
    first_block = first_block or b""
    if hasattr(first_block, "release") \
            and len(first_block) < INLINE_THRESHOLD:
        # only reachable with a sub-threshold block_size: the inline
        # branch stores bytes, so materialize and recycle the lease
        _l = first_block
        first_block = bytes(_l.view())
        _l.release()
    uuid = gen_uuid()
    ts = next_timestamp(existing)
    from ... import native

    md5 = native.Md5()  # hashlib fallback inside when no native lib

    if len(first_block) < INLINE_THRESHOLD:
        if content_length != len(first_block):
            # declared length absent or wrong (spoofed
            # x-amz-decoded-content-length): enforce on the actual size
            await check_quotas(garage, bucket_id, len(first_block),
                               existing, quotas=quotas)
        md5.update(first_block)
        etag = md5.hexdigest()
        if content_md5 is not None and not _md5_matches(content_md5, etag):
            raise bad_request("Content-MD5 mismatch")
        if checksummer is not None:
            # lint: ignore[GL10] first update may lazily build+dlopen the native CRC lib (one-time, lock-guarded); steady state is an in-memory table update
            checksummer.update(first_block)
            if checksummer.b64() != expected_checksum[1]:
                raise bad_request("checksum mismatch")
        if sse_key is not None:
            # never record/expose the plaintext MD5 of an encrypted
            # object: a queryable plaintext digest lets any reader
            # dictionary-attack SSE-C content (ref: encryption.rs:210)
            etag = ssec_etag()
        meta = ObjectVersionMeta(headers, len(first_block), etag)
        blob = (sse_key.encrypt_block(first_block) if sse_key is not None
                else first_block)
        ov = ObjectVersion(uuid, ts, ObjectVersionState.complete(
            ObjectVersionData.inline(meta, blob)))
        await garage.object_table.insert(Object(bucket_id, key, [ov]))
        return uuid, ts, etag, len(first_block)

    try:
        # register the upload, then stream blocks
        up = Object(bucket_id, key, [ObjectVersion(
            uuid, ts,
            ObjectVersionState.uploading(headers, multipart=False))])
        await garage.object_table.insert(up)
        version = Version.new(uuid, (BACKLINK_OBJECT, bucket_id, key))
        await garage.version_table.insert(version)

        total, md5_hex, etag, first_hash = await read_and_put_blocks(
            garage, version, 1, first_block, chunker, md5,
            checksummer=checksummer, sse_key=sse_key)
        if total != content_length:
            # the declared length was wrong or absent (spoofed
            # x-amz-decoded-content-length, form upload with no length):
            # re-check the size quota with the REAL streamed total
            await check_quotas(garage, bucket_id, total, existing,
                               quotas=quotas)
        if content_md5 is not None \
                and not _md5_matches(content_md5, md5_hex):
            raise bad_request("Content-MD5 mismatch")
        if checksummer is not None \
                and checksummer.b64() != expected_checksum[1]:
            raise bad_request("checksum mismatch")
        meta = ObjectVersionMeta(headers, total, etag)
        done = Object(bucket_id, key, [ObjectVersion(
            uuid, ts, ObjectVersionState.complete(
                ObjectVersionData.first_block(meta, first_hash)))])
        await garage.object_table.insert(done)
        return uuid, ts, etag, total
    except BaseException:
        if hasattr(first_block, "release"):
            first_block.release()  # idempotent (see above)
        # interrupted upload: mark aborted so refs get cleaned up
        # (ref: put.rs InterruptedCleanup)
        try:
            await garage.object_table.insert(Object(bucket_id, key, [
                ObjectVersion(uuid, ts, ObjectVersionState.aborted())]))
        except Exception as e:
            log.warning("aborted-upload marker failed (refs leak until "
                        "repair): %s", e)
        raise


def ssec_etag() -> str:
    """Random ETag for SSE-C objects (ref: encryption.rs:210-222) —
    32 hex chars so complete-multipart's bytes.fromhex still works."""
    import os

    return os.urandom(16).hex()


def _md5_matches(content_md5_b64: str, etag_hex: str) -> bool:
    import base64

    try:
        return base64.b64decode(content_md5_b64).hex() == etag_hex
    except Exception:
        return False


async def read_and_put_blocks(garage, version: Version, part_number: int,
                              first_block: bytes, chunker: Chunker, md5,
                              checksummer=None, sse_key=None):
    """The staged put pipeline (ref: put.rs:378-530): ≤3 concurrent
    block writes; version + block_ref rows inserted alongside each
    block. With `sse_key`, blocks are AES-GCM encrypted before hashing
    and storage (the content address covers the ciphertext, so scrub
    verifies without the key); the version's block map keeps PLAINTEXT
    sizes so range reads address plaintext offsets.

    -> (total_size, md5_hex, etag, first_hash). `md5_hex` is the
    plaintext MD5 for Content-MD5 validation only; `etag` is what may
    be stored/exposed — randomized here, structurally, whenever
    sse_key is set (ref: encryption.rs:210-222), so no call site can
    forget and leak the plaintext digest."""
    max_parallel = put_parallelism(garage)
    sem = asyncio.Semaphore(max_parallel)
    tasks: list[asyncio.Task] = []
    offset = 0
    first_hash = None
    block = first_block
    # rows THIS request enqueued, per table: the targeted flush
    # probes only keys that can exist in that table's queue
    queued_vkeys: set[bytes] = set()
    queued_bkeys: set[bytes] = set()

    async def put_one(blk, off: int, plain_len: int, h: bytes):
        """`blk` is bytes or a BlockLease (zero-copy path). This task
        owns a lease once created: release rides its finally, which
        runs on success, failure AND cancellation."""
        from ...utils.tracing import span

        try:
            async with sem, span("s3.put.block", offset=off,
                                 size=len(blk)):
                v = Version(version.uuid, version.deleted,
                            version.blocks.put((part_number, off),
                                               (h, plain_len)),
                            version.backlink)
                # version/block_ref rows ride the LOCAL insert queue
                # (ONE tiny db tx for both rows) instead of two quorum
                # RPCs per block — the reference's structure
                # (put.rs:545); read_and_put_blocks flushes the queues
                # through the quorum path before the caller commits the
                # Complete row, so read-your-writes is preserved
                from ...table.table import queue_insert_local_many

                # lint: ignore[GL10] measured (ISSUE 9): this deliberately tiny two-row tx (see comment above) costs less than the to_thread handoff on the per-block PUT path
                vk, bk = queue_insert_local_many([
                    (garage.version_table, v),
                    (garage.block_ref_table, BlockRef.new(h, version.uuid)),
                ])
                queued_vkeys.add(vk)
                queued_bkeys.add(bk)
                # SSE-C blocks are never cached (cacheable=False): the
                # stored payload is ciphertext tied to the client's key
                await garage.block_manager.rpc_put_block(
                    h, blk,
                    compress=False if sse_key is not None else None,
                    cacheable=sse_key is None)
        finally:
            if hasattr(blk, "release"):
                blk.release()

    from ...utils.tracing import span

    # plaintext MD5 (ETag chain) fuses with the content hash in one
    # native pass when there is no SSE boundary (md5 covers plaintext,
    # the content hash ciphertext, so encryption forces two walks)
    fused = sse_key is None and getattr(md5, "fused", False)
    feeder = garage.block_manager.feeder
    # active-stream mark (fused streams only: SSE/non-native streams
    # never submit hash_md5, so counting them would make the dispatcher
    # wait for lanes that cannot arrive): sizes the feeder's gather
    # window for the 8-way cross-request MD5
    if fused:
        feeder.active_streams += 1
    try:
        while block is not None:
            # a leased block is read EVERYWHERE below through a view
            # over the pinned buffer — digests, checksums and the
            # feeder all walk the same memory the socket filled
            data = block.view() if hasattr(block, "view") else block
            # md5 (ETag) and the declared checksum are independent
            # digests of the same block: run them concurrently in
            # worker threads (both release the GIL) so the cost is
            # max(), not sum(); on multicore the loop keeps serving
            # other requests meanwhile
            jobs = []
            if not fused:
                if _MULTICORE and len(block) >= 65536:
                    jobs.append(asyncio.to_thread(md5.update, data))
                else:
                    md5.update(data)
            if checksummer is not None:
                jobs.append(asyncio.to_thread(checksummer.update, data))
            if jobs:
                await asyncio.gather(*jobs)
            plain_len = len(block)
            stored = (await asyncio.to_thread(sse_key.encrypt_block, block)
                      if sse_key is not None else block)
            async with span("s3.put.hash", size=plain_len
                            if stored is block else len(stored)):
                if fused:
                    h = await garage.block_manager.hash_block_md5(data, md5)
                else:
                    h = await garage.block_manager.hash_block(
                        data if stored is block else stored)
            if first_hash is None:
                first_hash = h
            tasks.append(asyncio.create_task(
                put_one(stored, offset, plain_len, h)))
            offset += plain_len
            # backpressure: don't build an unbounded task list
            while len(tasks) > max_parallel:
                done, _ = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    if t.exception() is not None:
                        raise t.exception()
                tasks = [t for t in tasks if not t.done()]
            async with span("s3.put.chunk_read"):
                block = await chunker.next()
        if tasks:
            await asyncio.gather(*tasks)
        # make THIS request's queued version/block_ref rows
        # quorum-visible before the caller's Complete insert
        # (read-your-writes); other requests' backlog is theirs to flush
        async with span("s3.put.flush_meta"):
            await garage.version_table.flush_insert_queue(queued_vkeys)
            await garage.block_ref_table.flush_insert_queue(queued_bkeys)
    except BaseException:
        for t in tasks:
            t.cancel()
        # settle cancelled tasks before the caller writes its cleanup
        # tombstone, or a late block_ref insert could race past it
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        # the in-flight block may be a lease not yet handed to a
        # put_one (e.g. the checksum threw between next() and
        # create_task); handed-over ones were just released by their
        # task's finally, so this idempotent release never double-frees
        if hasattr(block, "release"):
            block.release()
        # flush queued rows BEFORE the caller's aborted-object tombstone:
        # the tombstone's trigger queue-inserts Version(deleted=True),
        # which would CRDT-merge into a still-queued per-block row and
        # wipe its block map before replicas ever saw it — then no
        # BlockRef tombstones fire while the queued live BlockRefs still
        # propagate, leaking the blocks' refcounts permanently. Shielded:
        # a task cancellation mid-flush (CancelledError is NOT an
        # Exception) must not reopen that ordering hazard — the flush
        # finishes in the background while we proceed to the tombstone.
        async def _flush_both():
            await garage.version_table.flush_insert_queue(queued_vkeys)
            await garage.block_ref_table.flush_insert_queue(queued_bkeys)

        flush = asyncio.ensure_future(_flush_both())
        # keep re-awaiting until the flush actually lands: returning
        # early (even to re-raise) would let the caller's tombstone
        # insert race the still-in-flight flush — the exact ordering
        # hazard documented above. Repeated cancellations only re-arm
        # the wait; the shielded task itself is never cancelled.
        while not flush.done():
            try:
                await asyncio.shield(flush)
            except asyncio.CancelledError:
                continue
            except Exception:
                break  # flush failed; retrieved below, original re-raised
        flush.cancelled() or flush.exception()  # retrieve, don't mask
        raise
    finally:
        if fused:
            feeder.active_streams -= 1
    md5_hex = md5.hexdigest()
    etag = ssec_etag() if sse_key is not None else md5_hex
    return offset, md5_hex, etag, first_hash


async def handle_put(ctx, req: Request) -> Response:
    """ref: put.rs:60-120 handle_put."""
    from ..checksum import request_checksum_value
    from .encryption import request_sse_key

    headers = extract_metadata_headers(req)
    try:
        expected_checksum = request_checksum_value(req.headers)
    except ValueError as e:
        raise bad_request(str(e))
    sse_key = request_sse_key(req)
    # aws-chunked bodies declare the true payload size separately; the
    # raw content-length there includes per-chunk framing
    cl = req.header("x-amz-decoded-content-length") \
        or req.header("content-length")
    uuid, ts, etag, _ = await save_stream(
        ctx.garage, ctx.bucket_id, ctx.key, headers, req.body,
        content_md5=req.header("content-md5"),
        expected_checksum=expected_checksum,
        sse_key=sse_key,
        content_length=int(cl) if cl and cl.isdigit() else None,
        quotas=(ctx.bucket.params.quotas.value or {})
        if ctx.bucket is not None and ctx.bucket.params is not None
        else None,
    )
    extra = []
    if sse_key is not None:
        from .encryption import ALGO_HEADER, KEY_MD5_HEADER

        extra = [(ALGO_HEADER, "AES256"),
                 (KEY_MD5_HEADER, sse_key.md5_b64)]
    return Response(200, [("etag", f'"{etag}"'),
                          ("x-amz-version-id", uuid.hex())] + extra)


async def handle_copy(ctx, req: Request) -> Response:
    """CopyObject within/between buckets (ref: api/s3/copy.rs — block
    reuse: new version/block_ref rows point at the same hashes; no data
    movement)."""
    from urllib.parse import unquote

    src = unquote(req.header("x-amz-copy-source") or "").lstrip("/")
    src_bucket_name, _, src_key = src.partition("/")
    if not src_bucket_name or not src_key:
        raise bad_request("malformed x-amz-copy-source")
    helper_g = ctx.garage
    from ...model.helper import GarageHelper

    helper = GarageHelper(helper_g)
    src_bucket_id = await helper.resolve_global_bucket_name(src_bucket_name)
    if src_bucket_id is None:
        raise S3Error("NoSuchBucket", 404, src_bucket_name)
    if not ctx.api_key.allow_read(src_bucket_id):
        raise S3Error("AccessDenied", 403, "no read access to source")
    src_obj = await helper_g.object_table.get(src_bucket_id,
                                              src_key.encode())
    src_v = src_obj.last_data() if src_obj is not None else None
    if src_v is None:
        raise S3Error("NoSuchKey", 404, src_key)
    from .get import check_copy_source_preconditions

    check_copy_source_preconditions(req, src_v, src_v.state.data.meta.etag)

    from .encryption import (check_key_for_meta, copy_source_sse_key,
                             meta_is_encrypted, request_sse_key)

    src_sse_hdr = copy_source_sse_key(req)
    dst_sse = request_sse_key(req)
    if src_sse_hdr is None and dst_sse is None \
            and meta_is_encrypted(src_v.state.data.meta):
        # don't silently duplicate ciphertext the caller can't prove it
        # can read (ref: encryption.rs check_decrypt_common)
        raise S3Error(
            "InvalidRequest", 400,
            "source object is SSE-C encrypted; "
            "x-amz-copy-source-server-side-encryption-customer-* "
            "headers are required")
    # x-amz-metadata-directive: REPLACE takes the request's metadata
    # instead of the source's (ref: copy.rs:83-90) — the canonical
    # "update an object's metadata" operation is a self-copy with
    # REPLACE
    replace_meta = (req.header("x-amz-metadata-directive") or "") \
        .upper() == "REPLACE"

    if src_sse_hdr is not None or dst_sse is not None:
        # encryption boundary crossing: stream the source plaintext
        # through the normal save path, re-encrypting under the
        # destination key (ref: copy.rs re-encryption path)
        src_meta = src_v.state.data.meta
        src_sse = check_key_for_meta(src_meta, src_sse_hdr)
        from .get import open_object_stream

        source = await open_object_stream(helper_g, src_v, 0,
                                          src_meta.size, src_sse)
        headers = (extract_metadata_headers(req) if replace_meta
                   else {k: v for k, v in src_meta.headers.items()
                         if not k.startswith("x-garage-ssec-")})
        try:
            uuid, ts, etag, _ = await save_stream(
                helper_g, ctx.bucket_id, ctx.key, headers, source,
                sse_key=dst_sse, content_length=src_meta.size,
                quotas=(ctx.bucket.params.quotas.value or {})
                if ctx.bucket is not None and ctx.bucket.params is not None
                else None)
        finally:
            # an aborted copy must cancel the source's readahead
            # prefetches now, not at GC time
            await source.aclose()
        from .xml import xml, xml_response

        return xml_response(xml("CopyObjectResult",
                                xml("LastModified", _http_date(ts)),
                                xml("ETag", f'"{etag}"')))

    uuid = gen_uuid()
    ts = now_msec()
    data = src_v.state.data
    meta = (ObjectVersionMeta(extract_metadata_headers(req),
                              data.meta.size, data.meta.etag)
            if replace_meta else data.meta)
    if data.kind == "inline":
        ov = ObjectVersion(uuid, ts, ObjectVersionState.complete(
            ObjectVersionData.inline(meta, data.blob)))
        await helper_g.object_table.insert(
            Object(ctx.bucket_id, ctx.key, [ov]))
    else:
        src_version = await helper_g.version_table.get(src_v.uuid, b"")
        if src_version is None:
            raise S3Error("NoSuchKey", 404, src_key)
        up = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
            uuid, ts, ObjectVersionState.uploading({}, False))])
        await helper_g.object_table.insert(up)
        new_version = Version.new(uuid,
                                  (BACKLINK_OBJECT, ctx.bucket_id, ctx.key))
        blocks = list(src_version.blocks.items())
        for bk, (h, size) in blocks:
            new_version = Version(new_version.uuid, new_version.deleted,
                                  new_version.blocks.put(bk, (h, size)),
                                  new_version.backlink)
        await helper_g.version_table.insert(new_version)
        for bk, (h, size) in blocks:
            await helper_g.block_ref_table.insert(BlockRef.new(h, uuid))
        done = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
            uuid, ts, ObjectVersionState.complete(
                ObjectVersionData.first_block(meta, data.blob)))])
        await helper_g.object_table.insert(done)

    from .xml import xml, xml_response

    lm = _http_date(ts)
    return xml_response(xml("CopyObjectResult",
                            xml("LastModified", lm),
                            xml("ETag", f'"{data.meta.etag}"')))


def _http_date(ts_msec: int) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts_msec / 1000, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")
