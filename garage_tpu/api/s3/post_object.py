"""PostObject: browser form uploads (multipart/form-data + POST policy).

Ref parity: src/api/s3/post_object.rs. The request is NOT header-signed:
the form carries a base64 policy document signed with the SigV4 signing
key (signature = HMAC(signing_key, policy_b64)). Every form field must
be authorized by a policy condition (exact / starts-with /
content-length-range), the file must be the last field, and `${filename}`
in the key field substitutes the uploaded file's name.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
from typing import Optional

from ..http import BodyReader, Request, Response
from ..signature import signing_key
from .put import save_stream
from .xml import S3Error, access_denied

# fields the policy need not cover (ref: post_object.rs:147)
_IGNORED_FIELDS = ("policy", "x-amz-signature", "file")


class _FormReader:
    """Streaming multipart/form-data parser. Text fields (before the
    file) are collected into a dict; the `file` part's content is
    exposed as a body-reader that stops at the closing boundary."""

    def __init__(self, body: BodyReader, boundary: str):
        self.body = body
        self.delim = b"\r\n--" + boundary.encode()
        self._buf = bytearray()
        self._eof = False

    async def _fill(self, n: int) -> None:
        while not self._eof and len(self._buf) < n:
            chunk = await self.body.read(65536)
            if not chunk:
                self._eof = True
                break
            self._buf.extend(chunk)

    async def _read_until(self, marker: bytes, limit: int = 1 << 20
                          ) -> bytes:
        """Consume through `marker`; returns bytes before it."""
        while True:
            i = bytes(self._buf).find(marker)
            if i >= 0:
                out = bytes(self._buf[:i])
                del self._buf[: i + len(marker)]
                return out
            if self._eof:
                raise S3Error("MalformedPOSTRequest", 400,
                              "truncated multipart body")
            if len(self._buf) > limit:
                raise S3Error("MalformedPOSTRequest", 400,
                              "form field too large")
            await self._fill(len(self._buf) + 65536)

    async def start(self) -> None:
        # first boundary has no leading CRLF
        await self._read_until(self.delim[2:])

    async def next_part(self) -> Optional[tuple[str, dict]]:
        """-> (field_name, part headers) or None after the final
        boundary. Call read_field() or the file reader afterwards."""
        await self._fill(2)
        if bytes(self._buf[:2]) == b"--":
            return None  # closing delimiter
        head = await self._read_until(b"\r\n\r\n", limit=16 << 10)
        headers: dict[str, str] = {}
        for line in head.split(b"\r\n"):
            name, _, val = line.partition(b":")
            if val:
                headers[name.decode().strip().lower()] = val.decode().strip()
        disp = headers.get("content-disposition", "")
        fname = None
        field = None
        for item in disp.split(";"):
            item = item.strip()
            if item.startswith("name="):
                field = item[5:].strip('"')
            elif item.startswith("filename="):
                fname = item[9:].strip('"')
        if field is None:
            raise S3Error("MalformedPOSTRequest", 400,
                          "part without a field name")
        headers["_filename"] = fname or ""
        return field, headers

    async def read_field(self, limit: int = 1 << 20) -> str:
        raw = await self._read_until(self.delim, limit=limit)
        return raw.decode("utf-8", "replace")

    def file_reader(self) -> "_FileReader":
        return _FileReader(self)


class _FileReader:
    """Body-reader over the file part: yields content up to the next
    boundary delimiter."""

    def __init__(self, form: _FormReader):
        self.form = form
        self.done = False

    async def read(self, n: int = 65536) -> bytes:
        if self.done:
            return b""
        form = self.form
        # keep enough lookahead that a delimiter split across chunk
        # borders is always detected
        await form._fill(n + len(form.delim) + 4)
        buf = bytes(form._buf)
        i = buf.find(form.delim)
        if i >= 0:
            out = buf[:i]
            del form._buf[: i + len(form.delim)]
            self.done = True
            return out
        keep = len(form.delim) - 1 if not form._eof else 0
        if len(buf) <= keep:
            if form._eof:
                raise S3Error("MalformedPOSTRequest", 400,
                              "file part not terminated")
            return await self.read(n)
        out = buf[: len(buf) - keep]
        del form._buf[: len(buf) - keep]
        return out


def _check_policy(policy_raw: bytes,
                  fields: dict[str, str]) -> tuple[int, int]:
    """Validate the decoded policy against the submitted fields; returns
    the (min, max) content-length-range
    (ref: post_object.rs:133-220 + Policy::into_conditions)."""
    try:
        policy = json.loads(policy_raw.decode())
        expiration = policy["expiration"]
        raw_conditions = policy["conditions"]
    except (ValueError, KeyError, UnicodeDecodeError):
        raise S3Error("InvalidPolicyDocument", 400, "invalid policy")
    try:
        exp = datetime.datetime.fromisoformat(
            expiration.replace("Z", "+00:00"))
    except ValueError:
        raise S3Error("InvalidPolicyDocument", 400,
                      "invalid expiration date")
    if datetime.datetime.now(datetime.timezone.utc) > exp:
        raise S3Error("AccessDenied", 403, "policy has expired")

    conditions: dict[str, list[tuple[str, str]]] = {}
    length = [0, 1 << 62]
    for cond in raw_conditions:
        if isinstance(cond, dict):
            if len(cond) != 1:
                raise S3Error("InvalidPolicyDocument", 400,
                              "invalid policy item")
            (k, v), = cond.items()
            conditions.setdefault(k.lower(), []).append(("eq", str(v)))
        elif isinstance(cond, list) and len(cond) == 3:
            op, k, v = cond
            if op == "content-length-range":
                try:
                    length[0] = max(length[0], int(k))
                    length[1] = min(length[1], int(v))
                except (TypeError, ValueError):
                    raise S3Error("InvalidPolicyDocument", 400,
                                  "content-length-range bounds must be "
                                  "integers")
                continue
            if not isinstance(k, str) or not k.startswith("$") \
                    or op not in ("eq", "starts-with"):
                raise S3Error("InvalidPolicyDocument", 400,
                              "invalid policy item")
            conditions.setdefault(k[1:].lower(), []).append((op, str(v)))
        else:
            raise S3Error("InvalidPolicyDocument", 400,
                          "invalid policy item")

    for name, value in fields.items():
        lname = name.lower()
        if lname in _IGNORED_FIELDS:
            continue
        ops = conditions.pop(lname, None)
        if ops is None:
            if lname.startswith("x-ignore-"):
                continue
            raise S3Error("AccessDenied", 403,
                          f"field {name!r} is not allowed by the policy")
        for op, v in ops:
            if op == "eq" and value != v:
                raise S3Error("AccessDenied", 403,
                              f"field {name!r} does not match the policy")
            if op == "starts-with" and not value.startswith(v):
                raise S3Error("AccessDenied", 403,
                              f"field {name!r} does not match the policy")
    if conditions:
        missing = next(iter(conditions))
        raise S3Error("AccessDenied", 403,
                      f"field {missing!r} is required by the policy")
    return length[0], length[1]


class _LimitReader:
    def __init__(self, inner, max_len: int, prebuffered: bytes = b""):
        self.inner = inner
        self.max_len = max_len
        self.count = 0
        self._pre = prebuffered

    async def read(self, n: int = 65536) -> bytes:
        if self._pre:
            chunk, self._pre = self._pre[:n], self._pre[n:]
        else:
            chunk = await self.inner.read(n)
        self.count += len(chunk)
        if self.count > self.max_len:
            raise S3Error("EntityTooLarge", 400,
                          "file larger than content-length-range maximum")
        return chunk


# pre-buffering bound for the min-size check; content-length-range
# minimums beyond this are rejected up front rather than buffered
_MIN_PREBUFFER_CAP = 64 << 20


async def handle_post_object(server, req: Request,
                             bucket_name: str) -> Response:
    ctype = req.header("content-type") or ""
    if not ctype.startswith("multipart/form-data"):
        raise S3Error("MalformedPOSTRequest", 400,
                      "expected multipart/form-data")
    boundary = None
    for item in ctype.split(";")[1:]:
        item = item.strip()
        if item.startswith("boundary="):
            boundary = item[9:].strip('"')
    if not boundary:
        raise S3Error("MalformedPOSTRequest", 400, "no multipart boundary")

    form = _FormReader(req.body, boundary)
    await form.start()
    fields: dict[str, str] = {}
    file_headers = None
    while True:
        part = await form.next_part()
        if part is None:
            raise S3Error("MalformedPOSTRequest", 400,
                          "request did not contain a file")
        field, headers = part
        if field == "file":
            file_headers = headers
            break
        if len(fields) > 64:
            raise S3Error("MalformedPOSTRequest", 400, "too many fields")
        fields[field] = await form.read_field()

    key_tmpl = fields.get("key")
    policy_b64 = fields.get("policy")
    credential = fields.get("x-amz-credential")
    signature = fields.get("x-amz-signature")
    if not key_tmpl or not policy_b64 or not credential or not signature:
        raise S3Error("MalformedPOSTRequest", 400,
                      "key, policy, x-amz-credential and x-amz-signature "
                      "fields are required")
    key = key_tmpl.replace("${filename}",
                           file_headers.get("_filename", ""))

    # signature over the raw base64 policy (SigV4 POST policy scheme)
    parts = credential.split("/")
    if len(parts) != 5 or parts[4] != "aws4_request" \
            or parts[2] != server.region or parts[3] != "s3":
        raise access_denied("malformed credential")
    key_id, scope_date = parts[0], parts[1]
    secret = await server.helper.key_secret(key_id)
    if secret is None:
        raise access_denied("no such key")
    sk = signing_key(secret, scope_date, server.region, "s3")
    expect = hmac.new(sk, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, signature):
        raise access_denied("policy signature mismatch")
    try:
        policy_raw = base64.b64decode(policy_b64)
    except Exception:
        raise S3Error("InvalidPolicyDocument", 400, "bad policy base64")

    api_key = await server.helper.get_existing_key(key_id)
    bucket_id = await server.helper.resolve_global_bucket_name(bucket_name)
    if bucket_id is None:
        from .xml import no_such_bucket

        raise no_such_bucket(bucket_name)
    if not api_key.allow_write(bucket_id):
        raise access_denied()

    fields_with_key = dict(fields)
    fields_with_key["key"] = key_tmpl
    # the bucket the policy is checked against is ALWAYS the request
    # URL's bucket — a client-supplied "bucket" form field must never
    # satisfy the condition for a different target bucket
    fields_with_key["bucket"] = bucket_name
    min_len, max_len = _check_policy(policy_raw, fields_with_key)

    meta = {}
    if fields.get("content-type"):
        meta["content-type"] = fields["content-type"]
    for name, v in fields.items():
        if name.lower().startswith("x-amz-meta-"):
            meta[name.lower()] = v

    # size bounds are enforced WITHOUT mutating state on violation:
    # the minimum by pre-buffering min_len bytes before anything is
    # persisted, the maximum during streaming (save_stream's
    # interrupted-cleanup tombstones the partial version)
    file_body = form.file_reader()
    pre = b""
    if min_len > 0:
        if min_len > _MIN_PREBUFFER_CAP:
            raise S3Error("InvalidPolicyDocument", 400,
                          "content-length-range minimum too large")
        chunks = []
        got = 0
        while got < min_len:
            chunk = await file_body.read(min(65536, min_len - got))
            if not chunk:
                raise S3Error("EntityTooSmall", 400,
                              "file smaller than content-length-range "
                              "minimum")
            chunks.append(chunk)
            got += len(chunk)
        pre = b"".join(chunks)
    uuid, ts, etag, total = await save_stream(
        server.garage, bucket_id, key, meta,
        _LimitReader(file_body, max_len, prebuffered=pre))

    status_field = fields.get("success_action_status", "204")
    redirect = fields.get("success_action_redirect")
    if redirect:
        sep = "&" if "?" in redirect else "?"
        loc = (f"{redirect}{sep}bucket={bucket_name}&key={key}"
               f"&etag=%22{etag}%22")
        return Response(303, [("location", loc), ("etag", f'"{etag}"')])
    if status_field == "200":
        return Response(200, [("etag", f'"{etag}"')])
    if status_field == "201":
        from .xml import xml, xml_response

        return xml_response(
            xml("PostResponse",
                xml("Bucket", bucket_name),
                xml("Key", key),
                xml("ETag", f'"{etag}"')), status=201)
    return Response(204, [("etag", f'"{etag}"')])
