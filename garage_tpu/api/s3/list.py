"""Listing endpoints: ListBuckets, ListObjects v1/v2, uploads, parts.

Ref parity: src/api/s3/list.rs (the pagination state machine) — here
the range reads page through the object table per partition key with
prefix / delimiter / common-prefix folding and continuation tokens.
"""

from __future__ import annotations

import base64
import logging
from typing import Optional

from ..http import Request, Response
from .get import http_date
from .xml import S3Error, xml, xml_response

log = logging.getLogger("garage_tpu.api.s3.list")

PAGE = 1000
# rows fetched right after a delimiter skip-seek: in prefix-heavy
# layouts the very next row folds into a new common prefix, so a full
# PAGE fetch per distinct prefix would re-create the O(keys) cost the
# skip-scan removed. A small probe keeps per-prefix cost ~constant; a
# probe that comes back fold-free falls back to full pages.
DELIM_PROBE = 16


def _enc_token(s: str) -> str:
    return base64.urlsafe_b64encode(s.encode()).decode()


def _dec_token(s: str) -> str:
    try:
        return base64.urlsafe_b64decode(s.encode()).decode()
    except Exception:
        raise S3Error("InvalidArgument", 400, "bad continuation token")


def _encoder(q):
    """encoding-type=url -> (percent-encoder, True); SDKs (boto3 et
    al.) request it by default so keys with arbitrary bytes survive
    XML (ref: list.rs uriencode_maybe). Unknown values are a 400."""
    enc = q.get("encoding-type")
    if enc in (None, ""):
        return (lambda s: s), False
    if enc != "url":
        raise S3Error("InvalidArgument", 400, "bad encoding-type")
    from urllib.parse import quote

    return (lambda s: quote(s, safe="/")), True


def _page_size(q, name: str, lo: int = 1) -> int:
    """Validated page-size query param, clamped to <=1000. Values < lo
    are a 400: a 0-size page with IsTruncated=true and a non-advancing
    marker would loop paginating clients forever."""
    raw = q.get(name)
    if raw in (None, ""):
        return 1000
    try:
        v = int(raw)
    except ValueError:
        raise S3Error("InvalidArgument", 400, f"bad {name}")
    if v < lo:
        raise S3Error("InvalidArgument", 400, f"{name} must be >= {lo}")
    return min(v, 1000)


async def handle_list_buckets(helper, api_key) -> Response:
    """ref: api/s3/bucket.rs handle_list_buckets — buckets this key may
    read, with their global aliases."""
    aliases = await helper.list_buckets(limit=10000)
    entries = []
    for a in aliases:
        if a.bucket_id is None:
            continue
        if not (api_key.allow_read(a.bucket_id)
                or api_key.allow_owner(a.bucket_id)):
            continue
        try:
            b = await helper.get_existing_bucket(a.bucket_id)
        except Exception as e:
            # alias row pointing at a deleted/ghost bucket: skip it,
            # but not silently (Aspirator/GL05)
            log.debug("ListBuckets: alias %s -> %s unresolvable: %s",
                      a.name, a.bucket_id.hex()[:8], e)
            continue
        created = b.params.creation_date if b.params else 0
        entries.append(xml("Bucket",
                           xml("Name", a.name),
                           xml("CreationDate", _iso(created))))
    return xml_response(
        xml("ListAllMyBucketsResult",
            xml("Owner", xml("ID", api_key.key_id),
                xml("DisplayName", api_key.params.name.value
                    if api_key.params else "")),
            xml("Buckets", *entries)))


def _iso(ts_msec: int) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts_msec / 1000, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")


def _marker_is_folded_prefix(marker: str, prefix: str,
                             delimiter: str) -> bool:
    """True when a NextMarker/NextKeyMarker names a folded common
    prefix. A folded prefix is always `prefix + <nonempty> + delimiter`
    — a marker that merely ends with the delimiter (e.g. equal to the
    request prefix, or outside its window) must resume key-by-key, or
    the ("p",...) cursor would seek past the entire prefix window and
    return an empty page."""
    return (bool(delimiter) and marker.endswith(delimiter)
            and marker.startswith(prefix) and len(marker) > len(prefix))


def _prefix_upper_bound(b: bytes):
    bb = bytearray(b)
    while bb:
        if bb[-1] != 0xFF:
            bb[-1] += 1
            return bytes(bb)
        bb.pop()
    return None


async def _collect_objects(ctx, prefix: str, resume, delimiter: str,
                           max_keys: int):
    """Shared lister. `resume` is None or ("k", last_key) /
    ("p", last_common_prefix) — the last item the previous page
    returned. Folds keys under `delimiter` into common prefixes.
    Returns (contents, common_prefixes, next_token, truncated).

    Delimiter skip-scan (ISSUE 7): the moment a key folds into a common
    prefix, the cursor jumps straight to the prefix's upper bound
    instead of consuming every key under it — one engine seek per
    DISTINCT prefix, so a page over a bucket with a million keys under
    `photos/` costs O(distinct prefixes) range reads, not O(keys)."""
    garage = ctx.garage
    contents = []  # (key, ObjectVersion) rows
    prefixes: set[str] = set()
    last_token = None  # last RETURNED item, for the continuation token

    probe = False
    if resume is None:
        sk = prefix.encode() if prefix else None
    elif resume[0] == "p":
        # skip everything under the already-returned common prefix
        sk = _prefix_upper_bound(resume[1].encode())
        if sk is None:
            return contents, [], None, False
        probe = True  # next row most likely folds again
    else:
        sk = resume[1].encode() + b"\x00"
    while True:
        lim = DELIM_PROBE if probe else PAGE
        entries = await garage.object_table.get_range(
            ctx.bucket_id, start_sk=sk, flt={"type": "data"}, limit=lim,
        )
        if not entries:
            return contents, sorted(prefixes), None, False
        reseek = False
        for o in entries:
            key = o.key
            sk = key.encode() + b"\x00"
            if not key.startswith(prefix):
                if key > prefix:  # past the prefix window: done
                    return contents, sorted(prefixes), None, False
                continue
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp not in prefixes:
                        if len(contents) + len(prefixes) >= max_keys:
                            return (contents, sorted(prefixes),
                                    last_token, True)
                        prefixes.add(cp)
                        last_token = ("p", cp)
                    # skip-scan: every remaining key under cp folds into
                    # the prefix just recorded — seek past all of them
                    sk = _prefix_upper_bound(cp.encode())
                    if sk is None:
                        return contents, sorted(prefixes), None, False
                    reseek = True
                    break
            v = o.last_data()
            if v is None:
                continue
            if len(contents) + len(prefixes) >= max_keys:
                return contents, sorted(prefixes), last_token, True
            contents.append((key, v))
            last_token = ("k", key)
        if reseek:
            probe = True
            continue
        if len(entries) < lim:
            return contents, sorted(prefixes), None, False
        probe = False  # a fold-free page: back to full pages


async def handle_list_objects_v2(ctx, req: Request) -> Response:
    q = req.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = _page_size(q, "max-keys", lo=0)
    token = q.get("continuation-token")
    start_after = q.get("start-after", "")
    if token:
        raw = _dec_token(token)
        resume = (raw[:1], raw[1:]) if raw[:1] in ("k", "p") else None
    elif start_after:
        resume = ("k", start_after)
    else:
        resume = None
    if max_keys == 0:  # AWS: empty page, never truncated
        contents, prefixes, next_token, truncated = [], [], None, False
    else:
        contents, prefixes, next_token, truncated = await _collect_objects(
            ctx, prefix, resume, delimiter, max_keys)

    enc, encoded = _encoder(q)
    nodes = [xml("Name", ctx.bucket_name), xml("Prefix", enc(prefix)),
             xml("KeyCount", str(len(contents) + len(prefixes))),
             xml("MaxKeys", str(max_keys)),
             xml("IsTruncated", "true" if truncated else "false")]
    if encoded:
        nodes.append(xml("EncodingType", "url"))
    if delimiter:
        nodes.append(xml("Delimiter", enc(delimiter)))
    if truncated and next_token is not None:
        nodes.append(xml("NextContinuationToken",
                         _enc_token(next_token[0] + next_token[1])))
    for key, v in contents:
        nodes.append(xml("Contents",
                         xml("Key", enc(key)),
                         xml("LastModified", _iso(v.timestamp)),
                         xml("ETag", f'"{v.state.data.meta.etag}"'),
                         xml("Size", str(v.state.data.meta.size)),
                         xml("StorageClass", "STANDARD")))
    for cp in prefixes:
        nodes.append(xml("CommonPrefixes", xml("Prefix", enc(cp))))
    return xml_response(xml("ListBucketResult", *nodes))


async def handle_list_objects_v1(ctx, req: Request) -> Response:
    q = req.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = _page_size(q, "max-keys", lo=0)
    marker = q.get("marker", "")
    if marker and _marker_is_folded_prefix(marker, prefix, delimiter):
        resume = ("p", marker)  # marker was a folded common prefix
    elif marker:
        resume = ("k", marker)
    else:
        resume = None
    if max_keys == 0:  # AWS: empty page, never truncated
        contents, prefixes, next_token, truncated = [], [], None, False
    else:
        contents, prefixes, next_token, truncated = await _collect_objects(
            ctx, prefix, resume, delimiter, max_keys)
    enc, encoded = _encoder(q)
    nodes = [xml("Name", ctx.bucket_name), xml("Prefix", enc(prefix)),
             xml("Marker", enc(marker)), xml("MaxKeys", str(max_keys)),
             xml("IsTruncated", "true" if truncated else "false")]
    if encoded:
        nodes.append(xml("EncodingType", "url"))
    if delimiter:
        nodes.append(xml("Delimiter", enc(delimiter)))
    if truncated and next_token:
        nodes.append(xml("NextMarker", enc(next_token[1])))
    for key, v in contents:
        nodes.append(xml("Contents",
                         xml("Key", enc(key)),
                         xml("LastModified", _iso(v.timestamp)),
                         xml("ETag", f'"{v.state.data.meta.etag}"'),
                         xml("Size", str(v.state.data.meta.size)),
                         xml("StorageClass", "STANDARD")))
    for cp in prefixes:
        nodes.append(xml("CommonPrefixes", xml("Prefix", enc(cp))))
    return xml_response(xml("ListBucketResult", *nodes))


async def _collect_uploads(ctx, prefix: str, resume, delimiter: str,
                           max_uploads: int):
    """Upload lister with full marker pagination (ref: list.rs:628-650
    ListMultipartUploadsQuery::begin + UploadAccumulator).

    `resume` is None or a cursor:
      ("k", key)        — start strictly after `key`
      ("p", cprefix)    — start past every key under `cprefix`
      ("i", key)        — start AT `key`, all of its uploads
      ("u", key, uuid)  — start AT `key`, uploads with id > `uuid`
    An object may hold several concurrent uploads (one uploading
    version each); same-key uploads are returned in lexicographic
    upload-id order, so ("u", ...) resumes mid-key losslessly.
    Returns (uploads, common_prefixes, next_cursor, truncated) where
    uploads is [(key, version)] and next_cursor follows the same
    cursor grammar (its key becomes NextKeyMarker; a ("u",...) or
    ("i",...) cursor additionally yields NextUploadIdMarker).

    Folded prefixes skip-scan exactly like _collect_objects: one
    engine seek past the whole prefix instead of consuming each key."""
    garage = ctx.garage
    ups = []
    prefixes: set[str] = set()
    last_cursor = resume  # scan position after the last consumed item

    after_uuid = None
    marker_key = None
    if resume is None:
        sk = prefix.encode() if prefix else None
    elif resume[0] == "k":
        sk = resume[1].encode() + b"\x00"
    elif resume[0] == "p":
        sk = _prefix_upper_bound(resume[1].encode())
        if sk is None:
            return ups, [], None, False
    else:  # "i" / "u": re-read the marker key itself
        sk = resume[1].encode()
        if resume[0] == "u":
            marker_key = resume[1]
            try:
                after_uuid = bytes.fromhex(resume[2])
            except ValueError:
                raise S3Error("InvalidArgument", 400, "bad upload-id-marker")

    def full() -> bool:
        return len(ups) + len(prefixes) >= max_uploads

    probe = resume is not None and resume[0] == "p"
    while True:
        lim = DELIM_PROBE if probe else PAGE
        entries = await garage.object_table.get_range(
            ctx.bucket_id, start_sk=sk,
            flt={"type": "uploading", "multipart": True}, limit=lim,
            prefix_sk=prefix.encode() if prefix else None,
        )
        if not entries:
            return ups, sorted(prefixes), None, False
        reseek = False
        for o in entries:
            key = o.key
            sk = key.encode() + b"\x00"
            if not key.startswith(prefix):
                if key > prefix:  # past the prefix window: done
                    return ups, sorted(prefixes), None, False
                continue
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp not in prefixes:
                        if full():
                            return ups, sorted(prefixes), last_cursor, True
                        prefixes.add(cp)
                    # skip-scan past every key under the folded prefix;
                    # the cursor records the prefix itself so a page
                    # that fills right here resumes past all of it
                    last_cursor = ("p", cp)
                    sk = _prefix_upper_bound(cp.encode())
                    if sk is None:
                        return ups, sorted(prefixes), None, False
                    reseek = True
                    break
            vs = sorted((v for v in o.versions if v.is_uploading(True)),
                        key=lambda v: v.uuid)
            if after_uuid is not None and key == marker_key:
                vs = [v for v in vs if v.uuid > after_uuid]
            placed_any = False
            for v in vs:
                if full():
                    return ups, sorted(prefixes), last_cursor, True
                ups.append((key, v))
                last_cursor = ("u", key, v.uuid.hex())
                placed_any = True
            if not placed_any:
                last_cursor = ("k", key)
        if reseek:
            probe = True
            continue
        if len(entries) < lim:
            return ups, sorted(prefixes), None, False
        probe = False  # a fold-free page: back to full pages


async def handle_list_object_versions(ctx, req: Request) -> Response:
    """GET ?versions. Buckets are unversioned (like the reference,
    whose router parses this endpoint but never implements it —
    router.rs:964 with no handler): every live object is exactly one
    Version with VersionId "null" and IsLatest true, the AWS contract
    for unversioned buckets, so version-aware clients (rclone, backup
    tools) work against this store. Pagination mirrors ListObjects
    (key-marker; version-id-marker is trivially satisfied at one
    version per key)."""
    q = req.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = _page_size(q, "max-keys", lo=0)
    key_marker = q.get("key-marker")
    if key_marker and _marker_is_folded_prefix(key_marker, prefix,
                                               delimiter):
        # the previous page ended on a folded common prefix (same
        # convention as v1/uploads): resume past the whole prefix,
        # or page 2 re-emits the same CommonPrefixes entry
        resume = ("p", key_marker)
    elif key_marker:
        resume = ("k", key_marker)
    else:
        resume = None
    if max_keys == 0:
        contents, prefixes, next_token, truncated = [], [], None, False
    else:
        contents, prefixes, next_token, truncated = await _collect_objects(
            ctx, prefix, resume, delimiter, max_keys)
    enc, encoded = _encoder(q)
    nodes = [xml("Name", ctx.bucket_name), xml("Prefix", enc(prefix)),
             xml("MaxKeys", str(max_keys)),
             xml("IsTruncated", "true" if truncated else "false")]
    if encoded:
        nodes.append(xml("EncodingType", "url"))
    if key_marker:
        nodes.append(xml("KeyMarker", enc(key_marker)))
    if delimiter:
        nodes.append(xml("Delimiter", enc(delimiter)))
    if truncated and next_token is not None:
        nodes.append(xml("NextKeyMarker", enc(next_token[1])))
        nodes.append(xml("NextVersionIdMarker", "null"))
    for key, v in contents:
        nodes.append(xml("Version",
                         xml("Key", enc(key)),
                         xml("VersionId", "null"),
                         xml("IsLatest", "true"),
                         xml("LastModified", _iso(v.timestamp)),
                         xml("ETag", f'"{v.state.data.meta.etag}"'),
                         xml("Size", str(v.state.data.meta.size)),
                         xml("StorageClass", "STANDARD")))
    for cp in prefixes:
        nodes.append(xml("CommonPrefixes", xml("Prefix", enc(cp))))
    return xml_response(xml("ListVersionsResult", *nodes))


async def handle_list_multipart_uploads(ctx, req: Request) -> Response:
    """ref: list.rs:169-265 handle_list_multipart_upload. Markers:
    key-marker alone starts after that key; with upload-id-marker it
    starts at that key after that upload id; the reference's "include"
    sentinel (an impossible hex id) means "at the key, first upload"
    and is emitted when a page fills right at a key boundary."""
    q = req.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_uploads = _page_size(q, "max-uploads")
    key_marker = q.get("key-marker")
    upload_id_marker = q.get("upload-id-marker")
    if key_marker is not None and upload_id_marker:
        if upload_id_marker == "include":
            resume = ("i", key_marker)
        else:
            resume = ("u", key_marker, upload_id_marker)
    elif key_marker is not None:
        if _marker_is_folded_prefix(key_marker, prefix, delimiter):
            # the previous page ended on a folded common prefix (same
            # convention as ListObjects v1): resume past the whole
            # prefix, not key-by-key under it
            resume = ("p", key_marker)
        else:
            resume = ("k", key_marker)
    else:
        resume = None
    ups, prefixes, next_cursor, truncated = await _collect_uploads(
        ctx, prefix, resume, delimiter, max_uploads)

    enc, encoded = _encoder(q)
    nodes = [xml("Bucket", ctx.bucket_name), xml("Prefix", enc(prefix)),
             xml("MaxUploads", str(max_uploads)),
             xml("IsTruncated", "true" if truncated else "false")]
    if encoded:
        nodes.append(xml("EncodingType", "url"))
    if delimiter:
        nodes.append(xml("Delimiter", enc(delimiter)))
    if key_marker is not None:
        nodes.append(xml("KeyMarker", enc(key_marker)))
    if upload_id_marker:
        nodes.append(xml("UploadIdMarker", upload_id_marker))
    if truncated and next_cursor is not None:
        nodes.append(xml("NextKeyMarker", enc(next_cursor[1])))
        if next_cursor[0] == "u":
            nodes.append(xml("NextUploadIdMarker", next_cursor[2]))
        elif next_cursor[0] == "i":
            nodes.append(xml("NextUploadIdMarker", "include"))
    for key, v in ups:
        nodes.append(xml("Upload",
                         xml("Key", enc(key)),
                         xml("UploadId", v.uuid.hex()),
                         xml("Initiated", _iso(v.timestamp))))
    for cp in prefixes:
        nodes.append(xml("CommonPrefixes", xml("Prefix", enc(cp))))
    return xml_response(xml("ListMultipartUploadsResult", *nodes))


async def handle_list_parts(ctx, req: Request) -> Response:
    """ref: list.rs:274-311 handle_list_parts + fetch_part_info
    (list.rs:512-558): newest record per part number, cut below the
    marker, NextPartNumberMarker when the page fills."""
    upload_id = req.query.get("uploadId", "")
    from .multipart import _get_upload

    # 404s aborted/completed uploads too, not just unknown ids
    mpu, _ov = await _get_upload(ctx, upload_id)
    marker = int(req.query.get("part-number-marker", "0") or 0)
    max_parts = _page_size(req.query, "max-parts")
    # newest record per part number with a finished etag
    best = {}
    for (pn, ts), part in mpu.parts.items():
        if part.etag is not None and pn > marker:
            if pn not in best or ts > best[pn][0]:
                best[pn] = (ts, part)
    all_parts = sorted(best.items())
    truncated = len(all_parts) > max_parts
    parts = all_parts[:max_parts]
    nodes = [xml("Bucket", ctx.bucket_name), xml("Key", ctx.key),
             xml("UploadId", upload_id),
             xml("PartNumberMarker", str(marker)),
             xml("MaxParts", str(max_parts)),
             xml("IsTruncated", "true" if truncated else "false")]
    if truncated:
        nodes.append(xml("NextPartNumberMarker", str(parts[-1][0])))
    for pn, (_ts, part) in parts:
        nodes.append(xml("Part",
                         xml("PartNumber", str(pn)),
                         xml("ETag", f'"{part.etag}"'),
                         xml("Size", str(part.size or 0))))
    return xml_response(xml("ListPartsResult", *nodes))
