"""Listing endpoints: ListBuckets, ListObjects v1/v2, uploads, parts.

Ref parity: src/api/s3/list.rs (the pagination state machine) — here
the range reads page through the object table per partition key with
prefix / delimiter / common-prefix folding and continuation tokens.
"""

from __future__ import annotations

import base64
from typing import Optional

from ..http import Request, Response
from .get import http_date
from .xml import S3Error, xml, xml_response

PAGE = 1000


def _enc_token(s: str) -> str:
    return base64.urlsafe_b64encode(s.encode()).decode()


def _dec_token(s: str) -> str:
    try:
        return base64.urlsafe_b64decode(s.encode()).decode()
    except Exception:
        raise S3Error("InvalidArgument", 400, "bad continuation token")


async def handle_list_buckets(helper, api_key) -> Response:
    """ref: api/s3/bucket.rs handle_list_buckets — buckets this key may
    read, with their global aliases."""
    aliases = await helper.list_buckets(limit=10000)
    entries = []
    for a in aliases:
        if a.bucket_id is None:
            continue
        if not (api_key.allow_read(a.bucket_id)
                or api_key.allow_owner(a.bucket_id)):
            continue
        try:
            b = await helper.get_existing_bucket(a.bucket_id)
        except Exception:
            continue
        created = b.params.creation_date if b.params else 0
        entries.append(xml("Bucket",
                           xml("Name", a.name),
                           xml("CreationDate", _iso(created))))
    return xml_response(
        xml("ListAllMyBucketsResult",
            xml("Owner", xml("ID", api_key.key_id),
                xml("DisplayName", api_key.params.name.value
                    if api_key.params else "")),
            xml("Buckets", *entries)))


def _iso(ts_msec: int) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts_msec / 1000, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.000Z")


def _prefix_upper_bound(b: bytes):
    bb = bytearray(b)
    while bb:
        if bb[-1] != 0xFF:
            bb[-1] += 1
            return bytes(bb)
        bb.pop()
    return None


async def _collect_objects(ctx, prefix: str, resume, delimiter: str,
                           max_keys: int):
    """Shared lister. `resume` is None or ("k", last_key) /
    ("p", last_common_prefix) — the last item the previous page
    returned. Folds keys under `delimiter` into common prefixes.
    Returns (contents, common_prefixes, next_token, truncated)."""
    garage = ctx.garage
    contents = []  # (key, ObjectVersion) rows
    prefixes: set[str] = set()
    last_token = None  # last RETURNED item, for the continuation token

    if resume is None:
        sk = prefix.encode() if prefix else None
    elif resume[0] == "p":
        # skip everything under the already-returned common prefix
        sk = _prefix_upper_bound(resume[1].encode())
        if sk is None:
            return contents, [], None, False
    else:
        sk = resume[1].encode() + b"\x00"
    while True:
        entries = await garage.object_table.get_range(
            ctx.bucket_id, start_sk=sk, flt={"type": "data"}, limit=PAGE,
        )
        if not entries:
            return contents, sorted(prefixes), None, False
        for o in entries:
            key = o.key
            sk = key.encode() + b"\x00"
            if not key.startswith(prefix):
                if key > prefix:  # past the prefix window: done
                    return contents, sorted(prefixes), None, False
                continue
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp in prefixes:
                        continue
                    if len(contents) + len(prefixes) >= max_keys:
                        return contents, sorted(prefixes), last_token, True
                    prefixes.add(cp)
                    last_token = ("p", cp)
                    continue
            v = o.last_data()
            if v is None:
                continue
            if len(contents) + len(prefixes) >= max_keys:
                return contents, sorted(prefixes), last_token, True
            contents.append((key, v))
            last_token = ("k", key)
        if len(entries) < PAGE:
            return contents, sorted(prefixes), None, False


async def handle_list_objects_v2(ctx, req: Request) -> Response:
    q = req.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = min(int(q.get("max-keys", "1000") or 1000), 1000)
    token = q.get("continuation-token")
    start_after = q.get("start-after", "")
    if token:
        raw = _dec_token(token)
        resume = (raw[:1], raw[1:]) if raw[:1] in ("k", "p") else None
    elif start_after:
        resume = ("k", start_after)
    else:
        resume = None
    contents, prefixes, next_token, truncated = await _collect_objects(
        ctx, prefix, resume, delimiter, max_keys)

    nodes = [xml("Name", ctx.bucket_name), xml("Prefix", prefix),
             xml("KeyCount", str(len(contents) + len(prefixes))),
             xml("MaxKeys", str(max_keys)),
             xml("IsTruncated", "true" if truncated else "false")]
    if delimiter:
        nodes.append(xml("Delimiter", delimiter))
    if truncated and next_token is not None:
        nodes.append(xml("NextContinuationToken",
                         _enc_token(next_token[0] + next_token[1])))
    for key, v in contents:
        nodes.append(xml("Contents",
                         xml("Key", key),
                         xml("LastModified", _iso(v.timestamp)),
                         xml("ETag", f'"{v.state.data.meta.etag}"'),
                         xml("Size", str(v.state.data.meta.size)),
                         xml("StorageClass", "STANDARD")))
    for cp in prefixes:
        nodes.append(xml("CommonPrefixes", xml("Prefix", cp)))
    return xml_response(xml("ListBucketResult", *nodes))


async def handle_list_objects_v1(ctx, req: Request) -> Response:
    q = req.query
    prefix = q.get("prefix", "")
    delimiter = q.get("delimiter", "")
    max_keys = min(int(q.get("max-keys", "1000") or 1000), 1000)
    marker = q.get("marker", "")
    if marker and delimiter and marker.endswith(delimiter):
        resume = ("p", marker)  # marker was a folded common prefix
    elif marker:
        resume = ("k", marker)
    else:
        resume = None
    contents, prefixes, next_token, truncated = await _collect_objects(
        ctx, prefix, resume, delimiter, max_keys)
    nodes = [xml("Name", ctx.bucket_name), xml("Prefix", prefix),
             xml("Marker", marker), xml("MaxKeys", str(max_keys)),
             xml("IsTruncated", "true" if truncated else "false")]
    if delimiter:
        nodes.append(xml("Delimiter", delimiter))
    if truncated and next_token:
        nodes.append(xml("NextMarker", next_token[1]))
    for key, v in contents:
        nodes.append(xml("Contents",
                         xml("Key", key),
                         xml("LastModified", _iso(v.timestamp)),
                         xml("ETag", f'"{v.state.data.meta.etag}"'),
                         xml("Size", str(v.state.data.meta.size)),
                         xml("StorageClass", "STANDARD")))
    for cp in prefixes:
        nodes.append(xml("CommonPrefixes", xml("Prefix", cp)))
    return xml_response(xml("ListBucketResult", *nodes))


async def handle_list_multipart_uploads(ctx, req: Request) -> Response:
    """ref: list.rs handle_list_multipart_upload (simplified paging)."""
    q = req.query
    prefix = q.get("prefix", "")
    max_uploads = min(int(q.get("max-uploads", "1000") or 1000), 1000)
    entries = await ctx.garage.object_table.get_range(
        ctx.bucket_id, flt={"type": "uploading", "multipart": True},
        limit=PAGE,
    )
    ups = []
    for o in entries:
        if not o.key.startswith(prefix):
            continue
        for v in o.versions:
            if v.is_uploading(True):
                ups.append((o.key, v))
    ups = ups[:max_uploads]
    nodes = [xml("Bucket", ctx.bucket_name), xml("Prefix", prefix),
             xml("MaxUploads", str(max_uploads)),
             xml("IsTruncated", "false")]
    for key, v in ups:
        nodes.append(xml("Upload",
                         xml("Key", key),
                         xml("UploadId", v.uuid.hex()),
                         xml("Initiated", _iso(v.timestamp))))
    return xml_response(xml("ListMultipartUploadsResult", *nodes))


async def handle_list_parts(ctx, req: Request) -> Response:
    """ref: list.rs handle_list_parts."""
    upload_id = req.query.get("uploadId", "")
    from .multipart import _get_upload

    # 404s aborted/completed uploads too, not just unknown ids
    mpu, _ov = await _get_upload(ctx, upload_id)
    marker = int(req.query.get("part-number-marker", "0") or 0)
    max_parts = min(int(req.query.get("max-parts", "1000") or 1000), 1000)
    # newest record per part number with a finished etag
    best = {}
    for (pn, ts), part in mpu.parts.items():
        if part.etag is not None and pn > marker:
            if pn not in best or ts > best[pn][0]:
                best[pn] = (ts, part)
    parts = sorted(best.items())[:max_parts]
    nodes = [xml("Bucket", ctx.bucket_name), xml("Key", ctx.key),
             xml("UploadId", upload_id),
             xml("MaxParts", str(max_parts)),
             xml("IsTruncated", "false")]
    for pn, (_ts, part) in parts:
        nodes.append(xml("Part",
                         xml("PartNumber", str(pn)),
                         xml("ETag", f'"{part.etag}"'),
                         xml("Size", str(part.size or 0))))
    return xml_response(xml("ListPartsResult", *nodes))
