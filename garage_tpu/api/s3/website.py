"""Bucket website + CORS configuration endpoints, and CORS evaluation.

Ref parity: src/api/s3/website.rs (Get/Put/DeleteBucketWebsite) and
src/api/s3/cors.rs (Get/Put/DeleteBucketCors + rule matching applied by
the web server and to cross-origin API requests). Configs live as Lww
registers in the bucket params (model/bucket_table.py plain-structure
payloads).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Optional

from ...model.helper import GarageHelper
from ..http import Request, Response
from .xml import S3Error, xml, xml_response

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _strip_ns(tag: str) -> str:
    return tag.split("}", 1)[1] if tag.startswith("{") else tag


# ---------------------------------------------------------------------------
# Website config CRUD (ref: website.rs)
# ---------------------------------------------------------------------------


async def handle_get_bucket_website(ctx) -> Response:
    cfg = ctx.bucket.params.website_config.value
    if cfg is None:
        raise S3Error("NoSuchWebsiteConfiguration", 404,
                      "The specified bucket does not have a website "
                      "configuration")
    children = [xml("IndexDocument", xml("Suffix", cfg["index_document"]))]
    if cfg.get("error_document"):
        children.append(xml("ErrorDocument", xml("Key",
                                                 cfg["error_document"])))
    return xml_response(xml(
        "WebsiteConfiguration", *children,
        xmlns="http://s3.amazonaws.com/doc/2006-03-01/"))


async def handle_put_bucket_website(ctx, req: Request) -> Response:
    body = await req.body.read_all(limit=1 << 20)
    try:
        root = ET.fromstring(body.decode())
    except (ET.ParseError, UnicodeDecodeError):
        raise S3Error("MalformedXML", 400, "cannot parse request body")
    if _strip_ns(root.tag) != "WebsiteConfiguration":
        raise S3Error("MalformedXML", 400, "expected WebsiteConfiguration")
    index = root.find(f"{_NS}IndexDocument/{_NS}Suffix")
    if index is None:
        index = root.find("IndexDocument/Suffix")
    # ref: website.rs — redirect_all_requests_to is rejected as
    # unimplemented; an index document is required
    if root.find(f"{_NS}RedirectAllRequestsTo") is not None \
            or root.find("RedirectAllRequestsTo") is not None:
        raise S3Error("NotImplemented", 501,
                      "RedirectAllRequestsTo is not implemented")
    if index is None or not (index.text or "").strip():
        raise S3Error("InvalidArgument", 400,
                      "IndexDocument.Suffix is required")
    err = root.find(f"{_NS}ErrorDocument/{_NS}Key")
    if err is None:
        err = root.find("ErrorDocument/Key")
    cfg = {"index_document": index.text.strip(),
           "error_document": (err.text.strip() if err is not None
                              and err.text else None)}
    await GarageHelper(ctx.garage).update_bucket_config(
        ctx.bucket_id, "website_config", cfg)
    return Response(200)


async def handle_delete_bucket_website(ctx) -> Response:
    await GarageHelper(ctx.garage).update_bucket_config(
        ctx.bucket_id, "website_config", None)
    return Response(204)


# ---------------------------------------------------------------------------
# CORS config CRUD (ref: cors.rs)
# ---------------------------------------------------------------------------


async def handle_get_bucket_cors(ctx) -> Response:
    rules = ctx.bucket.params.cors_config.value
    if not rules:
        raise S3Error("NoSuchCORSConfiguration", 404,
                      "The CORS configuration does not exist")
    out = []
    for r in rules:
        children = []
        if r.get("id"):
            children.append(xml("ID", r["id"]))
        for o in r.get("allow_origins", []):
            children.append(xml("AllowedOrigin", o))
        for m in r.get("allow_methods", []):
            children.append(xml("AllowedMethod", m))
        for h in r.get("allow_headers", []):
            children.append(xml("AllowedHeader", h))
        for h in r.get("expose_headers", []):
            children.append(xml("ExposeHeader", h))
        if r.get("max_age_seconds") is not None:
            children.append(xml("MaxAgeSeconds", str(r["max_age_seconds"])))
        out.append(xml("CORSRule", *children))
    return xml_response(xml(
        "CORSConfiguration", *out,
        xmlns="http://s3.amazonaws.com/doc/2006-03-01/"))


async def handle_put_bucket_cors(ctx, req: Request) -> Response:
    body = await req.body.read_all(limit=1 << 20)
    try:
        root = ET.fromstring(body.decode())
    except (ET.ParseError, UnicodeDecodeError):
        raise S3Error("MalformedXML", 400, "cannot parse request body")
    rules = []
    for rule in root:
        if _strip_ns(rule.tag) != "CORSRule":
            continue
        r = {"id": None, "max_age_seconds": None, "allow_origins": [],
             "allow_methods": [], "allow_headers": [], "expose_headers": []}
        for el in rule:
            tag, text = _strip_ns(el.tag), (el.text or "").strip()
            if tag == "ID":
                r["id"] = text
            elif tag == "AllowedOrigin":
                r["allow_origins"].append(text)
            elif tag == "AllowedMethod":
                r["allow_methods"].append(text)
            elif tag == "AllowedHeader":
                r["allow_headers"].append(text.lower())
            elif tag == "ExposeHeader":
                r["expose_headers"].append(text)
            elif tag == "MaxAgeSeconds":
                try:
                    r["max_age_seconds"] = int(text)
                except ValueError:
                    raise S3Error("MalformedXML", 400, "bad MaxAgeSeconds")
        if not r["allow_origins"] or not r["allow_methods"]:
            raise S3Error("MalformedXML", 400,
                          "CORSRule needs AllowedOrigin and AllowedMethod")
        rules.append(r)
    if not rules:
        raise S3Error("MalformedXML", 400, "no CORSRule in configuration")
    await GarageHelper(ctx.garage).update_bucket_config(
        ctx.bucket_id, "cors_config", rules)
    return Response(200)


async def handle_delete_bucket_cors(ctx) -> Response:
    await GarageHelper(ctx.garage).update_bucket_config(
        ctx.bucket_id, "cors_config", None)
    return Response(204)


# ---------------------------------------------------------------------------
# CORS rule evaluation (ref: cors.rs find_matching_cors_rule,
# add_cors_headers, handle_options_for_bucket)
# ---------------------------------------------------------------------------


def _origin_matches(patterns: list[str], origin: str) -> bool:
    for p in patterns:
        if p == "*" or p == origin:
            return True
        if "*" in p:
            pre, _, suf = p.partition("*")
            if origin.startswith(pre) and origin.endswith(suf) \
                    and len(origin) >= len(pre) + len(suf):
                return True
    return False


def find_matching_cors_rule(bucket_params, origin: str, method: str,
                            request_headers: list[str]) -> Optional[dict]:
    rules = bucket_params.cors_config.value or []
    for r in rules:
        if not _origin_matches(r.get("allow_origins", []), origin):
            continue
        methods = r.get("allow_methods", [])
        if "*" not in methods and method not in methods:
            continue
        allowed = r.get("allow_headers", [])
        if "*" not in allowed:
            if any(h.lower() not in allowed for h in request_headers):
                continue
        return r
    return None


def cors_headers(rule: dict, origin: str) -> list[tuple[str, str]]:
    out = [("access-control-allow-origin",
            "*" if "*" in rule.get("allow_origins", []) else origin),
           ("access-control-allow-methods",
            ", ".join(rule.get("allow_methods", []) or ["*"]))]
    if rule.get("allow_headers"):
        out.append(("access-control-allow-headers",
                    ", ".join(rule["allow_headers"])))
    if rule.get("expose_headers"):
        out.append(("access-control-expose-headers",
                    ", ".join(rule["expose_headers"])))
    if rule.get("max_age_seconds") is not None:
        out.append(("access-control-max-age",
                    str(rule["max_age_seconds"])))
    if "*" not in rule.get("allow_origins", []):
        out.append(("vary", "Origin"))
    return out


def handle_options_for_bucket(req: Request, bucket_params) -> Response:
    """CORS preflight against a bucket (ref: cors.rs
    handle_options_for_bucket)."""
    origin = req.header("origin")
    if origin is None:
        raise S3Error("BadRequest", 400, "Missing Origin header")
    method = req.header("access-control-request-method")
    if method is None:
        raise S3Error("BadRequest", 400,
                      "Missing Access-Control-Request-Method header")
    req_headers = [h.strip() for h in
                   (req.header("access-control-request-headers") or ""
                    ).split(",") if h.strip()]
    rule = find_matching_cors_rule(bucket_params, origin, method,
                                   req_headers)
    if rule is None:
        raise S3Error("AccessDenied", 403, "This CORS request is not allowed")
    return Response(200, cors_headers(rule, origin))


def apply_cors_to_response(req: Request, bucket_params,
                           resp: Response) -> Response:
    """Attach CORS headers to an actual (non-preflight) response when a
    rule matches (ref: cors.rs add_cors_headers call sites)."""
    origin = req.header("origin")
    if origin is None or bucket_params is None:
        return resp
    rule = find_matching_cors_rule(bucket_params, origin, req.method, [])
    if rule is not None:
        have = {n.lower() for n, _ in resp.headers}
        for n, v in cors_headers(rule, origin):
            if n not in have:
                resp.headers.append((n, v))
    return resp
