"""SSE-C: server-side encryption with customer-provided keys.

Ref parity: src/api/s3/encryption.rs:48-596. The client supplies an
AES-256 key per request (x-amz-server-side-encryption-customer-*); the
server encrypts each block with AES-256-GCM before it enters the block
store and forgets the key. Reads require the same key headers. Design
differences from the reference, chosen for the block-batched data
plane: each 1 MiB block is one AES-GCM message with a random 96-bit
nonce prepended (the reference uses an AES-GCM STREAM of 4 KiB
segments); the content-address hash covers the CIPHERTEXT, so scrub
and repair verify integrity without ever holding customer keys — same
property as the reference (blake2 over encrypted blocks,
encryption.rs:576-596). Compression is skipped for encrypted objects
(ciphertext doesn't compress; timing/size side channels).

Object metadata records only the algorithm marker and the key's MD5 so
GETs can verify the presented key without storing it.
"""

from __future__ import annotations

import base64
import hashlib
import os
from typing import Optional

from ..http import Request
from .xml import S3Error

ALGO_HEADER = "x-amz-server-side-encryption-customer-algorithm"
KEY_HEADER = "x-amz-server-side-encryption-customer-key"
KEY_MD5_HEADER = "x-amz-server-side-encryption-customer-key-md5"
COPY_ALGO_HEADER = ("x-amz-copy-source-server-side-encryption"
                    "-customer-algorithm")
COPY_KEY_HEADER = ("x-amz-copy-source-server-side-encryption"
                   "-customer-key")
COPY_KEY_MD5_HEADER = ("x-amz-copy-source-server-side-encryption"
                       "-customer-key-md5")

# stored in object meta headers (never the key itself)
META_SSEC_ALGO = "x-garage-ssec-algorithm"
META_SSEC_MD5 = "x-garage-ssec-key-md5"

NONCE_LEN = 12
TAG_LEN = 16
OVERHEAD = NONCE_LEN + TAG_LEN


def _aesgcm():
    """AESGCM or a clean S3 error when the wheel is absent (bare image:
    everything but SSE-C keeps working)."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ModuleNotFoundError:
        raise S3Error("NotImplemented", 501,
                      "SSE-C requires the `cryptography` wheel, which "
                      "is not installed on this node")
    return AESGCM


class SseCKey:
    __slots__ = ("key", "md5_b64")

    def __init__(self, key: bytes, md5_b64: str):
        self.key = key
        self.md5_b64 = md5_b64

    def encrypt_block(self, plain: bytes) -> bytes:
        AESGCM = _aesgcm()

        nonce = os.urandom(NONCE_LEN)
        return nonce + AESGCM(self.key).encrypt(nonce, plain, b"")

    def decrypt_block(self, cipher: bytes) -> bytes:
        AESGCM = _aesgcm()

        if len(cipher) < OVERHEAD:
            raise S3Error("InvalidRequest", 400, "corrupt encrypted block")
        try:
            return AESGCM(self.key).decrypt(cipher[:NONCE_LEN],
                                            cipher[NONCE_LEN:], b"")
        except Exception:
            raise S3Error("AccessDenied", 403,
                          "wrong encryption key for this object")


def _parse(algo: Optional[str], key_b64: Optional[str],
           md5_b64: Optional[str], what: str) -> Optional[SseCKey]:
    if algo is None and key_b64 is None and md5_b64 is None:
        return None
    if algo != "AES256":
        raise S3Error("InvalidRequest", 400,
                      f"{what}: algorithm must be AES256")
    if not key_b64:
        raise S3Error("InvalidRequest", 400, f"{what}: key is required")
    try:
        key = base64.b64decode(key_b64)
    except Exception:
        raise S3Error("InvalidRequest", 400, f"{what}: bad key base64")
    if len(key) != 32:
        raise S3Error("InvalidRequest", 400,
                      f"{what}: key must be 256 bits")
    digest = base64.b64encode(hashlib.md5(key).digest()).decode()
    if md5_b64 is not None and md5_b64 != digest:
        raise S3Error("InvalidRequest", 400, f"{what}: key MD5 mismatch")
    return SseCKey(key, digest)


def request_sse_key(req: Request) -> Optional[SseCKey]:
    """The x-amz-server-side-encryption-customer-* triple, or None."""
    return _parse(req.header(ALGO_HEADER), req.header(KEY_HEADER),
                  req.header(KEY_MD5_HEADER), "SSE-C")


def copy_source_sse_key(req: Request) -> Optional[SseCKey]:
    return _parse(req.header(COPY_ALGO_HEADER),
                  req.header(COPY_KEY_HEADER),
                  req.header(COPY_KEY_MD5_HEADER), "copy-source SSE-C")


def meta_is_encrypted(meta) -> bool:
    return META_SSEC_ALGO in meta.headers


def check_key_for_meta(meta, key: Optional[SseCKey]) -> Optional[SseCKey]:
    """Validate the presented key against the object's stored key-MD5.
    Returns the key to decrypt with (None for plaintext objects)."""
    if not meta_is_encrypted(meta):
        if key is not None:
            raise S3Error("InvalidRequest", 400,
                          "object is not SSE-C encrypted")
        return None
    if key is None:
        raise S3Error("InvalidRequest", 400,
                      "object is SSE-C encrypted: key headers required")
    if meta.headers.get(META_SSEC_MD5) != key.md5_b64:
        raise S3Error("AccessDenied", 403,
                      "wrong encryption key for this object")
    return key


def sse_response_headers(meta) -> list[tuple[str, str]]:
    if not meta_is_encrypted(meta):
        return []
    return [(ALGO_HEADER, "AES256"),
            (KEY_MD5_HEADER, meta.headers.get(META_SSEC_MD5, ""))]
