"""Bucket lifecycle configuration endpoints.

Ref parity: src/api/s3/lifecycle.rs — Get/Put/DeleteBucketLifecycle.
Rules are stored as the plain-structure payload documented in
model/bucket_table.py and executed by the daily lifecycle worker
(model/s3/lifecycle_worker.py). Supported actions: Expiration (days or
absolute date) and AbortIncompleteMultipartUpload; filters: Prefix and
object size bounds.
"""

from __future__ import annotations

import datetime
import xml.etree.ElementTree as ET

from ...model.helper import GarageHelper
from ..http import Request, Response
from .xml import S3Error, xml, xml_response


def _strip_ns(tag: str) -> str:
    return tag.split("}", 1)[1] if tag.startswith("{") else tag


def _find(el, name):
    for child in el:
        if _strip_ns(child.tag) == name:
            return child
    return None


def _int(el, what: str) -> int:
    """Parse an integer element; malformed input is the client's fault
    (MalformedXML 400), never a 500."""
    try:
        return int((el.text or "").strip())
    except (TypeError, ValueError):
        raise S3Error("MalformedXML", 400, f"bad integer in {what}")


async def handle_get_bucket_lifecycle(ctx) -> Response:
    rules = ctx.bucket.params.lifecycle_config.value
    if not rules:
        raise S3Error("NoSuchLifecycleConfiguration", 404,
                      "The lifecycle configuration does not exist")
    out = []
    for r in rules:
        children = []
        if r.get("id"):
            children.append(xml("ID", r["id"]))
        children.append(xml("Status",
                            "Enabled" if r.get("enabled", True)
                            else "Disabled"))
        f = r.get("filter") or {}
        fchildren = []
        if f.get("prefix"):
            fchildren.append(xml("Prefix", f["prefix"]))
        if f.get("size_gt") is not None:
            fchildren.append(xml("ObjectSizeGreaterThan", str(f["size_gt"])))
        if f.get("size_lt") is not None:
            fchildren.append(xml("ObjectSizeLessThan", str(f["size_lt"])))
        children.append(xml("Filter", *fchildren))
        if r.get("abort_incomplete_mpu_days") is not None:
            children.append(xml(
                "AbortIncompleteMultipartUpload",
                xml("DaysAfterInitiation",
                    str(r["abort_incomplete_mpu_days"]))))
        exp = r.get("expiration")
        if exp is not None:
            if isinstance(exp, int):
                children.append(xml("Expiration", xml("Days", str(exp))))
            else:
                children.append(xml("Expiration", xml("Date", exp)))
        out.append(xml("Rule", *children))
    return xml_response(xml(
        "LifecycleConfiguration", *out,
        xmlns="http://s3.amazonaws.com/doc/2006-03-01/"))


async def handle_put_bucket_lifecycle(ctx, req: Request) -> Response:
    body = await req.body.read_all(limit=1 << 20)
    try:
        root = ET.fromstring(body.decode())
    except (ET.ParseError, UnicodeDecodeError):
        raise S3Error("MalformedXML", 400, "cannot parse request body")
    rules = []
    for rule in root:
        if _strip_ns(rule.tag) != "Rule":
            continue
        r = {"id": None, "enabled": True, "filter": {},
             "abort_incomplete_mpu_days": None, "expiration": None}
        idel = _find(rule, "ID")
        if idel is not None:
            r["id"] = (idel.text or "").strip()
        st = _find(rule, "Status")
        if st is None or (st.text or "").strip() not in ("Enabled",
                                                         "Disabled"):
            raise S3Error("MalformedXML", 400,
                          "Rule.Status must be Enabled or Disabled")
        r["enabled"] = st.text.strip() == "Enabled"
        flt = _find(rule, "Filter")
        if flt is not None:
            inner = _find(flt, "And") or flt
            p = _find(inner, "Prefix")
            if p is not None and p.text:
                r["filter"]["prefix"] = p.text
            gt = _find(inner, "ObjectSizeGreaterThan")
            if gt is not None:
                r["filter"]["size_gt"] = _int(gt, "ObjectSizeGreaterThan")
            lt = _find(inner, "ObjectSizeLessThan")
            if lt is not None:
                r["filter"]["size_lt"] = _int(lt, "ObjectSizeLessThan")
        # legacy top-level Prefix
        p = _find(rule, "Prefix")
        if p is not None and p.text:
            r["filter"]["prefix"] = p.text
        ab = _find(rule, "AbortIncompleteMultipartUpload")
        if ab is not None:
            days = _find(ab, "DaysAfterInitiation")
            if days is None:
                raise S3Error("MalformedXML", 400,
                              "DaysAfterInitiation is required")
            r["abort_incomplete_mpu_days"] = _int(days,
                                                  "DaysAfterInitiation")
        exp = _find(rule, "Expiration")
        if exp is not None:
            days = _find(exp, "Days")
            date = _find(exp, "Date")
            if days is not None:
                r["expiration"] = _int(days, "Expiration.Days")
                if r["expiration"] <= 0:
                    raise S3Error("MalformedXML", 400,
                                  "Expiration.Days must be positive")
            elif date is not None:
                txt = (date.text or "").strip()
                try:
                    datetime.date.fromisoformat(txt[:10])
                except ValueError:
                    raise S3Error("MalformedXML", 400,
                                  "bad Expiration.Date")
                r["expiration"] = txt[:10]
            else:
                raise S3Error("MalformedXML", 400,
                              "Expiration needs Days or Date")
        rules.append(r)
    if not rules:
        # an empty configuration must not act as a silent delete
        raise S3Error("MalformedXML", 400, "no Rule in configuration")
    await GarageHelper(ctx.garage).update_bucket_config(
        ctx.bucket_id, "lifecycle_config", rules)
    return Response(200)


async def handle_delete_bucket_lifecycle(ctx) -> Response:
    await GarageHelper(ctx.garage).update_bucket_config(
        ctx.bucket_id, "lifecycle_config", None)
    return Response(204)
