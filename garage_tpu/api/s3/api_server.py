"""S3 API server: routing, auth, and dispatch.

Ref parity: src/api/s3/api_server.rs + router.rs:20-1109 (routing is by
method + path + query markers). Bucket addressing is path-style
(`/bucket/key...`) or vhost-style (`bucket.root_domain`).
"""

from __future__ import annotations

import logging
from typing import Optional

from ...model.helper import GarageHelper
from ...utils.error import (BadRequest, NoSuchBucket, NoSuchKey,
                            QuorumError)
from ..http import HttpError, HttpServer, Request, Response
from ...qos.limiter import CURRENT_QOS_KEY, SlowDown
from ..signature import claimed_key_id, verify_request, wrap_body
from . import bucket as bucket_handlers
from . import delete as delete_handlers
from . import get as get_handlers
from . import list as list_handlers
from . import lifecycle as lifecycle_handlers
from . import multipart as multipart_handlers
from . import put as put_handlers
from . import website as website_handlers
from .xml import S3Error, access_denied, no_such_bucket, slow_down

log = logging.getLogger("garage_tpu.api.s3")


def declared_body_length(req: Request):
    """Body size a request admits to up front — the qos bytes-bucket
    charge. aws-chunked bodies declare the true payload size separately
    (raw content-length includes per-chunk framing); bodies with
    neither header are charged nothing here and shaped per-block on the
    streaming path instead (put.py Chunker)."""
    cl = (req.header("x-amz-decoded-content-length")
          or req.header("content-length"))
    return int(cl) if cl and cl.isdigit() else None


class ReqCtx:
    """Per-request context handed to handlers (ref: api_server.rs
    ReqCtx)."""

    __slots__ = ("garage", "bucket_id", "bucket_name", "bucket", "key",
                 "api_key", "verified")

    def __init__(self, garage, bucket_id, bucket_name, bucket, key,
                 api_key, verified):
        self.garage = garage
        self.bucket_id = bucket_id
        self.bucket_name = bucket_name
        self.bucket = bucket
        self.key = key  # object key (str) or None
        self.api_key = api_key
        self.verified = verified


class S3ApiServer:
    def __init__(self, garage, region: Optional[str] = None,
                 root_domain: Optional[str] = None):
        self.garage = garage
        self.helper = GarageHelper(garage)
        self.region = region or garage.config.s3_region
        self.root_domain = root_domain or garage.config.root_domain
        self.http = HttpServer(self.handle, name="s3")

    async def start(self, host: str, port=None,
                    reuse_port: bool = False) -> None:
        # a path (port None) binds a Unix-domain socket, like the
        # reference's UnixOrTCPSocketAddress bind addresses; reuse_port
        # is the gateway workers' SO_REUSEPORT shared accept loop
        if port is None:
            await self.http.start_unix(host)
        else:
            await self.http.start(host, port, reuse_port=reuse_port)

    async def stop(self) -> None:
        await self.http.stop()

    # ---- request entry -------------------------------------------------

    def _split_bucket_key(self, req: Request) -> tuple[Optional[str], Optional[str]]:
        host = (req.header("host") or "").split(":")[0]
        path = req.path.lstrip("/")
        if host.endswith(self.root_domain) and host != self.root_domain.lstrip("."):
            bucket = host[: -len(self.root_domain)]
            return bucket, (path or None)
        if not path:
            return None, None
        bucket, _, key = path.partition("/")
        return bucket, (key or None)

    async def handle(self, req: Request) -> Response:
        # one conn task serves many keep-alive requests: the fairness
        # key must never leak from one request into the next. Seeded
        # with the CLAIMED key id (no crypto) so the global request-
        # rate DRR can queue fairly BEFORE SigV4 runs; replaced by the
        # verified id once auth resolves.
        qos_key_token = CURRENT_QOS_KEY.set(claimed_key_id(req))
        try:
            # global admission (qos/): requests/s + declared body bytes
            # + bounded concurrency, BEFORE SigV4 — shedding must stay
            # cheap or overload melts the node doing auth for requests
            # it then rejects. Per-key/per-bucket stages run in _handle
            # once identity is resolved.
            qos = getattr(self.garage, "qos", None)
            if qos is None:
                return await self._handle(req)
            async with qos.admit("s3", nbytes=declared_body_length(req)):
                return await self._handle(req)
        except SlowDown as e:
            return slow_down(e.header_value()).response()
        except S3Error as e:
            return e.response()
        except HttpError as e:
            return S3Error("InvalidRequest", e.status, e.reason).response()
        except NoSuchBucket as e:
            return no_such_bucket(str(e)).response()
        except NoSuchKey as e:
            return S3Error("NoSuchKey", 404, str(e)).response()
        except BadRequest as e:
            return S3Error("InvalidRequest", 400, str(e)).response()
        except QuorumError as e:
            # not enough replicas answered (node overload, a partition,
            # or a gateway worker whose store is slow): a retryable 503,
            # not an "internal error" — SDKs back off and retry 503s
            return S3Error(
                "ServiceUnavailable", 503,
                f"quorum not reached: {e}").response()
        finally:
            CURRENT_QOS_KEY.reset(qos_key_token)

    async def _handle(self, req: Request) -> Response:
        verified = await verify_request(req, self.region,
                                        self.helper.key_secret)
        req.body = wrap_body(req, verified, self.region,
                             feeder=self.garage.block_manager.feeder)
        bucket_name, key = self._split_bucket_key(req)

        api_key = None
        if verified is not None:
            api_key = await self.helper.get_existing_key(verified.key_id)

        # per-key / per-bucket admission, now that identity is known
        # (raises qos SlowDown, translated to 503 by handle())
        qos = getattr(self.garage, "qos", None)
        if qos is not None:
            await qos.admit_scoped(
                key_id=api_key.key_id if api_key is not None else None,
                bucket=bucket_name)
        if api_key is not None:
            # fairness identity for every downstream byte charge (block
            # reads, chunk shaping); reset by handle() per request
            CURRENT_QOS_KEY.set(api_key.key_id)

        if bucket_name is None:
            if req.method == "GET":
                if api_key is None:
                    raise access_denied("authentication required")
                return await list_handlers.handle_list_buckets(
                    self.helper, api_key)
            raise S3Error("InvalidRequest", 400, "no bucket specified")

        # browser form upload: authentication lives in the signed POST
        # policy inside the form, not in headers (ref: post_object.rs)
        if req.method == "POST" and key is None and (
                req.header("content-type") or ""
        ).startswith("multipart/form-data"):
            from . import post_object as post_object_handlers

            return await post_object_handlers.handle_post_object(
                self, req, bucket_name)

        # CreateBucket resolves no existing bucket
        if req.method == "PUT" and key is None and not req.query:
            if api_key is None:
                raise access_denied("authentication required")
            return await bucket_handlers.handle_create_bucket(
                self.helper, bucket_name, api_key, self.region, req)

        bucket_id = await self.helper.resolve_global_bucket_name(bucket_name)
        if bucket_id is None:
            raise no_such_bucket(bucket_name)
        bucket = await self.helper.get_existing_bucket(bucket_id)

        # CORS preflight is unauthenticated by definition
        # (ref: api_server.rs handle_options_api)
        if req.method == "OPTIONS":
            return website_handlers.handle_options_for_bucket(
                req, bucket.params)

        try:
            # authorization (ref: api_server.rs:96-171)
            if api_key is not None:
                allowed = (api_key.allow_read(bucket_id)
                           if req.method in ("GET", "HEAD")
                           else api_key.allow_write(bucket_id))
                if req.method == "DELETE" and key is None:
                    allowed = api_key.allow_owner(bucket_id)
                # bucket config CRUD is owner-only (ref: api_server.rs
                # Endpoint::authorization_type Owner for website/cors/
                # lifecycle endpoints)
                if key is None and any(x in req.query for x in
                                       ("website", "cors", "lifecycle")):
                    allowed = api_key.allow_owner(bucket_id)
            else:
                allowed = False  # no anonymous access (web server differs)
            if not allowed:
                raise access_denied()

            ctx = ReqCtx(self.garage, bucket_id, bucket_name, bucket, key,
                         api_key, verified)
            resp = await self._route(req, ctx)
        except S3Error as e:
            # errors carry CORS headers too, or browsers turn a plain
            # 404 into an opaque network error (ref: cors.rs
            # add_cors_headers on the error path)
            resp = e.response()
        return website_handlers.apply_cors_to_response(req, bucket.params,
                                                       resp)

    # ---- router (ref: router.rs:20-1109) -------------------------------

    # subresources the reference's router recognizes but neither it nor
    # this build implements: answer 501 NotImplemented like the
    # reference (api_server.rs:66,332) instead of silently falling
    # through to GetObject/ListObjects with the wrong response shape
    _UNIMPLEMENTED_SUBRESOURCES = frozenset((
        "tagging", "acl", "policy", "policyStatus", "replication",
        "encryption", "notification", "accelerate", "requestPayment",
        "logging", "ownershipControls", "publicAccessBlock",
        "intelligent-tiering", "inventory", "metrics", "analytics",
        "object-lock", "legal-hold", "retention", "torrent", "restore",
        "select", "attributes",
    ))

    async def _route(self, req: Request, ctx: ReqCtx) -> Response:
        m, q = req.method, req.query
        for sub in self._UNIMPLEMENTED_SUBRESOURCES:
            if sub in q:
                raise S3Error("NotImplemented", 501, sub)
        if ctx.key is None:
            # bucket-level ops
            if m in ("GET", "HEAD"):
                if "uploads" in q:
                    return await list_handlers.handle_list_multipart_uploads(
                        ctx, req)
                if "location" in q:
                    return bucket_handlers.handle_get_bucket_location(
                        self.region)
                if "versioning" in q:
                    return bucket_handlers.handle_get_bucket_versioning()
                if "versions" in q:
                    return await list_handlers.handle_list_object_versions(
                        ctx, req)
                if "website" in q:
                    return await website_handlers.handle_get_bucket_website(
                        ctx)
                if "cors" in q:
                    return await website_handlers.handle_get_bucket_cors(ctx)
                if "lifecycle" in q:
                    return await lifecycle_handlers.handle_get_bucket_lifecycle(
                        ctx)
                if m == "HEAD":
                    return Response(200)
                if q.get("list-type") == "2":
                    return await list_handlers.handle_list_objects_v2(ctx, req)
                return await list_handlers.handle_list_objects_v1(ctx, req)
            if m == "PUT":
                if "website" in q:
                    return await website_handlers.handle_put_bucket_website(
                        ctx, req)
                if "cors" in q:
                    return await website_handlers.handle_put_bucket_cors(
                        ctx, req)
                if "lifecycle" in q:
                    return await lifecycle_handlers.handle_put_bucket_lifecycle(
                        ctx, req)
            if m == "DELETE":
                if "website" in q:
                    return await website_handlers.handle_delete_bucket_website(
                        ctx)
                if "cors" in q:
                    return await website_handlers.handle_delete_bucket_cors(
                        ctx)
                if "lifecycle" in q:
                    return await \
                        lifecycle_handlers.handle_delete_bucket_lifecycle(ctx)
                return await bucket_handlers.handle_delete_bucket(
                    self.helper, ctx)
            if m == "POST" and "delete" in q:
                return await delete_handlers.handle_delete_objects(ctx, req)
            raise S3Error("NotImplemented", 501,
                          f"unsupported bucket operation {m} {sorted(q)}")
        # object-level ops
        if m == "GET" or m == "HEAD":
            if "uploadId" in q:
                return await list_handlers.handle_list_parts(ctx, req)
            return await get_handlers.handle_get(ctx, req, head=(m == "HEAD"))
        if m == "PUT":
            if "partNumber" in q and "uploadId" in q:
                if "x-amz-copy-source" in req.headers:
                    return await multipart_handlers.handle_upload_part_copy(
                        ctx, req)
                return await multipart_handlers.handle_put_part(ctx, req)
            if "x-amz-copy-source" in req.headers:
                return await put_handlers.handle_copy(ctx, req)
            return await put_handlers.handle_put(ctx, req)
        if m == "POST":
            if "uploads" in q:
                return await multipart_handlers.handle_create_multipart(
                    ctx, req)
            if "uploadId" in q:
                return await multipart_handlers.handle_complete_multipart(
                    ctx, req)
        if m == "DELETE":
            if "uploadId" in q:
                return await multipart_handlers.handle_abort_multipart(
                    ctx, req)
            return await delete_handlers.handle_delete_object(ctx, req)
        raise S3Error("NotImplemented", 501, f"unsupported operation {m}")
