"""Bucket-level endpoints.

Ref parity: src/api/s3/bucket.rs — CreateBucket (idempotent when the
key may write), DeleteBucket (owner only, must be empty), location,
versioning stub.
"""

from __future__ import annotations

from ...model.helper import allow_all
from ...utils.error import BadRequest
from ..http import Request, Response
from .xml import S3Error, xml, xml_response


async def handle_create_bucket(helper, bucket_name: str, api_key,
                               region: str, req: Request) -> Response:
    """ref: bucket.rs handle_create_bucket."""
    body = await req.body.read_all(limit=1 << 16)
    if body.strip():
        # CreateBucketConfiguration: a LocationConstraint naming any
        # other region is rejected (ref: bucket.rs:127-138)
        import xml.etree.ElementTree as ET

        try:
            root = ET.fromstring(body.decode())
        except (ET.ParseError, UnicodeDecodeError):
            raise S3Error("MalformedXML", 400,
                          "Invalid create bucket XML query")
        for c in root.iter():
            if c.tag.endswith("LocationConstraint") and c.text \
                    and c.text.strip() and c.text.strip() != region:
                raise S3Error(
                    "InvalidLocationConstraint", 400,
                    f"Cannot satisfy location constraint "
                    f"`{c.text.strip()}`: buckets can only be created "
                    f"in region `{region}`")
    existing = await helper.resolve_global_bucket_name(bucket_name)
    if existing is not None:
        if api_key.allow_write(existing) or api_key.allow_owner(existing):
            # you already own it: S3 says 200 in the default region
            return Response(200, [("location", f"/{bucket_name}")])
        raise S3Error("BucketAlreadyExists", 409,
                      "The requested bucket name is not available.")
    # ref: bucket.rs:166 — only keys granted create-bucket may claim
    # new global alias names.
    if api_key.params is None or not api_key.params.allow_create_bucket.value:
        raise S3Error("AccessDenied", 403,
                      "Your key does not allow creating buckets.")
    try:
        bucket = await helper.create_bucket(bucket_name)
    except BadRequest as e:
        raise S3Error("InvalidBucketName", 400, str(e))
    await helper.set_bucket_key_permissions(bucket.id, api_key.key_id,
                                            allow_all())
    return Response(200, [("location", f"/{bucket_name}")])


async def handle_delete_bucket(helper, ctx) -> Response:
    try:
        await helper.delete_bucket(ctx.bucket_id)
    except BadRequest as e:
        raise S3Error("BucketNotEmpty", 409, str(e))
    return Response(204)


def handle_get_bucket_location(region: str) -> Response:
    return xml_response(
        xml("LocationConstraint", region,
            xmlns="http://s3.amazonaws.com/doc/2006-03-01/"))


def handle_get_bucket_versioning() -> Response:
    # versioning is not supported (ref: bucket.rs:
    # handle_get_bucket_versioning returns unversioned)
    return xml_response(xml("VersioningConfiguration",
                            xmlns="http://s3.amazonaws.com/doc/2006-03-01/"))
