"""Multipart upload endpoints.

Ref parity: src/api/s3/multipart.rs:36-506. Create registers an
Uploading{multipart} object version + MPU row; each part gets its own
Version (keyed by a fresh uuid) whose blocks it streams; Complete
validates the client's part list against stored parts, splices all part
versions into one final Version (renumbered by part), and writes the
Complete object; Abort tombstones.
"""

from __future__ import annotations

import hashlib

from ...model.s3.block_ref_table import BlockRef
from ...model.s3.mpu_table import MpuPart, MultipartUpload, MultipartUploadTable
from ...model.s3.object_table import (Object, ObjectVersion,
                                      ObjectVersionData, ObjectVersionMeta,
                                      ObjectVersionState)
from ...model.s3.version_table import BACKLINK_MPU, BACKLINK_OBJECT, Version
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ..http import Request, Response
from .put import Chunker, extract_metadata_headers, read_and_put_blocks
from .xml import S3Error, xml, xml_response


async def _get_upload(ctx, upload_id_hex: str):
    """-> (mpu, object_version) or raises NoSuchUpload
    (ref: multipart.rs get_upload)."""
    try:
        uid = bytes.fromhex(upload_id_hex)
        if len(uid) != 32:
            raise ValueError
    except ValueError:
        raise S3Error("NoSuchUpload", 404, upload_id_hex)
    mpu = await ctx.garage.mpu_table.get(uid, b"")
    obj = await ctx.garage.object_table.get(ctx.bucket_id,
                                            ctx.key.encode())
    ov = obj.version(uid) if obj is not None else None
    if (mpu is None or mpu.is_tombstone() or ov is None
            or not ov.is_uploading(check_multipart=True)):
        raise S3Error("NoSuchUpload", 404, upload_id_hex)
    return mpu, ov


async def handle_create_multipart(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_create_multipart_upload."""
    await req.body.drain()
    headers = extract_metadata_headers(req)
    uuid = gen_uuid()
    ts = now_msec()
    obj = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
        uuid, ts, ObjectVersionState.uploading(headers, multipart=True))])
    await ctx.garage.object_table.insert(obj)
    mpu = MultipartUpload.new(uuid, ts, ctx.bucket_id, ctx.key)
    await ctx.garage.mpu_table.insert(mpu)
    return xml_response(xml("InitiateMultipartUploadResult",
                            xml("Bucket", ctx.bucket_name),
                            xml("Key", ctx.key),
                            xml("UploadId", uuid.hex())))


async def handle_put_part(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_put_part."""
    q = req.query
    try:
        part_number = int(q["partNumber"])
        if not (1 <= part_number <= 10000):
            raise ValueError
    except (KeyError, ValueError):
        raise S3Error("InvalidArgument", 400, "bad partNumber")
    mpu, _ov = await _get_upload(ctx, q.get("uploadId", ""))

    # validate headers BEFORE inserting any rows — a 400 here must not
    # leak an uploading version/part placeholder
    from ..checksum import Checksummer, request_checksum_value

    try:
        expected_checksum = request_checksum_value(req.headers)
    except ValueError as e:
        raise S3Error("InvalidRequest", 400, str(e))
    checksummer = (Checksummer(expected_checksum[0])
                   if expected_checksum is not None else None)

    ts = mpu.next_timestamp(part_number)
    version_uuid = gen_uuid()
    # register the part (etag/size unset until data is stored)
    mpu2 = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                               ctx.bucket_id, ctx.key)
    mpu2.parts = mpu2.parts.put((part_number, ts), MpuPart(version_uuid))
    await ctx.garage.mpu_table.insert(mpu2)
    version = Version.new(version_uuid, (BACKLINK_MPU, mpu.upload_id))
    await ctx.garage.version_table.insert(version)
    chunker = Chunker(req.body, ctx.garage.config.block_size)
    first = await chunker.next()
    if first is None:
        raise S3Error("EntityTooSmall", 400, "empty part")
    md5 = hashlib.md5()
    try:
        total, etag, _first_hash = await read_and_put_blocks(
            ctx.garage, version, part_number, first, chunker, md5,
            checksummer=checksummer)
        if checksummer is not None \
                and checksummer.b64() != expected_checksum[1]:
            raise S3Error("BadDigest", 400, "checksum mismatch")
    except BaseException:
        # interrupted part: tombstone its version so block refs get
        # dropped now instead of leaking until abort/complete
        # (ref: multipart.rs:165-258 InterruptedCleanup)
        try:
            await ctx.garage.version_table.insert(Version.new(
                version_uuid, (BACKLINK_MPU, mpu.upload_id), deleted=True))
        except Exception:
            pass
        raise

    # record the finished part
    done = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                               ctx.bucket_id, ctx.key)
    done.parts = done.parts.put((part_number, ts),
                                MpuPart(version_uuid, etag, total))
    await ctx.garage.mpu_table.insert(done)
    return Response(200, [("etag", f'"{etag}"')])


async def handle_complete_multipart(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_complete_multipart_upload."""
    import xml.etree.ElementTree as ET

    body = await req.body.read_all(limit=1 << 20)
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError:
        raise S3Error("MalformedXML", 400, "cannot parse request")
    asked = []  # [(part_number, etag)]
    for part in root:
        if not part.tag.endswith("Part"):
            continue
        pn = etag = None
        for c in part:
            if c.tag.endswith("PartNumber"):
                pn = int(c.text)
            elif c.tag.endswith("ETag"):
                etag = (c.text or "").strip().strip('"')
        if pn is not None:
            asked.append((pn, etag))
    if not asked or asked != sorted(asked, key=lambda x: x[0]) \
            or len({p for p, _ in asked}) != len(asked):
        raise S3Error("InvalidPartOrder", 400,
                      "parts must be ordered and unique")

    upload_id = req.query.get("uploadId", "")
    mpu, ov = await _get_upload(ctx, upload_id)

    # newest stored record per part number that has completed
    stored = {}
    for (pn, ts), part in mpu.parts.items():
        if part.etag is not None:
            if pn not in stored or ts > stored[pn][0]:
                stored[pn] = (ts, part)
    parts = []
    for pn, etag in asked:
        if pn not in stored or (etag and stored[pn][1].etag != etag):
            raise S3Error("InvalidPart", 400, f"part {pn} not found")
        parts.append((pn, stored[pn][1]))

    # splice all part versions into the final object version
    # (ref: multipart.rs:260-330)
    final = Version.new(ov.uuid, (BACKLINK_OBJECT, ctx.bucket_id, ctx.key))
    total_size = 0
    etag_md5 = hashlib.md5()
    for pn, part in parts:
        pv = await ctx.garage.version_table.get(part.version, b"")
        if pv is None or pv.is_tombstone():
            raise S3Error("InvalidPart", 400, f"part {pn} lost")
        for (_p, off), (h, sz) in pv.blocks.items():
            final = Version(final.uuid, final.deleted,
                            final.blocks.put((pn, off), (h, sz)),
                            final.backlink)
            total_size += sz
        etag_md5.update(bytes.fromhex(part.etag))
    await ctx.garage.version_table.insert(final)
    # re-point block refs from part versions to the final version
    for pn, part in parts:
        pv = await ctx.garage.version_table.get(part.version, b"")
        for _k, (h, _s) in pv.blocks.items():
            await ctx.garage.block_ref_table.insert(BlockRef.new(h, ov.uuid))

    etag = f"{etag_md5.hexdigest()}-{len(parts)}"
    headers = (ov.state.headers if ov.state.kind == "uploading" else {})
    meta = ObjectVersionMeta(headers, total_size, etag)
    first_hash = next(iter([h for _k, (h, _s) in final.blocks.items()]),
                      b"\x00" * 32)
    done = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
        ov.uuid, ov.timestamp,
        ObjectVersionState.complete(
            ObjectVersionData.first_block(meta, first_hash)))])
    await ctx.garage.object_table.insert(done)
    return xml_response(xml("CompleteMultipartUploadResult",
                            xml("Bucket", ctx.bucket_name),
                            xml("Key", ctx.key),
                            xml("ETag", f'"{etag}"')))


async def handle_abort_multipart(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_abort_multipart_upload."""
    upload_id = req.query.get("uploadId", "")
    mpu, ov = await _get_upload(ctx, upload_id)
    aborted = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
        ov.uuid, ov.timestamp, ObjectVersionState.aborted())])
    await ctx.garage.object_table.insert(aborted)
    return Response(204)
