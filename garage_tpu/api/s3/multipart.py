"""Multipart upload endpoints.

Ref parity: src/api/s3/multipart.rs:36-506. Create registers an
Uploading{multipart} object version + MPU row; each part gets its own
Version (keyed by a fresh uuid) whose blocks it streams; Complete
validates the client's part list against stored parts, splices all part
versions into one final Version (renumbered by part), and writes the
Complete object; Abort tombstones.
"""

from __future__ import annotations

import hashlib
import logging

from ...model.s3.block_ref_table import BlockRef
from ...model.s3.mpu_table import MpuPart, MultipartUpload, MultipartUploadTable
from ...model.s3.object_table import (Object, ObjectVersion,
                                      ObjectVersionData, ObjectVersionMeta,
                                      ObjectVersionState)
from ...model.s3.version_table import BACKLINK_MPU, BACKLINK_OBJECT, Version
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ..http import Request, Response
from .put import Chunker, extract_metadata_headers, read_and_put_blocks
from .xml import S3Error, xml, xml_response

log = logging.getLogger("garage_tpu.api.s3.multipart")


class _UploadMeta:
    """Adapter exposing an uploading version's headers dict with the
    `.headers` attribute check_key_for_meta expects."""

    __slots__ = ("headers",)

    def __init__(self, headers: dict):
        self.headers = headers


async def _get_upload(ctx, upload_id_hex: str):
    """-> (mpu, object_version) or raises NoSuchUpload
    (ref: multipart.rs get_upload)."""
    try:
        uid = bytes.fromhex(upload_id_hex)
        if len(uid) != 32:
            raise ValueError
    except ValueError:
        raise S3Error("NoSuchUpload", 404, upload_id_hex)
    mpu = await ctx.garage.mpu_table.get(uid, b"")
    obj = await ctx.garage.object_table.get(ctx.bucket_id,
                                            ctx.key.encode())
    ov = obj.version(uid) if obj is not None else None
    if (mpu is None or mpu.is_tombstone() or ov is None
            or not ov.is_uploading(check_multipart=True)):
        raise S3Error("NoSuchUpload", 404, upload_id_hex)
    return mpu, ov


async def handle_create_multipart(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_create_multipart_upload."""
    from .encryption import META_SSEC_ALGO, META_SSEC_MD5, request_sse_key

    await req.body.drain()
    headers = extract_metadata_headers(req)
    sse_key = request_sse_key(req)
    if sse_key is not None:
        headers = {**headers, META_SSEC_ALGO: "AES256",
                   META_SSEC_MD5: sse_key.md5_b64}
    uuid = gen_uuid()
    ts = now_msec()
    obj = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
        uuid, ts, ObjectVersionState.uploading(headers, multipart=True))])
    await ctx.garage.object_table.insert(obj)
    mpu = MultipartUpload.new(uuid, ts, ctx.bucket_id, ctx.key)
    await ctx.garage.mpu_table.insert(mpu)
    return xml_response(xml("InitiateMultipartUploadResult",
                            xml("Bucket", ctx.bucket_name),
                            xml("Key", ctx.key),
                            xml("UploadId", uuid.hex())))


async def handle_put_part(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_put_part."""
    q = req.query
    try:
        part_number = int(q["partNumber"])
        if not (1 <= part_number <= 10000):
            raise ValueError
    except (KeyError, ValueError):
        raise S3Error("InvalidArgument", 400, "bad partNumber")
    mpu, ov = await _get_upload(ctx, q.get("uploadId", ""))

    # validate headers BEFORE inserting any rows — a 400 here must not
    # leak an uploading version/part placeholder
    from ..checksum import Checksummer, request_checksum_value
    from .encryption import check_key_for_meta, request_sse_key

    try:
        expected_checksum = request_checksum_value(req.headers)
    except ValueError as e:
        raise S3Error("InvalidRequest", 400, str(e))
    checksummer = (Checksummer(expected_checksum[0])
                   if expected_checksum is not None else None)
    # SSE-C: the part's key must match the key declared at create time
    sse_key = check_key_for_meta(
        _UploadMeta(ov.state.headers or {}), request_sse_key(req))

    ts = mpu.next_timestamp(part_number)
    version_uuid = gen_uuid()
    # register the part (etag/size unset until data is stored)
    mpu2 = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                               ctx.bucket_id, ctx.key)
    mpu2.parts = mpu2.parts.put((part_number, ts), MpuPart(version_uuid))
    await ctx.garage.mpu_table.insert(mpu2)
    version = Version.new(version_uuid, (BACKLINK_MPU, mpu.upload_id))
    await ctx.garage.version_table.insert(version)
    # same zero-copy ingest pool as PutObject (put.save_stream): big
    # uploads arrive as parts, so UploadPart is the hotter wire path
    pool = None
    if sse_key is None:
        pool = ctx.garage.block_manager.ingest_pool(
            ctx.garage.config.block_size,
            getattr(ctx.garage.config, "s3_ingest_buffers", 0))
    chunker = Chunker(req.body, ctx.garage.config.block_size, pool=pool)
    first = await chunker.next()
    if first is None:
        raise S3Error("EntityTooSmall", 400, "empty part")
    from ... import native

    md5 = native.Md5()  # fuses with the content hash on the host route
    try:
        total, _md5_hex, etag, _first_hash = await read_and_put_blocks(
            ctx.garage, version, part_number, first, chunker, md5,
            checksummer=checksummer, sse_key=sse_key)
        if checksummer is not None \
                and checksummer.b64() != expected_checksum[1]:
            raise S3Error("BadDigest", 400, "checksum mismatch")
    except BaseException:
        if hasattr(first, "release"):
            first.release()  # idempotent: a handed-over lease already
            # went back via its put task's finally
        # interrupted part: tombstone its version so block refs get
        # dropped now instead of leaking until abort/complete
        # (ref: multipart.rs:165-258 InterruptedCleanup)
        try:
            await ctx.garage.version_table.insert(Version.new(
                version_uuid, (BACKLINK_MPU, mpu.upload_id), deleted=True))
        except Exception as e:
            log.warning("interrupted-part tombstone failed (block refs "
                        "leak until abort/complete): %s", e)
        raise

    # record the finished part
    done = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                               ctx.bucket_id, ctx.key)
    done.parts = done.parts.put((part_number, ts),
                                MpuPart(version_uuid, etag, total))
    await ctx.garage.mpu_table.insert(done)
    return Response(200, [("etag", f'"{etag}"')])


class _StreamReader:
    """Adapts an async byte-chunk generator to the body-reader interface
    Chunker expects (read(n) returning b'' at EOF, never over-returning).

    Fast path: with an empty carry buffer, a generator chunk that fits
    the request passes through untouched — the GET readahead pipeline's
    blocks reach the put pipeline (CopyObject re-encryption,
    UploadPartCopy) without the old extend+slice+memmove round trip."""

    def __init__(self, gen):
        self._gen = gen
        self._buf = bytearray()
        self._eof = False

    async def read(self, n: int = 65536):
        while not self._eof and len(self._buf) < n:
            try:
                chunk = await self._gen.__anext__()
            except StopAsyncIteration:
                self._eof = True
                break
            if chunk and not self._buf and len(chunk) <= n:
                return chunk  # zero-copy pass-through
            self._buf.extend(chunk)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    async def aclose(self) -> None:
        aclose = getattr(self._gen, "aclose", None)
        if aclose is not None:
            await aclose()


async def handle_upload_part_copy(ctx, req: Request) -> Response:
    """UploadPartCopy: fill a part from (a range of) an existing object
    (ref: api/s3/copy.rs:340-520 handle_upload_part_copy). The source
    streams through the normal put pipeline — re-chunked and, when the
    upload is SSE-C, re-encrypted under the destination key — so any
    source range and any encryption combination is correct; aligned
    whole-block reuse is left to CopyObject."""
    from urllib.parse import unquote

    from ...model.helper import GarageHelper
    from .encryption import (check_key_for_meta, copy_source_sse_key,
                             request_sse_key)
    from .get import parse_range

    q = req.query
    try:
        part_number = int(q["partNumber"])
        if not (1 <= part_number <= 10000):
            raise ValueError
    except (KeyError, ValueError):
        raise S3Error("InvalidArgument", 400, "bad partNumber")
    mpu, ov = await _get_upload(ctx, q.get("uploadId", ""))
    dst_sse = check_key_for_meta(_UploadMeta(ov.state.headers or {}),
                                 request_sse_key(req))

    src = unquote(req.header("x-amz-copy-source") or "").lstrip("/")
    src_bucket_name, _, src_key = src.partition("/")
    if not src_bucket_name or not src_key:
        raise S3Error("InvalidRequest", 400,
                      "malformed x-amz-copy-source")
    helper = GarageHelper(ctx.garage)
    src_bucket_id = await helper.resolve_global_bucket_name(src_bucket_name)
    if src_bucket_id is None:
        raise S3Error("NoSuchBucket", 404, src_bucket_name)
    if not ctx.api_key.allow_read(src_bucket_id):
        raise S3Error("AccessDenied", 403, "no read access to source")
    src_obj = await ctx.garage.object_table.get(src_bucket_id,
                                                src_key.encode())
    src_v = src_obj.last_data() if src_obj is not None else None
    if src_v is None:
        raise S3Error("NoSuchKey", 404, src_key)
    src_meta = src_v.state.data.meta
    from .get import check_copy_source_preconditions

    check_copy_source_preconditions(req, src_v, src_meta.etag)
    src_sse = check_key_for_meta(src_meta, copy_source_sse_key(req))

    size = src_meta.size
    start, end = 0, size
    range_hdr = req.header("x-amz-copy-source-range")
    if range_hdr:
        rng = parse_range(range_hdr, size)
        if rng is None:
            raise S3Error("InvalidRange", 416, "bad copy source range")
        start, end = rng
    # validate BEFORE inserting any rows — emptiness is knowable now
    if end - start == 0:
        raise S3Error("InvalidRequest", 400, "empty copy source range")
    from .get import open_object_stream

    source = await open_object_stream(ctx.garage, src_v, start, end,
                                      src_sse)

    await req.body.drain()
    ts = mpu.next_timestamp(part_number)
    version_uuid = gen_uuid()
    mpu2 = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                               ctx.bucket_id, ctx.key)
    mpu2.parts = mpu2.parts.put((part_number, ts), MpuPart(version_uuid))
    await ctx.garage.mpu_table.insert(mpu2)
    version = Version.new(version_uuid, (BACKLINK_MPU, mpu.upload_id))
    await ctx.garage.version_table.insert(version)

    from ... import native

    md5 = native.Md5()
    try:
        chunker = Chunker(source, ctx.garage.config.block_size)
        first = await chunker.next()
        if first is None:
            raise S3Error("InvalidRequest", 400, "empty copy source")
        total, _md5_hex, etag, _ = await read_and_put_blocks(
            ctx.garage, version, part_number, first, chunker, md5,
            sse_key=dst_sse)
    except BaseException:
        try:
            await ctx.garage.version_table.insert(Version.new(
                version_uuid, (BACKLINK_MPU, mpu.upload_id),
                deleted=True))
        except Exception as e:
            log.warning("interrupted-copy tombstone failed (block refs "
                        "leak until abort/complete): %s", e)
        raise
    finally:
        # an aborted copy must cancel the source's readahead prefetches
        # now, not at GC time
        await source.aclose()

    done = MultipartUpload.new(mpu.upload_id, mpu.timestamp,
                               ctx.bucket_id, ctx.key)
    done.parts = done.parts.put((part_number, ts),
                                MpuPart(version_uuid, etag, total))
    await ctx.garage.mpu_table.insert(done)
    from .put import _http_date

    return xml_response(xml("CopyPartResult",
                            xml("LastModified", _http_date(now_msec())),
                            xml("ETag", f'"{etag}"')))


async def handle_complete_multipart(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_complete_multipart_upload."""
    import xml.etree.ElementTree as ET

    body = await req.body.read_all(limit=1 << 20)
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError:
        raise S3Error("MalformedXML", 400, "cannot parse request")
    asked = []  # [(part_number, etag)]
    for part in root:
        if not part.tag.endswith("Part"):
            continue
        pn = etag = None
        for c in part:
            if c.tag.endswith("PartNumber"):
                pn = int(c.text)
            elif c.tag.endswith("ETag"):
                etag = (c.text or "").strip().strip('"')
        if pn is not None:
            asked.append((pn, etag))
    if not asked or asked != sorted(asked, key=lambda x: x[0]) \
            or len({p for p, _ in asked}) != len(asked):
        raise S3Error("InvalidPartOrder", 400,
                      "parts must be ordered and unique")

    upload_id = req.query.get("uploadId", "")
    mpu, ov = await _get_upload(ctx, upload_id)

    # newest stored record per part number that has completed
    stored = {}
    for (pn, ts), part in mpu.parts.items():
        if part.etag is not None:
            if pn not in stored or ts > stored[pn][0]:
                stored[pn] = (ts, part)
    parts = []
    for pn, etag in asked:
        if pn not in stored or (etag and stored[pn][1].etag != etag):
            raise S3Error("InvalidPart", 400, f"part {pn} not found")
        parts.append((pn, stored[pn][1]))

    # splice all part versions into the final object version
    # (ref: multipart.rs:260-330)
    final = Version.new(ov.uuid, (BACKLINK_OBJECT, ctx.bucket_id, ctx.key))
    total_size = 0
    etag_md5 = hashlib.md5()
    for pn, part in parts:
        pv = await ctx.garage.version_table.get(part.version, b"")
        if pv is None or pv.is_tombstone():
            raise S3Error("InvalidPart", 400, f"part {pn} lost")
        for (_p, off), (h, sz) in pv.blocks.items():
            final = Version(final.uuid, final.deleted,
                            final.blocks.put((pn, off), (h, sz)),
                            final.backlink)
            total_size += sz
        etag_md5.update(bytes.fromhex(part.etag))
    # quotas are enforced at completion, when the real total is known
    # (ref: multipart.rs handle_complete_multipart_upload check_quotas)
    from .put import check_quotas

    existing = await ctx.garage.object_table.get(ctx.bucket_id,
                                                 ctx.key.encode())
    await check_quotas(ctx.garage, ctx.bucket_id, total_size, existing)
    await ctx.garage.version_table.insert(final)
    # re-point block refs from part versions to the final version
    for pn, part in parts:
        pv = await ctx.garage.version_table.get(part.version, b"")
        for _k, (h, _s) in pv.blocks.items():
            await ctx.garage.block_ref_table.insert(BlockRef.new(h, ov.uuid))

    etag = f"{etag_md5.hexdigest()}-{len(parts)}"
    headers = (ov.state.headers if ov.state.kind == "uploading" else {})
    meta = ObjectVersionMeta(headers, total_size, etag)
    first_hash = next(iter([h for _k, (h, _s) in final.blocks.items()]),
                      b"\x00" * 32)
    done = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
        ov.uuid, ov.timestamp,
        ObjectVersionState.complete(
            ObjectVersionData.first_block(meta, first_hash)))])
    await ctx.garage.object_table.insert(done)
    return xml_response(xml("CompleteMultipartUploadResult",
                            xml("Bucket", ctx.bucket_name),
                            xml("Key", ctx.key),
                            xml("ETag", f'"{etag}"')))


async def handle_abort_multipart(ctx, req: Request) -> Response:
    """ref: multipart.rs handle_abort_multipart_upload."""
    upload_id = req.query.get("uploadId", "")
    mpu, ov = await _get_upload(ctx, upload_id)
    aborted = Object(ctx.bucket_id, ctx.key, [ObjectVersion(
        ov.uuid, ov.timestamp, ObjectVersionState.aborted())])
    await ctx.garage.object_table.insert(aborted)
    return Response(204)
