"""DeleteObject / DeleteObjects.

Ref parity: src/api/s3/delete.rs. Deletion inserts a DeleteMarker
version; the object-table merge drops older versions, whose cleanup
cascades through the version -> block_ref triggers.
"""

from __future__ import annotations

from ...model.s3.object_table import (Object, ObjectVersion,
                                      ObjectVersionData, ObjectVersionState)
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ..http import Request, Response
from .put import next_timestamp
from .xml import S3Error, xml, xml_response


async def delete_object(garage, bucket_id: bytes, key: str):
    """-> (deleted_uuid | None). ref: delete.rs handle_delete_internal."""
    obj = await garage.object_table.get(bucket_id, key.encode())
    if obj is None or obj.last_data() is None:
        return None  # idempotent: deleting nothing is fine
    uuid = gen_uuid()
    ts = next_timestamp(obj)
    marker = Object(bucket_id, key, [ObjectVersion(
        uuid, ts,
        ObjectVersionState.complete(ObjectVersionData.delete_marker()))])
    await garage.object_table.insert(marker)
    return uuid


async def handle_delete_object(ctx, req: Request) -> Response:
    await delete_object(ctx.garage, ctx.bucket_id, ctx.key)
    return Response(204)


async def handle_delete_objects(ctx, req: Request) -> Response:
    """POST /?delete — batch deletion (ref: delete.rs
    handle_delete_objects)."""
    import xml.etree.ElementTree as ET

    body = await req.body.read_all(limit=1 << 20)
    try:
        root = ET.fromstring(body.decode())
    except ET.ParseError:
        raise S3Error("MalformedXML", 400, "cannot parse Delete document")
    quiet = any(c.tag.endswith("Quiet") and (c.text or "").strip() == "true"
                for c in root)
    results = []
    for obj in root:
        if not obj.tag.endswith("Object"):
            continue
        key = None
        for c in obj:
            if c.tag.endswith("Key"):
                key = c.text or ""
        if key is None:
            continue
        try:
            await delete_object(ctx.garage, ctx.bucket_id, key)
            if not quiet:
                results.append(xml("Deleted", xml("Key", key)))
        except Exception as e:
            results.append(xml("Error", xml("Key", key),
                               xml("Code", "InternalError"),
                               xml("Message", str(e))))
    return xml_response(xml("DeleteResult", *results))
