"""S3-compatible API (ref: src/api/s3/)."""

from .api_server import S3ApiServer

__all__ = ["S3ApiServer"]
