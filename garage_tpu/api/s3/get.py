"""GetObject / HeadObject, with range and conditional requests.

Ref parity: src/api/s3/get.rs:139-508. Serves inline data directly;
block data streams block-by-block through BlockManager (ordered,
failover per block). Range requests binary-search the version's block
list; conditionals (If-None-Match / If-Modified-Since) answer 304.
"""

from __future__ import annotations

import asyncio
import datetime
from collections import deque
from typing import AsyncIterator, Optional

from ..http import Request, Response
from .xml import S3Error, no_such_key


def http_date(ts_msec: int) -> str:
    return datetime.datetime.fromtimestamp(
        ts_msec / 1000, datetime.timezone.utc
    ).strftime("%a, %d %b %Y %H:%M:%S GMT")


def _parse_http_date(s: str, which: str) -> float:
    try:
        return datetime.datetime.strptime(
            s, "%a, %d %b %Y %H:%M:%S GMT"
        ).replace(tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        # ref: get.rs PreconditionHeaders::parse ok_or_bad_request
        raise S3Error("InvalidArgument", 400, f"invalid date in {which}")


def _etag_matches(header_val: str, etag: str) -> bool:
    """Client tokens may come quoted or bare (the reference strips
    quotes: get.rs trim_matches('\"'))."""
    cands = [e.strip() for e in header_val.split(",")]
    return "*" in cands or any(c.strip('"') == etag for c in cands)


def check_preconditions(get_header, version, etag: str) -> Optional[str]:
    """RFC 7232 §6 evaluation order, shared by GET/HEAD and the copy
    family (ref: get.rs:819-855 PreconditionHeaders::check). Returns
    None (pass), "fail" (412 always) or "not_modified" (304 on GET,
    412 on copy). `get_header` maps a bare condition name ("if-match")
    to the header value, letting copy prefix x-amz-copy-source-."""
    im = get_header("if-match")
    if im is not None:
        if not _etag_matches(im, etag):
            return "fail"
    else:
        ius = get_header("if-unmodified-since")
        if ius is not None:
            t = _parse_http_date(ius, "if-unmodified-since")
            # floor to whole seconds: Last-Modified has 1 s resolution
            if version.timestamp // 1000 > t:
                return "fail"
    inm = get_header("if-none-match")
    if inm is not None:
        if _etag_matches(inm, etag):
            return "not_modified"
    else:
        ims = get_header("if-modified-since")
        if ims is not None:
            t = _parse_http_date(ims, "if-modified-since")
            if version.timestamp // 1000 <= t:
                return "not_modified"
    return None


def check_copy_source_preconditions(req: Request, version, etag: str) -> None:
    """`x-amz-copy-source-if-*` for CopyObject / UploadPartCopy. On a
    copy, EVERY failed condition — including the ones a GET would
    answer 304 to — is a 412 (ref: get.rs check_copy_source)."""
    pfx = "x-amz-copy-source-"
    if check_preconditions(
            lambda name: req.header(pfx + name), version, etag) is not None:
        raise S3Error("PreconditionFailed", 412,
                      "copy source precondition failed")


def _object_headers(version, meta) -> list[tuple[str, str]]:
    """ref: get.rs object_headers."""
    out = [("etag", f'"{meta.etag}"'),
           ("last-modified", http_date(version.timestamp)),
           ("accept-ranges", "bytes"),
           ("x-amz-version-id", version.uuid.hex())]
    for name, v in sorted(meta.headers.items()):
        if name.startswith(("x-garage-ssec-", "x-garage-checksum-")):
            continue  # internal markers; surfaced as x-amz-* on demand
        out.append((name, v))
    if "content-type" not in meta.headers:
        out.append(("content-type", "application/octet-stream"))
    from .encryption import sse_response_headers

    out.extend(sse_response_headers(meta))
    return out


def parse_range(spec: str, size: int) -> Optional[tuple[int, int]]:
    """'bytes=a-b' -> (start, end_exclusive), or None if unparsable.

    Multi-range specs ('bytes=0-0,5-9') are rejected as a whole (-> 416
    upstream): this server serves single ranges only, and silently
    answering with just the first range hands the client a body it
    didn't ask for — a multipart/byteranges consumer would misparse it.
    """
    if not spec.startswith("bytes="):
        return None
    ranges = [p for p in spec[len("bytes="):].split(",") if p.strip()]
    if len(ranges) != 1:
        return None
    r = ranges[0].strip()
    start_s, _, end_s = r.partition("-")
    try:
        if start_s == "":
            n = int(end_s)  # suffix range: last n bytes
            if n == 0:
                return None
            return max(0, size - n), size
        start = int(start_s)
        end = int(end_s) + 1 if end_s else size
    except ValueError:
        return None
    if start >= size or start >= end:
        return None
    return start, min(end, size)


async def handle_get(ctx, req: Request, head: bool = False) -> Response:
    from .encryption import check_key_for_meta, request_sse_key

    obj = await ctx.garage.object_table.get(ctx.bucket_id,
                                            ctx.key.encode())
    v = obj.last_data() if obj is not None else None
    if v is None:
        raise no_such_key(ctx.key)
    meta = v.state.data.meta
    sse_key = check_key_for_meta(meta, request_sse_key(req))

    # conditionals (ref: get.rs try_answer_cached)
    cond = check_preconditions(req.header, v, meta.etag)
    if cond == "fail":
        raise S3Error("PreconditionFailed", 412, "precondition failed")
    if cond == "not_modified":
        return Response(304, _object_headers(v, meta))

    headers = _object_headers(v, meta)
    # response-content-* query overrides (ref: get.rs:41-44,104-107;
    # presigned-download UX: the signer picks the browser-facing
    # content-type/disposition at sign time)
    for qname, hname in (("response-content-type", "content-type"),
                         ("response-content-language", "content-language"),
                         ("response-content-encoding", "content-encoding"),
                         ("response-content-disposition",
                          "content-disposition"),
                         ("response-cache-control", "cache-control"),
                         ("response-expires", "expires")):
        ov = req.query.get(qname)
        if ov is not None:
            headers = [(n, val) for n, val in headers if n != hname]
            headers.append((hname, ov))
    if (req.header("x-amz-checksum-mode") or "").upper() == "ENABLED":
        for name, val in meta.headers.items():
            if name.startswith("x-garage-checksum-"):
                algo = name[len("x-garage-checksum-"):]
                headers.append((f"x-amz-checksum-{algo}", val))
    size = meta.size
    rng = None
    prefetched_version = None
    part_q = req.query.get("partNumber")
    if part_q is not None:
        if req.header("range"):
            raise S3Error("InvalidRequest", 400,
                          "cannot combine partNumber and Range")
        rng, n_parts, prefetched_version = await _part_range(
            ctx, v, size, part_q)
        headers.append(("x-amz-mp-parts-count", str(n_parts)))
        if size == 0:
            # a 0-byte object has no valid byte range; serve the whole
            # (empty) body like AWS instead of "bytes 0--1/0"
            rng = None
    elif req.header("range"):
        rng = parse_range(req.header("range"), size)
        if rng is None:
            return Response(416, [("content-range", f"bytes */{size}")])

    data = v.state.data
    if data.kind == "inline":
        payload = (sse_key.decrypt_block(data.blob)
                   if sse_key is not None else data.blob)
        if rng is not None:
            start, end = rng
            headers.append(("content-range",
                            f"bytes {start}-{end - 1}/{size}"))
            if head:
                headers.append(("content-length", str(end - start)))
                return Response(206, headers)
            return Response(206, headers, payload[start:end])
        if head:
            headers.append(("content-length", str(len(payload))))
            return Response(200, headers)
        return Response(200, headers, payload)

    version = (prefetched_version if prefetched_version is not None
               else await ctx.garage.version_table.get(v.uuid, b""))
    if version is None:
        raise no_such_key(ctx.key)
    blocks = list(version.blocks.items())  # sorted by (part, offset)

    if head:
        if rng is not None:
            start, end = rng
            headers.append(("content-range",
                            f"bytes {start}-{end - 1}/{size}"))
            headers.append(("content-length", str(end - start)))
            return Response(206, headers)
        headers.append(("content-length", str(size)))
        return Response(200, headers)

    if rng is None:
        return Response(200, headers + [("content-length", str(size))],
                        _stream_blocks(ctx.garage, blocks, 0, size,
                                       sse_key))
    start, end = rng
    headers.append(("content-range", f"bytes {start}-{end - 1}/{size}"))
    headers.append(("content-length", str(end - start)))
    return Response(206, headers, _stream_blocks(ctx.garage, blocks,
                                                 start, end, sse_key))


async def _part_range(ctx, v, size: int, part_q: str):
    """?partNumber=N -> (byte range, part count, prefetched Version or
    None) — the Version row is returned so the block path doesn't fetch
    it a second time (ref: get.rs handle_get with part_number).
    Non-multipart objects are a single part 1 covering the whole body."""
    try:
        pn = int(part_q)
        if pn < 1:
            raise ValueError
    except ValueError:
        raise S3Error("InvalidArgument", 400, "bad partNumber")
    version = None
    if v.state.data.kind != "inline":
        version = await ctx.garage.version_table.get(v.uuid, b"")
    if version is None or not list(version.blocks.items()):
        if pn != 1:
            raise S3Error("InvalidPartNumber", 416, "no such part")
        return (0, size), 1, version
    part_sizes: dict[int, int] = {}
    for (part, _off), (_h, blen) in version.blocks.items():
        part_sizes[part] = part_sizes.get(part, 0) + blen
    parts = sorted(part_sizes)
    if pn not in part_sizes:
        raise S3Error("InvalidPartNumber", 416, "no such part")
    start = sum(part_sizes[p] for p in parts if p < pn)
    return (start, start + part_sizes[pn]), len(parts), version


async def open_object_stream(garage, src_v, start: int, end: int,
                             src_sse=None):
    """Plaintext byte-stream reader over [start, end) of an object
    version (inline or block-backed), decrypting with `src_sse` when
    given. Shared by CopyObject and UploadPartCopy (ref: copy.rs
    source-stream plumbing)."""
    from .multipart import _StreamReader
    from .xml import S3Error

    if src_v.state.data.kind == "inline":
        blob = src_v.state.data.blob
        if src_sse is not None:
            blob = src_sse.decrypt_block(blob)
        piece = blob[start:end]

        async def gen_inline():
            yield piece

        return _StreamReader(gen_inline())
    src_version = await garage.version_table.get(src_v.uuid, b"")
    if src_version is None:
        raise S3Error("NoSuchKey", 404, "source version vanished")
    blocks = list(src_version.blocks.items())
    return _StreamReader(_stream_blocks(garage, blocks, start, end,
                                        src_sse))


def _plan_blocks(blocks, start: int, end: int) -> list[tuple[bytes, int, int]]:
    """-> [(hash, lo, hi)] covering [start, end) of the concatenated
    block list. lo/hi are plaintext offsets within each block."""
    plan = []
    pos = 0
    for _key, (h, size) in blocks:
        if pos + size <= start:
            pos += size
            continue
        if pos >= end:
            break
        plan.append((h, max(0, start - pos), min(size, end - pos)))
        pos += size
    return plan


# decrypt below this size stays inline: a thread hop costs more than
# the AES-GCM call itself (matches the put path's 64 KiB hash threshold)
_DECRYPT_OFFLOAD_MIN = 64 * 1024


def _slice(data, lo: int, hi: int):
    """Zero-copy body slice: a partial block is served through a
    memoryview instead of materializing a fresh bytes object (the HTTP
    writer accepts any bytes-like)."""
    if lo == 0 and hi >= len(data):
        return data
    return memoryview(data)[lo:hi]


async def _stream_blocks(garage, blocks, start: int, end: int,
                         sse_key=None) -> AsyncIterator[bytes]:
    """Stream [start, end) of the concatenated block list
    (ref: get.rs body_from_blocks_range + the ordered readahead buffer
    it feeds). Block sizes in the version map are plaintext sizes; with
    `sse_key` each fetched block is decrypted before slicing, so ranges
    address plaintext offsets.

    Readahead: up to `[s3_api] get_readahead_blocks` blocks beyond the
    one currently being streamed are fetched concurrently with
    asyncio.create_task, and yielded strictly in order — the next
    block(s) ride the wire while the current one drains to the client,
    so GET throughput is no longer one-block-RTT-at-a-time.
    Per-block failover lives inside rpc_get_block and is unchanged; a
    block that fails on every holder fails the stream exactly where the
    sequential loop would have. Client disconnects close this generator
    (http.write_response calls aclose), whose finally block cancels
    every in-flight prefetch — no orphaned tasks.
    get_readahead_blocks = 0 reproduces the sequential behavior."""
    plan = _plan_blocks(blocks, start, end)
    depth = getattr(garage.config, "s3_get_readahead_blocks", 3)

    # SSE-C blocks are excluded from the hot-block read cache: the
    # payload is ciphertext the node can only decrypt while the
    # client's key is in hand — never keep it in RAM past the request
    cacheable = sse_key is None

    if depth <= 0:
        # strictly sequential fallback switch
        for h, lo, hi in plan:
            data = await garage.block_manager.rpc_get_block(
                h, cacheable=cacheable)
            if sse_key is not None:
                data = sse_key.decrypt_block(data)
            yield _slice(data, lo, hi)
        return

    async def fetch(h):
        data = await garage.block_manager.rpc_get_block(
            h, cacheable=cacheable)
        if sse_key is not None:
            # AES-GCM releases the GIL; MiB-scale blocks decrypt in a
            # worker thread so the loop keeps serving other requests.
            # Decrypting inside the prefetch task (not at yield time)
            # overlaps decrypt with the wire, and ordered yields keep
            # the plaintext sequence correct regardless of which
            # prefetch finishes first.
            if len(data) >= _DECRYPT_OFFLOAD_MIN:
                data = await asyncio.to_thread(sse_key.decrypt_block, data)
            else:
                data = sse_key.decrypt_block(data)
        return data

    window: deque[asyncio.Task] = deque()
    nxt = 0  # next plan index to schedule
    try:
        while nxt < len(plan) or window:
            # current block + `depth` ahead may be in flight at once
            while nxt < len(plan) and len(window) < depth + 1:
                window.append(asyncio.create_task(fetch(plan[nxt][0])))
                nxt += 1
            _h, lo, hi = plan[nxt - len(window)]
            # await while the task is STILL in the window: if this
            # generator itself is cancelled mid-await, the task must
            # remain reachable by the finally below or it leaks
            data = await window[0]
            window.popleft()
            yield _slice(data, lo, hi)
    finally:
        # client went away (or a fetch failed): cancel synchronously
        # first so nothing new starts, then settle the tasks so no
        # "exception was never retrieved" noise outlives the request
        for t in window:
            t.cancel()
        if window:
            await asyncio.gather(*window, return_exceptions=True)
