"""K2V item endpoints: ReadItem / InsertItem / DeleteItem / PollItem.

Ref parity: src/api/k2v/item.rs. Values travel raw
(application/octet-stream, single value) or as a JSON array of base64
strings (null = deletion marker); the X-Garage-Causality-Token header
carries the item's causal context both ways.
"""

from __future__ import annotations

import base64
import json

from ...model.k2v.causality import CausalContext
from ...model.k2v.item_table import K2VItem, partition_pk
from ..http import Request, Response
from ..s3.xml import S3Error

CAUSALITY_TOKEN = "x-garage-causality-token"


def parse_causality_token(s: str) -> CausalContext:
    ct = CausalContext.parse(s)
    if ct is None:
        raise S3Error("InvalidCausalityToken", 400,
                      "Invalid causality token")
    return ct


def _accept(req: Request) -> str:
    """-> "json" | "binary" | "either" (ref: item.rs ReturnFormat)."""
    accept = req.header("accept")
    if accept is None:
        return "json"
    parts = [p.strip().split(";")[0] for p in accept.split(",")]
    wants_json = "application/json" in parts or "*/*" in parts
    wants_bin = "application/octet-stream" in parts or "*/*" in parts
    if wants_json and wants_bin:
        return "either"
    if wants_json:
        return "json"
    if wants_bin:
        return "binary"
    raise S3Error("NotAcceptable", 406,
                  "Accept must include application/json or "
                  "application/octet-stream")


def make_item_response(req: Request, item: K2VItem) -> Response:
    vals = item.values()
    if not vals:
        raise S3Error("NoSuchKey", 404, "no such key")
    ct = item.causal_context().serialize()
    fmt = _accept(req)
    if fmt == "binary" and len(vals) > 1:
        return Response(409, [(CAUSALITY_TOKEN, ct)])
    if fmt == "binary" or (fmt == "either" and len(vals) == 1):
        v = vals[0]
        if v is None:
            return Response(204, [(CAUSALITY_TOKEN, ct),
                                  ("content-type",
                                   "application/octet-stream")])
        return Response(200, [(CAUSALITY_TOKEN, ct),
                              ("content-type",
                               "application/octet-stream")], v)
    body = json.dumps([
        None if v is None else base64.b64encode(v).decode() for v in vals
    ]).encode()
    return Response(200, [(CAUSALITY_TOKEN, ct),
                          ("content-type", "application/json")], body)


async def handle_read_item(ctx, req: Request, partition_key: str,
                           sort_key: str) -> Response:
    item = await ctx.garage.k2v_item_table.get(
        partition_pk(ctx.bucket_id, partition_key), sort_key.encode())
    if item is None:
        raise S3Error("NoSuchKey", 404, "no such key")
    return make_item_response(req, item)


async def handle_insert_item(ctx, req: Request, partition_key: str,
                             sort_key: str) -> Response:
    ct_str = req.header(CAUSALITY_TOKEN)
    ct = parse_causality_token(ct_str) if ct_str else None
    value = await req.body.read_all(limit=10 << 20)
    await ctx.garage.k2v_rpc.insert(ctx.bucket_id, partition_key,
                                    sort_key, ct, value)
    return Response(204)


async def handle_delete_item(ctx, req: Request, partition_key: str,
                             sort_key: str) -> Response:
    ct_str = req.header(CAUSALITY_TOKEN)
    if not ct_str:
        raise S3Error("InvalidRequest", 400,
                      "X-Garage-Causality-Token is required for deletes")
    ct = parse_causality_token(ct_str)
    await req.body.drain()
    await ctx.garage.k2v_rpc.insert(ctx.bucket_id, partition_key,
                                    sort_key, ct, None)
    return Response(204)


async def handle_poll_range(ctx, req: Request,
                            partition_key: str) -> Response:
    """POST /{bucket}/{partition}?poll_range — wait for changes in a
    sort-key range vs a seen marker (ref: api/k2v poll_range +
    model/k2v/seen.rs)."""
    raw = await req.body.read_all(limit=1 << 20)
    try:
        spec = json.loads(raw.decode()) if raw else {}
    except (ValueError, UnicodeDecodeError):
        raise S3Error("InvalidRequest", 400, "body is not valid JSON")
    try:
        timeout = min(float(spec.get("timeout", 300)), 600.0)
    except (TypeError, ValueError):
        raise S3Error("InvalidRequest", 400, "bad timeout")
    try:
        res = await ctx.garage.k2v_rpc.poll_range(
            ctx.bucket_id, partition_key,
            spec.get("prefix"), spec.get("start"), spec.get("end"),
            spec.get("seenMarker"), timeout)
    except ValueError as e:
        raise S3Error("InvalidRequest", 400, str(e))
    if res is None:
        return Response(304)
    items, seen = res
    body = json.dumps({
        "items": [{
            "sk": i.sort_key_str,
            "ct": i.causal_context().serialize(),
            "v": [None if v is None else base64.b64encode(v).decode()
                  for v in i.values()],
        } for i in items],
        "seenMarker": seen,
    }).encode()
    return Response(200, [("content-type", "application/json")], body)


async def handle_poll_item(ctx, req: Request, partition_key: str,
                           sort_key: str) -> Response:
    ct = parse_causality_token(req.query.get("causality_token", ""))
    try:
        timeout = min(float(req.query.get("timeout", "300")), 600.0)
    except ValueError:
        raise S3Error("InvalidRequest", 400, "bad timeout")
    item = await ctx.garage.k2v_rpc.poll_item(
        ctx.bucket_id, partition_key, sort_key, ct, timeout)
    if item is None:
        return Response(304, [(CAUSALITY_TOKEN, ct.serialize())])
    return make_item_response(req, item)
