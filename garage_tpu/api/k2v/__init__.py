"""K2V API: causally-consistent key-key-value store over HTTP.

Ref parity: src/api/k2v/. See api_server.K2VApiServer.
"""

from .api_server import K2VApiServer

__all__ = ["K2VApiServer"]
