"""K2V API server: routing, auth, dispatch.

Ref parity: src/api/k2v/api_server.rs + router.rs. URL shape:

  GET    /{bucket}                       ?start&end&limit&prefix  ReadIndex
  POST   /{bucket}                       body = [items]           InsertBatch
  POST   /{bucket}?search                body = [queries]         ReadBatch
  POST   /{bucket}?delete                body = [queries]         DeleteBatch
  GET    /{bucket}/{partition_key}?sort_key=...                   ReadItem
  GET    /{bucket}/{partition_key}?sort_key=...&causality_token=
         ...&timeout=...                                          PollItem
  PUT    /{bucket}/{partition_key}?sort_key=...                   InsertItem
  DELETE /{bucket}/{partition_key}?sort_key=...                   DeleteItem

Auth is SigV4 with scope service "k2v". Permissions reuse the bucket
key grants (read for GET/POLL, write for PUT/DELETE/batches).
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from ...model.helper import GarageHelper
from ...qos.limiter import SlowDown
from ...utils.error import BadRequest, NoSuchBucket, NoSuchKey
from ..http import HttpError, HttpServer, Request, Response
from ..s3.api_server import ReqCtx
from ..s3.xml import S3Error, access_denied, no_such_bucket
from ..signature import verify_request
from . import batch as batch_handlers
from . import index as index_handlers
from . import item as item_handlers

log = logging.getLogger("garage_tpu.api.k2v")


def json_error(code: str, status: int, message: str,
               headers: Optional[list] = None) -> Response:
    body = json.dumps({"code": code, "message": message}).encode()
    return Response(status,
                    [("content-type", "application/json")]
                    + (headers or []), body)


class K2VApiServer:
    def __init__(self, garage, region: Optional[str] = None):
        self.garage = garage
        self.helper = GarageHelper(garage)
        self.region = region or garage.config.s3_region
        self.http = HttpServer(self.handle, name="k2v")

    async def start(self, host: str, port=None,
                    reuse_port: bool = False) -> None:
        # a path (port None) binds a Unix-domain socket, like the
        # reference's UnixOrTCPSocketAddress bind addresses; reuse_port
        # is the gateway workers' SO_REUSEPORT shared accept loop
        if port is None:
            await self.http.start_unix(host)
        else:
            await self.http.start(host, port, reuse_port=reuse_port)

    async def stop(self) -> None:
        await self.http.stop()

    async def handle(self, req: Request) -> Response:
        # claimed key id = per-key fairness identity for the global
        # request-rate DRR (same discipline as the S3 frontend; reset
        # per keep-alive request so identity never leaks across)
        from ...qos.limiter import CURRENT_QOS_KEY
        from ..signature import claimed_key_id

        qos_key_token = CURRENT_QOS_KEY.set(claimed_key_id(req))
        try:
            # same two-stage qos admission as the S3 frontend: global
            # (cheap, pre-auth) here, per-key/per-bucket in _handle
            qos = getattr(self.garage, "qos", None)
            if qos is None:
                return await self._handle(req)
            cl = req.header("content-length")
            async with qos.admit(
                    "k2v", nbytes=int(cl) if cl and cl.isdigit() else None):
                return await self._handle(req)
        except SlowDown as e:
            return json_error("SlowDown", 503,
                              "Please reduce your request rate.",
                              headers=[("retry-after", e.header_value())])
        except S3Error as e:
            return json_error(e.code, e.status, e.message)
        except HttpError as e:
            return json_error("InvalidRequest", e.status, e.reason)
        except NoSuchBucket as e:
            return json_error("NoSuchBucket", 404, str(e))
        except NoSuchKey as e:
            return json_error("NoSuchKey", 404, str(e))
        except BadRequest as e:
            return json_error("InvalidRequest", 400, str(e))
        finally:
            CURRENT_QOS_KEY.reset(qos_key_token)

    async def _handle(self, req: Request) -> Response:
        verified = await verify_request(req, self.region,
                                        self.helper.key_secret,
                                        service="k2v")
        if verified is None:
            raise access_denied("authentication required")
        api_key = await self.helper.get_existing_key(verified.key_id)

        path = req.path.lstrip("/")
        bucket_name, _, partition_key = path.partition("/")
        if not bucket_name:
            raise S3Error("InvalidRequest", 400, "no bucket in path")
        qos = getattr(self.garage, "qos", None)
        if qos is not None:
            await qos.admit_scoped(key_id=api_key.key_id,
                                   bucket=bucket_name)
        # fairness identity for downstream byte charges, now VERIFIED
        # (reset by handle() per request)
        from ...qos.limiter import CURRENT_QOS_KEY

        CURRENT_QOS_KEY.set(api_key.key_id)

        bucket_id = await self.helper.resolve_global_bucket_name(bucket_name)
        if bucket_id is None:
            raise no_such_bucket(bucket_name)
        bucket = await self.helper.get_existing_bucket(bucket_id)

        # PollRange is a READ despite traveling as POST (it carries a
        # JSON body); gating it on write would both leak values to
        # write-only keys and lock out read-only consumers
        is_read = (req.method in ("GET", "HEAD")
                   or (req.method == "POST" and "poll_range" in req.query))
        allowed = (api_key.allow_read(bucket_id) if is_read
                   else api_key.allow_write(bucket_id))
        if not allowed:
            raise access_denied()

        ctx = ReqCtx(self.garage, bucket_id, bucket_name, bucket,
                     partition_key or None, api_key, verified)
        return await self._route(req, ctx, partition_key)

    async def _route(self, req: Request, ctx: ReqCtx,
                     partition_key: str) -> Response:
        m, q = req.method, req.query
        if not partition_key:
            if m == "GET":
                return await index_handlers.handle_read_index(ctx, req)
            if m == "POST":
                if "search" in q:
                    return await batch_handlers.handle_read_batch(ctx, req)
                if "delete" in q:
                    return await batch_handlers.handle_delete_batch(ctx,
                                                                    req)
                return await batch_handlers.handle_insert_batch(ctx, req)
            raise S3Error("NotImplemented", 501,
                          f"unsupported K2V bucket operation {m}")
        if m == "POST" and "poll_range" in q:
            return await item_handlers.handle_poll_range(ctx, req,
                                                         partition_key)
        if "sort_key" not in q:
            raise S3Error("InvalidRequest", 400, "sort_key is required")
        sort_key = q["sort_key"]
        if m in ("GET", "HEAD"):
            if "causality_token" in q:
                return await item_handlers.handle_poll_item(
                    ctx, req, partition_key, sort_key)
            return await item_handlers.handle_read_item(
                ctx, req, partition_key, sort_key)
        if m == "PUT":
            return await item_handlers.handle_insert_item(
                ctx, req, partition_key, sort_key)
        if m == "DELETE":
            return await item_handlers.handle_delete_item(
                ctx, req, partition_key, sort_key)
        raise S3Error("NotImplemented", 501,
                      f"unsupported K2V item operation {m}")
