"""K2V ReadIndex: list partition keys with their counter aggregates.

Ref parity: src/api/k2v/index.rs — reads the k2v index counter table
(entries / conflicts / values / bytes per partition key).
"""

from __future__ import annotations

import json

from ...model.k2v.item_table import BYTES, CONFLICTS, ENTRIES, VALUES
from ..http import Request, Response
from ..s3.xml import S3Error
from .batch import check_start_in_prefix

MAX_LIMIT = 1000


async def handle_read_index(ctx, req: Request) -> Response:
    q = req.query
    prefix = q.get("prefix")
    start = q.get("start")
    end = q.get("end")
    check_start_in_prefix(start, prefix)
    try:
        limit = min(int(q.get("limit", MAX_LIMIT)), MAX_LIMIT)
    except ValueError:
        raise S3Error("InvalidRequest", 400, "bad limit")
    reverse = q.get("reverse", "").lower() in ("1", "true", "yes")

    garage = ctx.garage
    nodes = list(garage.system.layout_manager.history.all_nongateway_nodes())
    counter_table = garage.k2v_counter.table

    entries = await counter_table.get_range(
        ctx.bucket_id,
        start.encode() if start is not None else None,
        flt={"deleted": "not_deleted", "nodes": nodes},
        limit=limit + 1, reverse=reverse,
        prefix_sk=prefix.encode() if prefix else None,
        end_sk=end.encode() if end is not None else None)

    keys = []
    more, next_start = False, None
    for e in entries:
        pk_str = e.sk.decode("utf-8", "replace")
        if len(keys) >= limit:
            more, next_start = True, pk_str
            break
        vals = e.filtered_values(nodes)
        keys.append({
            "pk": pk_str,
            "entries": vals.get(ENTRIES, 0),
            "conflicts": vals.get(CONFLICTS, 0),
            "values": vals.get(VALUES, 0),
            "bytes": vals.get(BYTES, 0),
        })

    body = json.dumps({
        "prefix": prefix,
        "start": start,
        "end": end,
        "limit": limit,
        "reverse": reverse,
        "partitionKeys": keys,
        "more": more,
        "nextStart": next_start,
    }).encode()
    return Response(200, [("content-type", "application/json")], body)
