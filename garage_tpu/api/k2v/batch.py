"""K2V batch endpoints: InsertBatch / ReadBatch / DeleteBatch.

Ref parity: src/api/k2v/batch.rs. All three take JSON arrays; reads and
deletes are per-partition-key range queries over the item table.
"""

from __future__ import annotations

import base64
import json

from ...model.k2v.causality import CausalContext
from ...model.k2v.item_table import partition_pk
from ..http import Request, Response
from ..s3.xml import S3Error
from .item import parse_causality_token

MAX_LIMIT = 1000


async def _json_body(req: Request):
    raw = await req.body.read_all(limit=10 << 20)
    try:
        return json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        raise S3Error("InvalidRequest", 400, "body is not valid JSON")


async def handle_insert_batch(ctx, req: Request) -> Response:
    spec = await _json_body(req)
    if not isinstance(spec, list):
        raise S3Error("InvalidRequest", 400, "expected a JSON array")
    items = []
    for it in spec:
        try:
            pk, sk = it["pk"], it["sk"]
            ct = (parse_causality_token(it["ct"])
                  if it.get("ct") else None)
            v = it.get("v")
            # strict base64 like the reference (batch.rs:30-33): a
            # value with out-of-alphabet bytes is a client bug, not
            # data to silently mangle
            value = (base64.b64decode(v, validate=True)
                     if v is not None else None)
        except (KeyError, TypeError, ValueError):
            raise S3Error("InvalidRequest", 400, "malformed batch item")
        items.append((pk, sk, ct, value))
    await ctx.garage.k2v_rpc.insert_batch(ctx.bucket_id, items)
    return Response(204)


def check_start_in_prefix(start, prefix) -> None:
    """ref: range.rs:30-40 — a start key outside the prefix window is a
    contradiction the reference rejects up front (both directions).
    Non-string values 400 too: the reference rejects them at
    deserialization, and letting them through turns into a 500 at the
    first .startswith/.encode."""
    for v in (start, prefix):
        if v is not None and not isinstance(v, str):
            raise S3Error("InvalidRequest", 400,
                          "prefix/start must be strings")
    if prefix and start is not None and not start.startswith(prefix):
        raise S3Error(
            "InvalidRequest", 400,
            f"Start key '{start}' does not start with prefix '{prefix}'")


def _parse_query(qjson: dict) -> dict:
    if not isinstance(qjson, dict) or "partitionKey" not in qjson:
        raise S3Error("InvalidRequest", 400, "query needs partitionKey")
    if not isinstance(qjson["partitionKey"], str):
        raise S3Error("InvalidRequest", 400, "partitionKey must be a string")
    if qjson.get("end") is not None and not isinstance(qjson["end"], str):
        raise S3Error("InvalidRequest", 400, "end must be a string")
    check_start_in_prefix(qjson.get("start"), qjson.get("prefix"))
    raw_limit = qjson.get("limit")
    return {
        "partition_key": qjson["partitionKey"],
        "prefix": qjson.get("prefix"),
        "start": qjson.get("start"),
        "end": qjson.get("end"),
        "limit": (min(int(raw_limit), MAX_LIMIT)
                  if raw_limit is not None else MAX_LIMIT),
        "reverse": bool(qjson.get("reverse", False)),
        "single_item": bool(qjson.get("singleItem", False)),
        "conflicts_only": bool(qjson.get("conflictsOnly", False)),
        "tombstones": bool(qjson.get("tombstones", False)),
    }


async def _range_items(ctx, spec: dict, limit: int) -> list:
    """Range bounds (prefix / start / exclusive end, both directions)
    are enforced server-side by TableData.read_range."""
    pk = partition_pk(ctx.bucket_id, spec["partition_key"])
    flt = {"type": "item", "conflicts_only": spec["conflicts_only"],
           "tombstones": spec["tombstones"]}
    return await ctx.garage.k2v_item_table.get_range(
        pk,
        spec["start"].encode() if spec["start"] else None,
        flt=flt, limit=limit, reverse=spec["reverse"],
        prefix_sk=spec["prefix"].encode() if spec["prefix"] else None,
        end_sk=spec["end"].encode() if spec["end"] is not None else None)


def _item_json(item) -> dict:
    return {
        "sk": item.sort_key_str,
        "ct": item.causal_context().serialize(),
        "v": [None if v is None else base64.b64encode(v).decode()
              for v in item.values()],
    }


async def handle_read_batch(ctx, req: Request) -> Response:
    spec = await _json_body(req)
    if not isinstance(spec, list):
        raise S3Error("InvalidRequest", 400, "expected a JSON array")
    queries = [_parse_query(qj) for qj in spec]
    results = []
    for q in queries:
        if q["single_item"]:
            if q["start"] is None:
                raise S3Error("InvalidRequest", 400,
                              "singleItem requires start (the sort key)")
            item = await ctx.garage.k2v_item_table.get(
                partition_pk(ctx.bucket_id, q["partition_key"]),
                q["start"].encode())
            items = ([_item_json(item)] if item is not None
                     and (q["tombstones"] or not item.is_tombstone())
                     else [])
            results.append({
                "partitionKey": q["partition_key"],
                "prefix": q["prefix"], "start": q["start"],
                "end": q["end"], "limit": q["limit"],
                "reverse": q["reverse"], "singleItem": True,
                "items": items, "more": False, "nextStart": None,
            })
            continue
        # fetch one extra row: its sort key becomes the next page's
        # (inclusive) start without re-serving the boundary item
        items = await _range_items(ctx, q, q["limit"] + 1)
        more = len(items) > q["limit"]
        next_start = items[q["limit"]].sort_key_str if more else None
        items = items[:q["limit"]]
        results.append({
            "partitionKey": q["partition_key"],
            "prefix": q["prefix"], "start": q["start"], "end": q["end"],
            "limit": q["limit"], "reverse": q["reverse"],
            "singleItem": False,
            "items": [_item_json(i) for i in items],
            "more": more,
            "nextStart": next_start,
        })
    return Response(200, [("content-type", "application/json")],
                    json.dumps(results).encode())


async def handle_delete_batch(ctx, req: Request) -> Response:
    spec = await _json_body(req)
    if not isinstance(spec, list):
        raise S3Error("InvalidRequest", 400, "expected a JSON array")
    results = []
    for qj in spec:
        q = _parse_query(qj)
        if q["single_item"]:
            if q["start"] is None:
                raise S3Error("InvalidRequest", 400,
                              "singleItem requires start (the sort key)")
            item = await ctx.garage.k2v_item_table.get(
                partition_pk(ctx.bucket_id, q["partition_key"]),
                q["start"].encode())
            deleted = 0
            if item is not None and not item.is_tombstone():
                await ctx.garage.k2v_rpc.insert(
                    ctx.bucket_id, q["partition_key"], q["start"],
                    item.causal_context(), None)
                deleted = 1
        else:
            # drain the whole range in pages — a silent cap would
            # report success while leaving items behind
            deleted = 0
            page = dict(q)
            while True:
                items = await _range_items(ctx, page, MAX_LIMIT)
                batch = [(q["partition_key"], i.sort_key_str,
                          i.causal_context(), None)
                         for i in items if not i.is_tombstone()]
                if batch:
                    await ctx.garage.k2v_rpc.insert_batch(ctx.bucket_id,
                                                          batch)
                deleted += len(batch)
                if len(items) < MAX_LIMIT:
                    break
                page["start"] = items[-1].sort_key_str + "\x00"
        results.append({
            "partitionKey": q["partition_key"], "prefix": q["prefix"],
            "start": q["start"], "end": q["end"],
            "singleItem": q["single_item"], "deletedItems": deleted,
        })
    return Response(200, [("content-type", "application/json")],
                    json.dumps(results).encode())
