"""Minimal asyncio HTTP/1.1 server for the API frontends.

Ref parity: src/api/common/generic_server.rs:48-330 (there: hyper). No
third-party HTTP dependency: requests are parsed from the stream, bodies
are exposed as a bounded async reader (content-length or chunked), and
responses stream either bytes or an async byte-chunk generator.
Keep-alive and graceful shutdown (drain live connections) included.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import AsyncIterator, Callable, Optional
from urllib.parse import unquote_plus

log = logging.getLogger("garage_tpu.api.http")

MAX_HEADER_BYTES = 64 * 1024
MAX_LINE = 16 * 1024


class HttpError(Exception):
    def __init__(self, status: int, reason: str = ""):
        self.status = status
        self.reason = reason or STATUS_REASONS.get(status, "Error")
        super().__init__(f"{status} {self.reason}")


STATUS_REASONS = {
    100: "Continue", 200: "OK", 204: "No Content", 206: "Partial Content",
    301: "Moved Permanently", 304: "Not Modified", 307: "Temporary Redirect",
    400: "Bad Request", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 409: "Conflict", 411: "Length Required",
    412: "Precondition Failed", 413: "Payload Too Large",
    416: "Range Not Satisfiable", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
}


class BodyReader:
    """Bounded body reader over the connection stream."""

    def __init__(self, reader: asyncio.StreamReader,
                 content_length: Optional[int], chunked: bool):
        self.r = reader
        self.remaining = content_length
        self.chunked = chunked
        self._chunk_left = 0
        self._done = content_length == 0 and not chunked

    async def read(self, n: int = 65536) -> bytes:
        """Next ≤ n body bytes; b"" at end."""
        if self._done:
            return b""
        if self.chunked:
            return await self._read_chunked(n)
        take = min(n, self.remaining)
        data = await self.r.read(take)
        if not data:
            raise HttpError(400, "truncated body")
        self.remaining -= len(data)
        if self.remaining == 0:
            self._done = True
        return data

    async def _read_chunked(self, n: int) -> bytes:
        if self._chunk_left == 0:
            line = await self.r.readline()
            if not line:
                raise HttpError(400, "truncated chunked body")
            try:
                size = int(line.split(b";")[0].strip(), 16)
            except ValueError:
                raise HttpError(400, "bad chunk size")
            if size == 0:
                # trailers until blank line
                while True:
                    t = await self.r.readline()
                    if t in (b"\r\n", b"\n", b""):
                        break
                self._done = True
                return b""
            self._chunk_left = size
        data = await self.r.read(min(n, self._chunk_left))
        if not data:
            raise HttpError(400, "truncated chunk")
        self._chunk_left -= len(data)
        if self._chunk_left == 0:
            await self.r.readexactly(2)  # CRLF
        return data

    async def readinto1(self, mv: memoryview) -> int:
        """One read landed directly into `mv` (a leased ingest-buffer
        slice, ISSUE 17); -> bytes written, 0 at end of body. asyncio's
        StreamReader has no recv_into, so the socket bytes materialize
        once in read() — the copy into `mv` here is the PUT path's ONE
        allowed materialization (counted under s3_put_copy_bytes
        path="ingest"); everything downstream reads views over the
        same buffer."""
        chunk = await self.read(len(mv))
        n = len(chunk)
        if n:
            mv[:n] = chunk
            from ..utils.metrics import registry

            registry().inc("s3_put_copy_bytes", n, path="ingest")
        return n

    async def read_all(self, limit: int = 1 << 30) -> bytes:
        out = bytearray()
        while True:
            chunk = await self.read()
            if not chunk:
                return bytes(out)
            out.extend(chunk)
            if len(out) > limit:
                raise HttpError(413)

    async def drain(self) -> None:
        try:
            while await self.read(1 << 20):
                pass
        except HttpError:
            pass


class Request:
    __slots__ = ("method", "raw_path", "raw_query", "path", "query",
                 "headers", "body", "peer", "version")

    def __init__(self, method: str, raw_path: str, raw_query: str, path: str,
                 query: dict[str, str], headers: dict[str, str],
                 body: BodyReader, peer, version: str):
        self.method = method
        self.raw_path = raw_path  # undecoded path, needed for SigV4
        self.raw_query = raw_query  # undecoded query string, for SigV4
        self.path = path
        self.query = query  # decoded; empty-valued keys present as ""
        self.headers = headers  # lowercased names
        self.body = body
        self.peer = peer
        self.version = version

    def header(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self.headers.get(name.lower(), default)


class Response:
    def __init__(self, status: int = 200,
                 headers: Optional[list[tuple[str, str]]] = None,
                 body: bytes | AsyncIterator[bytes] = b""):
        self.status = status
        self.headers = headers or []
        self.body = body


def parse_query(qs: str) -> tuple[dict[str, str], list[tuple[str, str]]]:
    """-> (decoded dict, raw pair list in order). Keys with no '=' map
    to ""."""
    d: dict[str, str] = {}
    raw: list[tuple[str, str]] = []
    if not qs:
        return d, raw
    for part in qs.split("&"):
        if not part:
            continue
        if "=" in part:
            k, _, v = part.partition("=")
        else:
            k, v = part, ""
        raw.append((k, v))
        d[unquote_plus(k)] = unquote_plus(v)
    return d, raw


async def read_request(reader: asyncio.StreamReader,
                       peer) -> Optional[Request]:
    """Parse one request head; None on clean EOF."""
    try:
        line = await reader.readline()
    except (ConnectionError, asyncio.IncompleteReadError):
        return None
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise HttpError(400, "request line too long")
    try:
        method, target, version = line.decode("ascii").split()
    except ValueError:
        raise HttpError(400, "malformed request line")
    headers: dict[str, str] = {}
    total = 0
    while True:
        h = await reader.readline()
        total += len(h)
        if total > MAX_HEADER_BYTES:
            raise HttpError(400, "headers too large")
        if h in (b"\r\n", b"\n"):
            break
        if not h:
            raise HttpError(400, "truncated headers")
        name, _, value = h.decode("latin-1").partition(":")
        name = name.strip().lower()
        value = value.strip()
        if name in headers:
            headers[name] += "," + value
        else:
            headers[name] = value
    raw_path, _, qs = target.partition("?")
    query, _ = parse_query(qs)
    te = headers.get("transfer-encoding", "").lower()
    chunked = "chunked" in te
    cl = headers.get("content-length")
    clen = int(cl) if cl is not None and not chunked else (None if chunked else 0)
    body = BodyReader(reader, clen, chunked)
    # decode path segments (keep raw for signing)
    from urllib.parse import unquote

    path = unquote(raw_path)
    return Request(method, raw_path, qs, path, query, headers, body, peer,
                   version)


# bytes buffered in the transport before the writer pauses to drain.
# Draining after EVERY chunk costs an await (and often a scheduler trip)
# per block; draining only past a high-water mark keeps the hot GET loop
# on the fast path while still bounding memory to ~one mark per
# connection on top of the transport's own buffer. Runtime-visible via
# admin GET /v1/s3/tuning.
DRAIN_HIGH_WATER = 1 << 20

# coalesce head+body into one transport write below this body size: one
# syscall for the whole response (the common XML/JSON/error case). Large
# bodies are handed to the transport unjoined — no copy.
_COALESCE_MAX = 64 * 1024


async def write_response(writer: asyncio.StreamWriter, req: Optional[Request],
                         resp: Response, keep_alive: bool) -> None:
    head = [f"HTTP/1.1 {resp.status} {STATUS_REASONS.get(resp.status, 'X')}"]
    names = {n.lower() for n, _ in resp.headers}
    body = resp.body
    fixed = isinstance(body, (bytes, bytearray, memoryview))
    # RFC 7230 §3.3.2: a message must not carry both Content-Length and
    # Transfer-Encoding. Streams whose length the handler declared are
    # written with content-length framing; only unknown-length streams
    # get chunked.
    chunked = not fixed and "content-length" not in names
    if fixed and "content-length" not in names:
        resp.headers.append(("content-length", str(len(body))))
    if chunked:
        resp.headers.append(("transfer-encoding", "chunked"))
    if "connection" not in names:
        resp.headers.append(("connection", "keep-alive" if keep_alive else "close"))
    for n, v in resp.headers:
        head.append(f"{n}: {v}")
    head_bytes = ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
    if req is not None and req.method == "HEAD":
        writer.write(head_bytes)
        await writer.drain()
        if not fixed:
            await _aclose_body(body)
        return
    if fixed:
        # zero-copy: bytes-like bodies go to the transport as-is (the
        # old path re-materialized bytes(body), copying every bytearray
        # and memoryview). Small responses coalesce head+body into one
        # write — one packet for the whole response.
        if body and len(body) <= _COALESCE_MAX:
            writer.write(head_bytes + bytes(body))
        else:
            writer.write(head_bytes)
            if body:
                writer.write(body)
        await writer.drain()
        return
    try:
        if chunked:
            pending = len(head_bytes)
            first = True
            async for chunk in body:
                if not chunk:
                    continue
                # head (first time) + chunk-size line coalesce into one
                # small write; the chunk itself is never copied
                frame = b"%x\r\n" % len(chunk)
                writer.write(head_bytes + frame if first else frame)
                first = False
                writer.write(chunk)
                writer.write(b"\r\n")
                pending += len(chunk)
                if pending >= DRAIN_HIGH_WATER:
                    await writer.drain()
                    pending = 0
            writer.write(head_bytes + b"0\r\n\r\n" if first
                         else b"0\r\n\r\n")
            await writer.drain()
        else:
            declared = int(dict((n.lower(), v) for n, v in resp.headers)
                           ["content-length"])
            written = 0
            pending = len(head_bytes)
            first = True
            async for chunk in body:
                if not chunk:
                    continue
                if written + len(chunk) > declared:
                    # never write past the declared boundary: the client
                    # would parse the excess as the next response
                    raise ConnectionError(
                        f"stream exceeds declared {declared} bytes")
                if first:
                    writer.write(head_bytes)
                    first = False
                writer.write(chunk)
                written += len(chunk)
                pending += len(chunk)
                if pending >= DRAIN_HIGH_WATER:
                    await writer.drain()
                    pending = 0
            if first:
                writer.write(head_bytes)
            await writer.drain()
            if written != declared:
                # short stream would desync a keep-alive conn: abort
                raise ConnectionError(
                    f"stream wrote {written} of {declared} declared bytes")
    finally:
        # deterministic generator shutdown: a client disconnect (write
        # raising) or a mid-stream error must cancel the readahead
        # pipeline NOW, not whenever the GC finalizes the generator
        await _aclose_body(body)


async def _aclose_body(body) -> None:
    aclose = getattr(body, "aclose", None)
    if aclose is None:
        return
    try:
        await aclose()
    except Exception as e:
        # the response is already dead; nothing to salvage
        log.debug("body aclose failed: %s", e)


class HttpServer:
    """ref: generic_server.rs ApiServer::run_server."""

    def __init__(self, handler: Callable, name: str = "api"):
        self.handler = handler  # async (Request) -> Response
        self.name = name
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set[asyncio.Task] = set()
        self.bound_port: Optional[int] = None
        self.metrics = {"requests": 0, "errors": 0}

    async def start(self, host: str, port: int,
                    reuse_port: bool = False) -> None:
        # default StreamReader limit is 64 KiB, which caps body reads
        # and costs ~16 loop iterations per 1 MiB block on the PUT path
        #
        # reuse_port=True is the multi-process gateway's accept loop:
        # every worker binds the same port with SO_REUSEPORT and the
        # kernel balances incoming connections across them (the
        # nginx/Envoy worker model; gateway/worker.py)
        kwargs = {"limit": 1 << 20}
        if reuse_port:
            kwargs["reuse_port"] = True
        self._server = await asyncio.start_server(self._conn, host, port,
                                                  **kwargs)
        self.bound_port = self._server.sockets[0].getsockname()[1]
        log.info("%s server listening on %s:%d", self.name, host, self.bound_port)

    async def start_unix(self, path: str, mode: int = 0o222) -> None:
        """Listen on a Unix-domain socket instead of TCP (ref:
        api/common/generic_server.rs:120-131 — same 0o222 default mode
        as the reference: reachable by anyone who may traverse the
        directory, not readable as a file)."""
        import os as _os
        import stat as _stat

        try:
            st = _os.stat(path)
            if not _stat.S_ISSOCK(st.st_mode):
                # never delete a real file someone pointed the bind at
                raise OSError(f"{path} exists and is not a socket")
            _os.remove(path)  # stale socket from a previous run
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(self._conn, path,
                                                       limit=1 << 20)
        _os.chmod(path, mode)
        self.bound_port = None
        log.info("%s server listening on unix:%s", self.name, path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for t in list(self._conns):
            t.cancel()

    async def _conn(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter) -> None:
        t = asyncio.current_task()
        self._conns.add(t)
        peer = writer.get_extra_info("peername")
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s

            try:
                # response flushes are already whole buffers: never
                # wait out Nagle. A wide receive window keeps 1 MiB
                # PUT bodies flowing while the loop serves other conns.
                sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
                sock.setsockopt(_s.SOL_SOCKET, _s.SO_RCVBUF, 1 << 21)
            except OSError:
                pass  # unix sockets / restricted environments
        try:
            while True:
                try:
                    req = await read_request(reader, peer)
                except HttpError as e:
                    await write_response(
                        writer, None, Response(e.status), False)
                    break
                if req is None:
                    break
                if req.header("expect", "").lower() == "100-continue":
                    writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                    await writer.drain()
                keep = req.header("connection", "").lower() != "close"
                self.metrics["requests"] += 1
                from ..utils.metrics import registry

                from ..utils.tracing import span

                t0 = time.perf_counter()
                try:
                    async with span("http.request", api=self.name,
                                    method=req.method, path=req.path[:128]):
                        resp = await self.handler(req)
                except HttpError as e:
                    resp = Response(e.status, [("content-type", "text/plain")],
                                    e.reason.encode())
                except Exception:
                    log.exception("%s handler error", self.name)
                    self.metrics["errors"] += 1
                    resp = Response(500, [("content-type", "text/plain")],
                                    b"internal error")
                registry().observe(
                    "api_request_duration_seconds",
                    time.perf_counter() - t0,
                    api=self.name,
                    # label cardinality is bounded: arbitrary client
                    # method strings must not grow the registry forever
                    method=(req.method if req.method in (
                        "GET", "HEAD", "PUT", "POST", "DELETE",
                        "OPTIONS") else "OTHER"),
                    status=resp.status // 100 * 100)
                try:
                    await req.body.drain()  # finish consuming the body
                except Exception:
                    keep = False
                try:
                    await write_response(writer, req, resp, keep)
                except (ConnectionError, asyncio.CancelledError):
                    break
                if not keep:
                    break
        finally:
            self._conns.discard(t)
            try:
                writer.close()
            except Exception:
                pass  # lint: ignore[GL05] socket already dead; close is best-effort
