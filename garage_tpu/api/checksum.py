"""Checksum framework: x-amz-checksum-* values over request payloads.

Ref parity: src/api/common/signature/checksum.rs — crc32, crc32c, sha1,
sha256 (md5 is handled separately as the etag). Values travel base64 in
headers/trailers; crc32c (Castagnoli) is table-driven since the stdlib
only ships crc32.
"""

from __future__ import annotations

import base64
import hashlib
import zlib
from typing import Optional

ALGORITHMS = ("crc32", "crc32c", "sha1", "sha256", "crc64nvme")

# pure-Python table fallbacks live with the native kernels so every
# layer shares one implementation (garage_tpu/native)
from ..native import crc32c_py as _crc32c_py
from ..native import crc64nvme_py as _crc64nvme_py


def crc32c(data: bytes, crc: int = 0) -> int:
    """Native slice-by-8 when the library is already loaded (the server
    warms it off-loop at startup; checking `loaded` here never triggers
    a blocking compile on the event loop), else the Python table loop."""
    from .. import native

    if native.loaded():
        return native.crc32c(data, crc)
    return _crc32c_py(data, crc)


def crc64nvme(data: bytes, crc: int = 0) -> int:
    from .. import native

    if native.loaded():
        return native.crc64nvme(data, crc)
    return _crc64nvme_py(data, crc)


class Checksummer:
    """Incrementally computes one named checksum; result is raw bytes."""

    def __init__(self, algo: str):
        if algo not in ALGORITHMS:
            raise ValueError(f"unsupported checksum algorithm {algo!r}")
        self.algo = algo
        if algo == "crc32":
            self._crc = 0
        elif algo in ("crc32c", "crc64nvme"):
            self._crc = 0
        else:
            self._h = hashlib.new(algo)

    def update(self, data: bytes) -> None:
        if self.algo == "crc32":
            self._crc = zlib.crc32(data, self._crc)
        elif self.algo == "crc32c":
            self._crc = crc32c(data, self._crc)
        elif self.algo == "crc64nvme":
            self._crc = crc64nvme(data, self._crc)
        else:
            self._h.update(data)

    def digest(self) -> bytes:
        if self.algo == "crc32":
            return self._crc.to_bytes(4, "big")
        if self.algo == "crc32c":
            return self._crc.to_bytes(4, "big")
        if self.algo == "crc64nvme":
            return self._crc.to_bytes(8, "big")
        return self._h.digest()

    def b64(self) -> str:
        return base64.b64encode(self.digest()).decode()


def header_algorithm(header_name: str) -> Optional[str]:
    """"x-amz-checksum-crc32" -> "crc32" (None if not a checksum hdr)."""
    prefix = "x-amz-checksum-"
    name = header_name.lower()
    if name.startswith(prefix) and name[len(prefix):] in ALGORITHMS:
        return name[len(prefix):]
    return None


def request_checksum_value(headers: dict[str, str]) -> Optional[tuple[str, str]]:
    """-> (algo, base64 value) from x-amz-checksum-* headers; raises on
    multiple (ref: checksum.rs request_checksum_value)."""
    found = [(a, v) for h, v in headers.items()
             if (a := header_algorithm(h)) is not None]
    if not found:
        return None
    if len(found) > 1:
        raise ValueError("multiple x-amz-checksum-* headers")
    return found[0]


def trailer_algorithm(headers: dict[str, str]) -> Optional[str]:
    """Algorithm named by the x-amz-trailer header, if any."""
    t = headers.get("x-amz-trailer")
    if not t:
        return None
    algo = header_algorithm(t.strip())
    if algo is None:
        raise ValueError(f"unsupported x-amz-trailer {t!r}")
    return algo
