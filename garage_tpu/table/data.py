"""TableData: node-local table storage with CRDT merge-on-write.

Ref parity: src/table/data.rs. Every mutation happens inside a db
transaction: decode incoming entry, merge with what's stored, run the
schema's `updated()` trigger, append the row to the Merkle todo queue,
and (for tombstones, on the partition leader) enqueue a GC entry.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Iterator, Optional

from ..db import Db, TxAbort
from ..utils.data import blake2sum
from .replication import TableReplication
from .schema import Entry, TableSchema, partition_hash, tree_key

log = logging.getLogger("garage_tpu.table.data")


def _prefix_upper_bound(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every string with this prefix,
    or None if the prefix is all 0xFF (no upper bound)."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return None


class TableData:
    def __init__(self, db: Db, schema: TableSchema, replication: TableReplication,
                 system_id: bytes):
        self.db = db
        self.schema = schema
        self.replication = replication
        self.system_id = system_id
        name = schema.TABLE_NAME
        self.name = name
        self.store = db.open_tree(f"table:{name}")
        self.merkle_todo = db.open_tree(f"{name}:merkle_todo")
        self.merkle_tree = db.open_tree(f"{name}:merkle_tree")
        self.gc_todo = db.open_tree(f"{name}:gc_todo")
        self.insert_queue = db.open_tree(f"{name}:insert_queue")
        self.merkle_todo_notify = threading.Event()
        self.insert_queue_notify = threading.Event()
        from .gc import TABLE_GC_DELAY

        self.gc_delay = TABLE_GC_DELAY  # tunable (tests, config)
        # listeners called (outside the tx) after local changes; used by
        # k2v-style subscriptions and tests
        self.changed_hooks: list[Callable[[Entry], None]] = []
        # table_size accounting (see size_bytes)
        self._bytes_base: Optional[int] = None
        self._bytes_delta = 0

    # ---- reads ---------------------------------------------------------

    def read_entry(self, pk: bytes, sk: bytes) -> Optional[bytes]:
        return self.store.get(tree_key(pk, sk))

    def decode_stored(self, raw: bytes) -> Entry:
        return self.schema.decode_entry(raw)

    def read_range(self, pk: bytes, start_sk: Optional[bytes], flt,
                   limit: int, reverse: bool = False,
                   prefix_sk: Optional[bytes] = None,
                   end_sk: Optional[bytes] = None) -> list[bytes]:
        """Rows of one partition key, from start_sk, filtered, ≤ limit.
        `prefix_sk` bounds both ends to sort keys with that prefix (so a
        reverse scan without an explicit start begins at the prefix's
        upper bound, not at it); `end_sk` is an exclusive stop bound.
        ref: table/data.rs read_range + k2v range semantics.

        Streams from a BOUNDED cursor (ISSUE 7): the engine is asked for
        at most ~limit rows per batch, never the whole partition tail —
        on a bucket with a million keys the old unbounded iter()
        materialized every row after the start key just to return the
        first page."""
        lo, hi, prefix = self._range_bounds(pk, start_sk, reverse,
                                            prefix_sk, end_sk)
        out = []
        while len(out) < limit:
            # filtered scans over-fetch a little so sparse matches don't
            # degenerate into per-row engine calls
            want = (limit - len(out)) if flt is None \
                else max(limit - len(out), 64)
            if not reverse:
                batch = list(self.store.iter(start=lo, end=hi,
                                             limit=want))
            else:
                batch = list(self.store.iter(start=lo, end=hi,
                                             reverse=True, limit=want))
            for k, v in batch:
                if not k.startswith(prefix):
                    return out
                if flt is None:
                    # unfiltered pages skip the per-row decode entirely
                    out.append(v)
                elif self.schema.matches_filter(
                        self.schema.decode_entry(v), flt):
                    out.append(v)
                if len(out) >= limit:
                    return out
            if len(batch) < want:
                break
            if not reverse:
                lo = batch[-1][0] + b"\x00"
            else:
                hi = batch[-1][0]
        return out

    def _range_bounds(self, pk: bytes, start_sk: Optional[bytes],
                      reverse: bool, prefix_sk: Optional[bytes],
                      end_sk: Optional[bytes]
                      ) -> tuple[bytes, Optional[bytes], bytes]:
        """(lo, hi, partition prefix) engine bounds shared by
        read_range and read_range_raw."""
        prefix = tree_key(pk, b"")
        part_end = _prefix_upper_bound(prefix)
        lo, hi = prefix, part_end
        if prefix_sk is not None:
            lo = tree_key(pk, prefix_sk)
            hi = _prefix_upper_bound(lo) or part_end
        if not reverse:
            if start_sk is not None:
                lo = max(lo, tree_key(pk, start_sk))
            if end_sk is not None:
                hi = min(hi, tree_key(pk, end_sk))
        else:
            # reverse: start_sk = inclusive upper start; end_sk =
            # exclusive lower stop (keys must stay > end_sk)
            if start_sk is not None:
                hi = min(hi, tree_key(pk, start_sk) + b"\x00")
            if end_sk is not None:
                lo = max(lo, tree_key(pk, end_sk) + b"\x00")
        return lo, hi, prefix

    def read_range_raw(self, pk: bytes, start_sk: Optional[bytes],
                       limit: int, prefix_sk: Optional[bytes] = None,
                       end_sk: Optional[bytes] = None
                       ) -> tuple[list[tuple[bytes, bytes]],
                                  Optional[bytes]]:
        """Raw-cursor page (ISSUE 9): up to `limit` (sort_key, raw row)
        pairs of one partition, forward order, NO per-row decode — the
        sort key comes straight off the engine key, so callers that
        page a range (k2v poll_range) advance their cursor without
        decoding a single row they end up skipping. Returns
        (rows, next_start_sk): next_start_sk is the sort key to resume
        AFTER the last returned row, or None when the range is
        exhausted."""
        lo, hi, prefix = self._range_bounds(pk, start_sk, False,
                                            prefix_sk, end_sk)
        rows: list[tuple[bytes, bytes]] = []
        plen = len(prefix)
        while len(rows) < limit:
            want = limit - len(rows)
            batch = list(self.store.iter(start=lo, end=hi, limit=want))
            for k, v in batch:
                if not k.startswith(prefix):
                    return rows, None
                rows.append((k[plen:], v))
            if len(batch) < want:
                return rows, None
            lo = batch[-1][0] + b"\x00"
        return rows, (rows[-1][0] + b"\x00" if rows else None)

    def iter_all(self) -> Iterator[tuple[bytes, bytes]]:
        return self.store.iter()

    # ---- writes --------------------------------------------------------

    def update_entry(self, raw: bytes) -> Optional[Entry]:
        """Merge one incoming encoded entry; returns the new merged entry
        if the stored value changed, else None. ref: data.rs:178-268."""
        entry = self.schema.decode_entry(raw)
        return self.update_entry_decoded(entry)

    def update_entry_decoded(self, entry: Entry) -> Optional[Entry]:
        return self._apply_row(
            entry.partition_key(), entry.sort_key(),
            lambda tx, old: old.merge(entry) if old is not None else entry,
        )

    def update_entry_with(self, pk: bytes, sk: bytes, fn) -> Optional[Entry]:
        """Read-modify-write one row inside a single transaction with the
        full trigger/merkle path: `fn(tx, old_entry_or_None) -> Entry`.
        ref: table/data.rs update_entry_with (K2V's local insert uses it
        so the DVVS update + local-timestamp bump commit atomically)."""
        return self._apply_row(pk, sk, fn)

    def _apply_row(self, pk: bytes, sk: bytes, produce) -> Optional[Entry]:
        """The one commit path for local row changes:
        `produce(tx, old_or_None) -> new` runs inside the transaction,
        then store write + merkle todo + updated() trigger + gc todo +
        changed hooks. `produce` MAY mutate the decoded old entry and
        return it — the trigger's `old` is re-decoded from the stored
        bytes so counter deltas never alias old and new."""
        new = self.db.transaction(
            lambda tx: self._apply_row_in(tx, pk, sk, produce))
        self._after_commit([new])
        return new

    def _apply_row_in(self, tx, pk: bytes, sk: bytes,
                      produce) -> Optional[Entry]:
        k = tree_key(pk, sk)
        old_raw = tx.get(self.store, k)
        old_for_fn = (self.schema.decode_entry(old_raw)
                      if old_raw is not None else None)
        new = produce(tx, old_for_fn)
        new_raw = self.schema.encode_entry(new)
        if old_raw == new_raw:
            return None
        old = (self.schema.decode_entry(old_raw)
               if old_raw is not None else None)
        tx.insert(self.store, k, new_raw)
        delta = len(new_raw) - (len(old_raw) if old_raw is not None
                                else -len(k))
        tx.on_commit(lambda: self._apply_bytes_delta(delta))
        tx.insert(self.merkle_todo, k, blake2sum(new_raw))
        self.schema.updated(tx, old, new)
        self._maybe_gc_todo(tx, new, k, new_raw)
        return new

    def _after_commit(self, news: list) -> None:
        for new in news:
            if new is None:
                continue
            self.merkle_todo_notify.set()
            for h in self.changed_hooks:
                try:
                    h(new)
                except Exception:
                    log.exception("changed hook failed")

    # entries per transaction in update_many: each row is ~4 tiny
    # statements, so per-row BEGIN/COMMIT dominated the replica write
    # path (quorum "update" RPC, anti-entropy push, queue flush) under
    # PUT load; 32 amortize it while bounding db-lock hold time
    _UPDATE_TX_STEP = 32

    def update_many(self, raws: list[bytes]) -> int:
        n = 0
        for i in range(0, len(raws), self._UPDATE_TX_STEP):
            chunk = raws[i:i + self._UPDATE_TX_STEP]

            def body(tx, chunk=chunk):
                out = []
                for raw in chunk:
                    entry = self.schema.decode_entry(raw)
                    out.append(self._apply_row_in(
                        tx, entry.partition_key(), entry.sort_key(),
                        lambda t, old, e=entry:
                            old.merge(e) if old is not None else e))
                return out

            news = self.db.transaction(body)
            self._after_commit(news)
            n += sum(1 for x in news if x is not None)
        return n

    def _maybe_gc_todo(self, tx, new: Entry, k: bytes,
                       new_raw: bytes) -> None:
        """Tombstones get a GC-todo entry on the partition leader
        (ref: data.rs:242-257)."""
        if not new.is_tombstone():
            return
        ph = partition_hash(new.partition_key())
        nodes = self.replication.storage_nodes(ph)
        if nodes and nodes[0] == self.system_id:
            from .gc import GcTodoEntry

            GcTodoEntry.new(k, blake2sum(new_raw), delay=self.gc_delay).save(
                tx, self.gc_todo
            )

    def delete_if_equal_hash(self, k: bytes, vhash: bytes) -> bool:
        """Remove row k only if its stored encoding hashes to vhash
        (phase 3 of GC; ref: data.rs:280-310)."""

        def body(tx):
            cur = tx.get(self.store, k)
            if cur is None or blake2sum(cur) != vhash:
                return False
            old = self.schema.decode_entry(cur)
            tx.remove(self.store, k)
            freed = len(cur) + len(k)
            tx.on_commit(lambda: self._apply_bytes_delta(-freed))
            tx.insert(self.merkle_todo, k, b"")
            self.schema.updated(tx, old, None)
            return True

        changed = self.db.transaction(body)
        if changed:
            self.merkle_todo_notify.set()
        return changed

    # ---- async insert queue (ref: table/queue.rs) ----------------------

    def queue_insert(self, tx, entry: Entry) -> None:
        """Enqueue an entry for asynchronous insertion via the normal
        quorum path; called from inside `updated()` triggers so the
        enqueue commits atomically with the triggering write. Keyed by
        the full row key; a second enqueue for the same row CRDT-merges
        into the pending one (ref: data.rs:322-336)."""
        k = tree_key(entry.partition_key(), entry.sort_key())
        cur = tx.get(self.insert_queue, k)
        if cur is not None:
            entry = self.schema.decode_entry(cur).merge(entry)
        tx.insert(self.insert_queue, k, self.schema.encode_entry(entry))
        tx.on_commit(self.insert_queue_notify.set)

    # ---- stats ---------------------------------------------------------

    def stats(self) -> dict:
        return {
            "rows": len(self.store),
            "merkle_todo": len(self.merkle_todo),
            "gc_todo": len(self.gc_todo),
            "insert_queue": len(self.insert_queue),
        }

    def _apply_bytes_delta(self, delta: int) -> None:
        # on_commit only: a rolled-back tx must not skew the metric
        self._bytes_delta += delta

    def size_bytes(self) -> int:
        """Approximate stored bytes (keys + encoded rows) for the
        table_size metric family (ref: table/metrics.rs:132 table_size).
        Baseline is computed by one scan on first call; afterwards the
        commit paths maintain an incremental delta via on_commit."""
        if self._bytes_base is None:
            # batched cursor walk — a single full scan would hold the
            # db lock (and materialize the whole table) for its whole
            # duration. Consistency: if any commit lands mid-scan (the
            # delta moved), retry once; a second dirty pass settles for
            # the approximation (the metric is approximate by design).
            for _attempt in range(2):
                d0 = self._bytes_delta
                base = 0
                cursor = None
                while True:
                    batch = list(self.store.iter(start=cursor, limit=4096))
                    for k, v in batch:
                        base += len(k) + len(v)
                    if len(batch) < 4096:
                        break
                    cursor = batch[-1][0] + b"\x00"
                if self._bytes_delta == d0:
                    break
            self._bytes_base = base - d0
        return self._bytes_base + self._bytes_delta
