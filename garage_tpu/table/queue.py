"""Async insert queue: local writes that propagate via normal quorum path.

Ref parity: src/table/queue.rs:1-77. Triggers (`TableSchema.updated`)
often need to insert into *other* tables; doing a quorum RPC inside a db
transaction would deadlock, so they enqueue locally (atomic with the
triggering commit) and this worker drains the queue through
`Table.insert_many` in batches, removing entries only if unchanged.
"""

from __future__ import annotations

import asyncio
import logging

from ..utils.background import Worker, WState

log = logging.getLogger("garage_tpu.table.queue")

BATCH_SIZE = 1024


class InsertQueueWorker(Worker):
    def __init__(self, table):
        self.table = table
        self.data = table.data
        self.name = f"{table.name} queue"

    async def work(self):
        batch = await asyncio.to_thread(
            lambda: list(self.data.insert_queue.iter())[:BATCH_SIZE])
        if not batch:
            return WState.IDLE
        await self.table.propagate_queue_batch(batch)
        return WState.BUSY

    async def wait_for_work(self):
        while not len(self.data.insert_queue):
            await asyncio.sleep(0.1)

    def info(self):
        from ..utils.background import WorkerInfo

        return WorkerInfo(name=self.name,
                          queue_length=len(self.data.insert_queue))
