"""Anti-entropy: Merkle-tree sync between partition replicas.

Ref parity: src/table/sync.rs. Every ANTI_ENTROPY_INTERVAL (and after a
layout change), each partition this node stores is compared with the
other replicas: exchange root checksums, recursively descend differing
trie nodes, push missing/newer items. Partitions this node no longer
stores are offloaded (send everything to the new owners, then delete
locally). Completion of a sync round for a layout version reports
`sync_table_until` so the layout's sync trackers advance and old
versions can be garbage-collected.

RPC ops on endpoint "garage_tpu/table_sync:{name}":
  {op: "root_ck", partition}            -> {hash}
  {op: "get_node", partition, prefix}   -> {node}   (packed MerkleNode)
  {op: "items", entries: [raw..]}       -> {ok}
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..net.message import PRIO_BACKGROUND
from ..utils.background import Worker, WState
from .merkle import INTERMEDIATE, LEAF, MerkleNode

log = logging.getLogger("garage_tpu.table.sync")

ANTI_ENTROPY_INTERVAL = 600.0
FAILED_ROUND_RETRY = 5.0  # a partial round blocks layout-sync progress


class TableSyncer(Worker):
    def __init__(self, table, interval: float = ANTI_ENTROPY_INTERVAL):
        self.table = table
        self.data = table.data
        self.merkle = table.merkle
        self.name = f"{table.name} sync"
        self.interval = interval
        self.endpoint = table.system.netapp.endpoint(
            f"garage_tpu/table_sync:{table.name}"
        ).set_handler(self._handle)
        self._last_sync = 0.0
        self._layout_version = None
        self.rounds_done = 0
        self._fail_streak = 0
        # seconds slept between partitions during a round; the qos
        # governor maps its pressure onto this during a rebalance so
        # anti-entropy storms yield to foreground p99 (resize: a layout
        # change triggers a round of EVERY table on EVERY node at once)
        self.tranquility = 0.0
        # one sync source per table: the node's layout sync tracker
        # advances at the minimum across every registered layer
        self._sync_source = f"table:{table.name}"
        lm = getattr(table.system, "layout_manager", None)
        if lm is not None:
            lm.register_sync_source(self._sync_source)

    # ---- worker --------------------------------------------------------

    async def work(self):
        # trigger on the layout VERSION, not the full gossip digest:
        # the digest covers the CRDT ack/sync trackers, which tick on
        # every gossip round of a layout transition — digest-triggered
        # rounds made every syncer on every node re-walk all 256
        # partitions continuously for the whole transition window
        # (measured as the dominant foreground-p99 cost of a resize)
        version = self.table.system.layout_helper.current().version
        due = (
            time.monotonic() - self._last_sync >= self.interval
            or version != self._layout_version
        )
        if not due:
            return WState.IDLE
        self._layout_version = version
        all_ok = await self.sync_all_partitions()
        self.rounds_done += 1
        if all_ok:
            # lint: ignore[GL12] one syncer worker per table owns _last_sync; BackgroundRunner serializes a worker's work() frames
            self._last_sync = time.monotonic()
            self._fail_streak = 0
        else:
            # a failed round never reported sync_until_from, and with
            # the digest already recorded nothing would retry it until
            # the 600 s interval — mid-resize that wedges the whole
            # cluster's sync convergence on one dropped RPC. Retry soon,
            # but back off exponentially toward the full interval: a
            # peer that stays down for an hour must not cost every
            # replica a doomed root_ck RPC per partition every 5 s.
            retry = min(self.interval,
                        FAILED_ROUND_RETRY * (2 ** self._fail_streak))
            self._fail_streak += 1
            self._last_sync = time.monotonic() - self.interval + retry
        return WState.IDLE

    async def wait_for_work(self):
        await asyncio.sleep(1.0)

    def add_full_sync(self) -> None:
        """Force a full anti-entropy round on the next worker tick
        (ref: table/sync.rs add_full_sync, CLI `repair tables`)."""
        self._last_sync = 0.0

    async def sync_all_partitions(self) -> bool:
        me = self.table.system.id
        # pin the version we're syncing against BEFORE the round; a layout
        # change mid-round must not get credit for this round's work
        round_version = self.table.system.layout_helper.current().version
        all_ok = True
        for sp in self.table.replication.sync_partitions():
            if self.tranquility > 0:
                # governed yield: background anti-entropy paces itself
                # so foreground requests interleave
                await asyncio.sleep(self.tranquility)
            stored_here = any(me in s for s in sp.storage_sets)
            try:
                if stored_here:
                    for s in sp.storage_sets:
                        for peer in s:
                            if peer != me:
                                await self.sync_partition_with(sp.partition, peer)
                else:
                    await self.offload_partition(sp)
            except Exception as e:
                all_ok = False
                log.info("%s: sync partition %d failed: %s",
                         self.name, sp.partition, e)
        # advance the sync tracker ONLY on a fully clean round — a partial
        # round must not let the cluster GC a layout version whose
        # replicas never received their data (ref: sync.rs:520-567)
        lm = getattr(self.table.system, "layout_manager", None)
        if all_ok and lm is not None:
            lm.sync_until_from(self._sync_source, round_version)
        return all_ok

    # ---- pairwise merkle sync (push) -----------------------------------

    async def sync_partition_with(self, partition: int, peer: bytes) -> None:
        """Push items the peer is missing/behind on (ref: sync.rs:275-405)."""
        empty, my_root = await asyncio.to_thread(
            lambda: (self.merkle.read_node(partition, b"").is_empty(),
                     self.merkle.root_hash(partition)))
        if empty:
            # nothing to push from an empty partition — and sync is
            # push-based, so the peer's own round covers the reverse
            # direction. With 256 partitions x every table x every
            # node re-walked on each layout change, skipping the empty
            # ones is the difference between a resize round of ~10^2
            # and ~10^5 RPCs on a sparse table.
            return
        resp = await self.endpoint.call(
            peer, {"op": "root_ck", "partition": partition}, PRIO_BACKGROUND
        )
        their_root = resp[0]["hash"]
        if their_root == my_root:
            return
        await self._descend(partition, b"", peer)

    async def _descend(self, partition: int, prefix: bytes, peer: bytes) -> None:
        mine = await asyncio.to_thread(self.merkle.read_node,
                                       partition, prefix)
        if mine.is_empty():
            return
        resp = await self.endpoint.call(
            peer, {"op": "get_node", "partition": partition, "prefix": prefix},
            PRIO_BACKGROUND,
        )
        theirs = MerkleNode.unpack(resp[0]["node"])
        if mine.node_hash() == theirs.node_hash():
            return
        if mine.kind != INTERMEDIATE:  # LEAF: push the single item
            await self._push_items_under(partition, prefix, peer)
            return
        if theirs.kind == LEAF or theirs.is_empty():
            # they have at most one item under this prefix: push subtree
            await self._push_items_under(partition, prefix, peer)
            return
        for byte, child_hash in mine.children:
            if theirs.child(byte) != child_hash:
                await self._descend(partition, prefix + bytes([byte]), peer)

    async def _push_items_under(self, partition: int, prefix: bytes,
                                peer: bytes) -> None:
        """Push every row under a trie prefix; the trie's own leaves
        enumerate them (ref: sync.rs walks the merkle subtree)."""
        def read_rows():
            row_keys = self.merkle.leaf_rows(partition, prefix)
            return [v for v in (self.data.store.get(k)
                                for k in row_keys)
                    if v is not None]

        items = await asyncio.to_thread(read_rows)
        for i in range(0, len(items), 64):
            await self.endpoint.call(
                peer, {"op": "items", "entries": items[i:i + 64]},
                PRIO_BACKGROUND,
            )

    # ---- offload (ref: sync.rs:164-265) --------------------------------

    async def offload_partition(self, sp) -> None:
        """This node no longer stores sp: push everything to the new
        owners, then delete locally."""
        me = self.table.system.id
        new_owners = [n for s in sp.storage_sets for n in s if n != me]
        if not new_owners:
            return
        while True:
            batch = await asyncio.to_thread(self._partition_rows, sp, 256)
            if not batch:
                return
            keys, vals = zip(*batch)
            for peer in dict.fromkeys(new_owners):
                await self.endpoint.call(
                    peer, {"op": "items", "entries": list(vals)},
                    PRIO_BACKGROUND,
                )
            # delete only rows unchanged since we read them
            def body(tx):
                freed = 0
                for k, v in batch:
                    if tx.get(self.data.store, k) == v:
                        tx.remove(self.data.store, k)
                        tx.insert(self.data.merkle_todo, k, b"")
                        freed += len(k) + len(v)
                if freed:
                    tx.on_commit(
                        lambda: self.data._apply_bytes_delta(-freed))

            await asyncio.to_thread(self.data.db.transaction, body)
            self.data.merkle_todo_notify.set()

    def _partition_rows(self, sp, limit: int) -> list[tuple[bytes, bytes]]:
        out = []
        for k, v in self.data.store.iter(start=sp.first_hash):
            if self.data.replication.partition_of(k[:32]) != sp.partition:
                break
            out.append((k, v))
            if len(out) >= limit:
                break
        return out

    # ---- server --------------------------------------------------------

    async def _handle(self, from_node: bytes, payload, stream):
        op = payload["op"]
        if op == "root_ck":
            h = await asyncio.to_thread(self.merkle.root_hash,
                                        payload["partition"])
            return {"hash": h}
        if op == "get_node":
            n = await asyncio.to_thread(self.merkle.read_node,
                                        payload["partition"],
                                        payload["prefix"])
            return {"node": n.pack()}
        if op == "items":
            await asyncio.to_thread(self.data.update_many, payload["entries"])
            return {"ok": True}
        raise ValueError(f"unknown sync op {op!r}")
