"""Table: quorum-replicated CRDT table operations.

Ref parity: src/table/table.rs. insert/insert_many write encoded entries
to every live write set with per-set quorum (layout transitions are
covered by writing to old+new sets under the ack lock); get/get_range
read-quorum from the ring, CRDT-merge the responses, and schedule a
background read-repair when replicas disagree.

RPC ops (payload dicts on endpoint "garage_tpu/table:{name}"):
  {op: "update", entries: [raw,..]}
  {op: "read_entry", pk, sk}
  {op: "read_range", pk, start_sk, limit, reverse}
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ..net.message import PRIO_NORMAL
from ..rpc.rpc_helper import RequestStrategy, RpcHelper
from ..utils.error import QuorumError
from .data import TableData
from .merkle import MerkleUpdater
from .replication import TableFullReplication, TableReplication
from .schema import Entry, TableSchema, partition_hash

log = logging.getLogger("garage_tpu.table")

# gateway-node control-table read cache: staleness bound (seconds) for
# full-copy rows read over RPC by a node that holds no local copy. The
# bound mirrors what storage nodes already tolerate (anti-entropy lag);
# a worker's OWN writes invalidate immediately.
GATEWAY_READ_TTL = 2.0
GATEWAY_READ_CACHE_MAX = 1024

_MISS = object()


class Table:
    def __init__(self, schema: TableSchema, replication: TableReplication,
                 rpc_helper: RpcHelper, db):
        self.schema = schema
        self.replication = replication
        self.rpc = rpc_helper
        self.system = rpc_helper.system
        self.data = TableData(db, schema, replication, self.system.id)
        self.merkle = MerkleUpdater(self.data)
        self.name = schema.TABLE_NAME
        self.endpoint = self.system.netapp.endpoint(
            f"garage_tpu/table:{self.name}"
        ).set_handler(self._handle)
        # background read-repair tasks (kept so tests/shutdown can drain)
        self._repairs: set[asyncio.Task] = set()
        # (pk, sk) -> (expiry, entry|None); only populated on gateway
        # nodes reading full-copy tables over RPC (see _get_traced)
        self._control_cache: dict[tuple, tuple[float, Optional[Entry]]] \
            = {}
        # (pk, sk) -> monotonic time of this node's last completed
        # write: a read that BEGAN before that write may carry the
        # pre-write row and must not repopulate the cache
        self._control_inval: dict[tuple, float] = {}

    def spawn_workers(self, runner) -> None:
        from .gc import TableGc
        from .queue import InsertQueueWorker
        from .sync import TableSyncer

        self.syncer = TableSyncer(self)
        runner.spawn_worker(self.merkle)
        runner.spawn_worker(self.syncer)
        runner.spawn_worker(TableGc(self))
        runner.spawn_worker(InsertQueueWorker(self))

    # ---- client ops ----------------------------------------------------

    def _control_cache_get(self, key: tuple):
        hit = self._control_cache.get(key)
        if hit is None:
            return _MISS
        expiry, entry = hit
        if expiry < time.monotonic():
            del self._control_cache[key]
            return _MISS
        return entry

    def _control_cache_put(self, key: tuple, entry: Optional[Entry],
                           read_started: float) -> None:
        if self._control_inval.get(key, -1.0) >= read_started:
            # this node completed a write to the key after the read
            # began: the fetched row may predate the write — caching it
            # would break read-your-writes for a full TTL
            return
        if len(self._control_cache) >= GATEWAY_READ_CACHE_MAX:
            # wholesale reset beats tracking LRU for a cache this small
            # and this short-lived
            self._control_cache.clear()
        self._control_cache[key] = (time.monotonic() + GATEWAY_READ_TTL,
                                    entry)

    def _control_invalidate(self, key: tuple) -> None:
        # fencing only exists where the cache does: gateway nodes
        # reading a full-copy table over RPC. Everywhere else —
        # including the hot sharded-table bulk-insert path and storage
        # nodes' control writes — this must stay O(1) and must not
        # accumulate state (an unconditional record would grow the
        # inval map with every insert and rebuild it per write once
        # past the cap).
        rep = self.replication
        if not isinstance(rep, TableFullReplication) \
                or self.rpc.system.id in rep.storage_nodes(b""):
            return
        self._control_cache.pop(key, None)
        now = time.monotonic()
        if len(self._control_inval) >= GATEWAY_READ_CACHE_MAX:
            # entries only matter for one TTL: prune instead of growing
            self._control_inval = {
                k: t for k, t in self._control_inval.items()
                if t > now - GATEWAY_READ_TTL}
        self._control_inval[key] = now

    async def insert(self, entry: Entry) -> None:
        """ref: table/table.rs:106-144."""
        from ..utils.metrics import registry
        from ..utils.tracing import span

        registry().inc("table_put_total", table=self.name)
        async with span("table.insert", table=self.name):
            await self._insert_traced(entry)
        # read-your-writes through the gateway control cache: this
        # node's own mutation must be visible on its next read. Runs
        # AFTER the quorum write, and also fences concurrent reads
        # that began before it (they must not repopulate the cache
        # with the pre-write row — see _control_cache_put).
        self._control_invalidate(
            (entry.partition_key(), entry.sort_key()))

    async def _insert_traced(self, entry: Entry) -> None:
        raw = self.schema.encode_entry(entry)
        ph = partition_hash(entry.partition_key())
        with self.replication.write_lock():
            sets = self.replication.write_sets(ph)
            # lint: ignore[GL06] write_lock is a layout-version PIN (refcount), not mutual exclusion; holding it across the quorum write IS the union-window contract (manager.rs:344)
            await self.rpc.try_write_many_sets(
                self.endpoint,
                sets,
                {"op": "update", "entries": [raw]},
                RequestStrategy(quorum=self.replication.write_quorum(),
                                prio=PRIO_NORMAL),
            )

    async def insert_many(self, entries: list[Entry]) -> None:
        """Batch insert: one RPC per node carrying all entries destined
        to it; per-write-set quorum accounting (ref: table.rs:164-285)."""
        if not entries:
            return
        with self.replication.write_lock():
            per_node: dict[bytes, list[bytes]] = {}
            all_sets: list[list[bytes]] = []
            seen_sets: set[tuple] = set()
            for e in entries:
                raw = self.schema.encode_entry(e)
                ph = partition_hash(e.partition_key())
                sets = self.replication.write_sets(ph)
                # each entry goes once per node, even when the node sits
                # in several (old+new) write sets (ref: table.rs:198-236)
                dest = {n for s in sets for n in s}
                for s in sets:
                    key = tuple(sorted(s))
                    if key not in seen_sets:
                        seen_sets.add(key)
                        all_sets.append(s)
                for n in dest:
                    per_node.setdefault(n, []).append(raw)
            # lint: ignore[GL06] write_lock is a layout-version PIN (refcount), not mutual exclusion; holding it across the quorum write IS the union-window contract (manager.rs:344)
            await self.rpc.try_write_many_sets(
                self.endpoint,
                all_sets,
                None,
                RequestStrategy(quorum=self.replication.write_quorum(),
                                prio=PRIO_NORMAL),
                make_payload=lambda n: {"op": "update",
                                        "entries": per_node.get(n, [])},
            )
        # after the quorum write, same fencing as insert()
        for e in entries:
            self._control_invalidate(
                (e.partition_key(), e.sort_key()))

    async def get(self, pk: bytes, sk: bytes,
                  consistency=None) -> Optional[Entry]:
        """Read-quorum get with CRDT merge + background read-repair.
        ref: table.rs:287-361.

        `consistency=ConsistencyMode.DEGRADED` (ISSUE 16) is the
        per-request escape hatch for zone partitions: serve from any
        one surviving replica instead of failing the consistent
        quorum. The merge/read-repair machinery still runs on whatever
        replicas answered."""
        from ..utils.metrics import registry
        from ..utils.tracing import span

        registry().inc("table_get_total", table=self.name)
        async with span("table.get", table=self.name):
            return await self._get_traced(pk, sk, consistency)

    async def _get_traced(self, pk: bytes, sk: bytes,
                          consistency=None) -> Optional[Entry]:
        ph = partition_hash(pk)
        nodes = self.replication.read_nodes(ph)
        # Gateway node reading a full-copy (control) table: it holds no
        # local copy, so every auth/bucket resolve would cost an RPC to
        # the holders — on an API worker that is 4+ round-trips per S3
        # request for rows that change rarely. A short-TTL read-through
        # cache bounds staleness to GATEWAY_READ_TTL seconds, the same
        # order as the anti-entropy lag storage nodes already tolerate.
        gateway_remote = (isinstance(self.replication,
                                     TableFullReplication)
                          and self.rpc.system.id not in nodes)
        read_started = time.monotonic()
        if gateway_remote:
            hit = self._control_cache_get((pk, sk))
            if hit is not _MISS:
                return hit
        resps = await self.rpc.try_call_many(
            self.endpoint,
            nodes,
            {"op": "read_entry", "pk": pk, "sk": sk},
            RequestStrategy(quorum=self.replication.read_quorum(),
                            consistency=consistency),
        )
        ret: Optional[Entry] = None
        raws = []
        for r in resps:
            raw = r.get("entry")
            raws.append(raw)
            if raw is not None:
                e = self.schema.decode_entry(raw)
                ret = e if ret is None else ret.merge(e)
        if ret is not None:
            merged_raw = self.schema.encode_entry(ret)
            if any(r != merged_raw for r in raws):
                self._spawn_repair([ret])
        if gateway_remote:
            self._control_cache_put((pk, sk), ret, read_started)
        return ret

    async def get_range(self, pk: bytes, start_sk: Optional[bytes] = None,
                        flt=None, limit: int = 100,
                        reverse: bool = False,
                        prefix_sk: Optional[bytes] = None,
                        end_sk: Optional[bytes] = None,
                        consistency=None) -> list[Entry]:
        """ref: table.rs:363-483. `consistency` as in get()."""
        ph = partition_hash(pk)
        nodes = self.replication.read_nodes(ph)
        resps = await self.rpc.try_call_many(
            self.endpoint,
            nodes,
            {"op": "read_range", "pk": pk, "start_sk": start_sk,
             "limit": limit, "reverse": reverse, "filter": flt,
             "prefix_sk": prefix_sk, "end_sk": end_sk},
            RequestStrategy(quorum=self.replication.read_quorum(),
                            consistency=consistency),
        )
        by_key: dict[tuple, Entry] = {}
        raw_seen: dict[tuple, set] = {}
        appearances: dict[tuple, int] = {}
        for r in resps:
            for raw in r.get("entries", []):
                e = self.schema.decode_entry(raw)
                kk = (e.partition_key(), e.sort_key())
                by_key[kk] = e if kk not in by_key else by_key[kk].merge(e)
                raw_seen.setdefault(kk, set()).add(raw)
                appearances[kk] = appearances.get(kk, 0) + 1
        # repair keys whose replicas returned divergent values or that
        # some replica was missing entirely (ref: table.rs:449-471; the
        # missing-entry check is approximate near the limit boundary,
        # where absence may just mean "past that replica's window")
        to_repair = [
            e for kk, e in by_key.items()
            if len(raw_seen[kk]) > 1 or appearances[kk] < len(resps)
        ]
        if to_repair:
            self._spawn_repair(to_repair)
        out = sorted(by_key.values(),
                     key=lambda e: e.sort_key(), reverse=reverse)
        return out[:limit]

    def _spawn_repair(self, entries: list[Entry]) -> None:
        async def repair():
            try:
                await self.insert_many(entries)
            except Exception as e:
                log.debug("%s read-repair failed: %s", self.name, e)

        t = asyncio.create_task(repair())
        self._repairs.add(t)
        t.add_done_callback(self._repairs.discard)

    # ---- local (trigger-path) ops --------------------------------------

    def queue_insert(self, tx, entry: Entry) -> None:
        self.data.queue_insert(tx, entry)

    def queue_insert_local(self, entry: Entry) -> bytes:
        """Durable local enqueue outside any caller transaction: one
        tiny local tx instead of a quorum RPC (the reference's hot PUT
        path queues version/block_ref rows this way, put.rs:545; the
        InsertQueueWorker batch-propagates with quorum). Returns the
        queue row key so the caller can target its flush."""
        from .schema import tree_key

        self.data.db.transaction(
            lambda tx: self.data.queue_insert(tx, entry))
        return tree_key(entry.partition_key(), entry.sort_key())

    async def propagate_queue_batch(self, batch: list) -> None:
        """One drain step shared by InsertQueueWorker and
        flush_insert_queue: insert_many through the quorum path, then
        remove each queue row only if unchanged (a concurrent enqueue
        CRDT-merges into the pending row; the merged value stays queued
        for the next pass)."""
        entries = [self.schema.decode_entry(v) for _, v in batch]
        await self.insert_many(entries)

        def body(tx):
            for k, v in batch:
                if tx.get(self.data.insert_queue, k) == v:
                    tx.remove(self.data.insert_queue, k)

        await asyncio.to_thread(self.data.db.transaction, body)

    async def flush_insert_queue(self, keys=None) -> None:
        """Quorum-propagate queued rows AS OF NOW — only those whose
        queue key is in `keys` when given (a request flushes ITS rows
        before its final Complete insert, not the whole shared backlog).
        A single snapshot — later enqueues are the next flush's (or the
        worker's) problem, so sustained load cannot starve a caller."""
        from .queue import BATCH_SIZE

        def read_snapshot():
            if keys is None:
                return list(self.data.insert_queue.iter())
            # O(|keys|) lookups, not an O(backlog) scan per request
            return [(k, v) for k in keys
                    if (v := self.data.insert_queue.get(k)) is not None]

        snapshot = await asyncio.to_thread(read_snapshot)
        for i in range(0, len(snapshot), BATCH_SIZE):
            await self.propagate_queue_batch(snapshot[i:i + BATCH_SIZE])

    async def get_local(self, pk: bytes, sk: bytes) -> Optional[Entry]:
        # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
        raw = self.data.read_entry(pk, sk)
        return self.schema.decode_entry(raw) if raw is not None else None

    # ---- server side ---------------------------------------------------

    async def _handle(self, from_node: bytes, payload, stream):
        op = payload["op"]
        if op == "update":
            await asyncio.to_thread(self.data.update_many, payload["entries"])
            return {"ok": True}
        if op == "read_entry":
            # lint: ignore[GL10] measured (ISSUE 9): this single-row page-cached db op costs less than the to_thread handoff it would ride; scans and multi-row transactions do hop
            raw = self.data.read_entry(payload["pk"], payload["sk"])
            return {"entry": raw}
        if op == "read_range":
            entries = await asyncio.to_thread(
                self.data.read_range,
                payload["pk"], payload.get("start_sk"), payload.get("filter"),
                payload.get("limit", 100), payload.get("reverse", False),
                payload.get("prefix_sk"), payload.get("end_sk"),
            )
            return {"entries": entries}
        raise ValueError(f"unknown table op {op!r}")


def queue_insert_local_many(items: list) -> list[bytes]:
    """queue_insert_local for rows spanning TABLES that share one db,
    in a single transaction — the PUT path enqueues a version and a
    block_ref row per block, and one tx instead of two halves the
    BEGIN/COMMIT cost on its hottest metadata step. `items` is
    [(table, entry)]; returns the queue row keys."""
    from .schema import tree_key

    db = items[0][0].data.db

    def body(tx):
        for t, e in items:
            t.data.queue_insert(tx, e)

    db.transaction(body)
    return [tree_key(e.partition_key(), e.sort_key()) for _, e in items]
