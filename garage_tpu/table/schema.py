"""Table schemas: typed CRDT entries keyed by (partition key, sort key).

Ref parity: src/table/schema.rs:71-103. An entry is a CRDT (merge) that
is also Migratable (versioned encoding); the schema binds entry type to
a table name and provides the `updated()` transactional trigger that
propagates changes to other tables (e.g. object -> version -> block_ref).
"""

from __future__ import annotations

from typing import Optional, Type

from ..utils import migrate
from ..utils.data import Hash, blake2sum


class Entry(migrate.Migratable):
    """A table row. Subclasses define partition_key/sort_key and CRDT
    merge; encoding comes from Migratable (pack/unpack + VERSION_MARKER).
    ref: table/schema.rs Entry trait."""

    def partition_key(self) -> bytes:
        raise NotImplementedError

    def sort_key(self) -> bytes:
        raise NotImplementedError

    def merge(self, other: "Entry") -> "Entry":
        raise NotImplementedError

    def is_tombstone(self) -> bool:
        """Fully-deleted entries are GC candidates (ref: schema.rs:34)."""
        return False


def partition_hash(pk: bytes) -> Hash:
    """Ring position of a partition key. 32-byte keys (uuids, block
    hashes) are already uniformly random and index the ring directly —
    crucially this co-locates block_ref rows with their block's shard
    placement (ref: table/schema.rs PartitionKey: identity for
    FixedBytes32, blake2 for String). Row keys written before this rule
    existed (pre-model-layer dev databases) are not migrated."""
    if len(pk) == 32:
        return pk
    return blake2sum(pk)


def tree_key(pk: bytes, sk: bytes) -> bytes:
    """On-disk row key: hash(P) ++ P-len ++ P ++ S so rows group by ring
    partition first (the Merkle trie and sync walk this prefix order)
    while remaining unambiguous for any P/S byte strings.
    ref: table/data.rs tree_key (hash(P) ++ S)."""
    return partition_hash(pk) + len(pk).to_bytes(4, "big") + pk + sk


def split_tree_key(key: bytes) -> tuple[bytes, bytes]:
    """Inverse of tree_key: -> (pk, sk)."""
    plen = int.from_bytes(key[32:36], "big")
    return key[36:36 + plen], key[36 + plen:]


class TableSchema:
    """Binds a table name to an entry type + triggers.
    ref: table/schema.rs:71."""

    TABLE_NAME: str = "?"
    ENTRY: Type[Entry] = Entry

    def decode_entry(self, raw: bytes) -> Entry:
        return migrate.decode(self.ENTRY, raw)

    def encode_entry(self, entry: Entry) -> bytes:
        return migrate.encode(entry)

    def updated(self, tx, old: Optional[Entry], new: Optional[Entry]) -> None:
        """Transactional trigger run inside the db transaction that
        applied the change (ref: schema.rs:86-95). `tx` is the open
        db Transaction; raise TxAbort to reject the write."""

    def matches_filter(self, entry: Entry, flt) -> bool:
        """Server-side filter for get_range (ref: schema.rs:97)."""
        return True
