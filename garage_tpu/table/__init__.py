"""Replicated CRDT table engine.

Ref parity: src/table/ (SURVEY.md §2.5). Tables are the metadata plane:
entries are CRDTs keyed by (partition key, sort key); writes are
quorum-replicated to the ring write sets; reads quorum-merge and
read-repair in the background; a per-partition Merkle trie drives
anti-entropy sync; tombstones are garbage-collected with a 3-phase
protocol that cannot resurrect deleted data.
"""

from .schema import Entry, TableSchema  # noqa: F401
from .replication import (  # noqa: F401
    TableReplication,
    TableShardedReplication,
    TableFullReplication,
)
from .data import TableData  # noqa: F401
from .merkle import MerkleUpdater, MerkleNode  # noqa: F401
from .table import Table  # noqa: F401
from .sync import TableSyncer  # noqa: F401
from .gc import TableGc  # noqa: F401
from .queue import InsertQueueWorker  # noqa: F401
