"""Merkle trie per table partition, driving anti-entropy sync.

Ref parity: src/table/merkle.rs. A background worker drains the
merkle_todo queue (row key -> new item hash, or empty = deleted) and
folds each change up a 256-ary trie stored in the `{table}:merkle_tree`
db tree. Node kinds mirror the reference (merkle.rs:55-67): Empty,
Leaf(row key, item-hash), Intermediate(children).

The trie descends along the bytes of blake2(row key) — fixed 32 bytes,
so no key is ever a prefix of another — while leaves carry the full row
key (merkle.rs:131-247, `key.next_key(khash)`). Intermediates that drop
to a single leaf child collapse upward, so the trie shape is a pure
function of the stored key set: equal content ⇒ equal root hash on
every replica, regardless of write order.

Trie storage keys: 2-byte big-endian partition ++ khash prefix.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..utils import migrate
from ..utils.background import Worker, WState
from ..utils.data import blake2sum
from .data import TableData

log = logging.getLogger("garage_tpu.table.merkle")

EMPTY, LEAF, INTERMEDIATE = 0, 1, 2
EMPTY_HASH = b"\x00" * 32


class MerkleNode:
    """ref: merkle.rs:55-67."""

    __slots__ = ("kind", "key", "hash", "children")

    def __init__(self, kind: int, key: bytes = b"", h: bytes = b"",
                 children: Optional[list] = None):
        self.kind = kind
        self.key = key  # LEAF: full row key
        self.hash = h  # LEAF: item hash
        self.children = children or []  # INTERMEDIATE: [(byte, child-hash)]

    @classmethod
    def empty(cls) -> "MerkleNode":
        return cls(EMPTY)

    @classmethod
    def leaf(cls, key: bytes, h: bytes) -> "MerkleNode":
        return cls(LEAF, key=key, h=h)

    @classmethod
    def intermediate(cls, children: list) -> "MerkleNode":
        return cls(INTERMEDIATE, children=sorted(children))

    def is_empty(self) -> bool:
        return self.kind == EMPTY

    def child(self, byte: int) -> Optional[bytes]:
        for b, h in self.children:
            if b == byte:
                return h
        return None

    def with_child(self, byte: int, h: Optional[bytes]) -> "MerkleNode":
        ch = [(b, x) for b, x in self.children if b != byte]
        if h is not None:
            ch.append((byte, h))
        if not ch:
            return MerkleNode.empty()
        return MerkleNode.intermediate(ch)

    def pack(self) -> bytes:
        if self.kind == EMPTY:
            o = [EMPTY]
        elif self.kind == LEAF:
            o = [LEAF, self.key, self.hash]
        else:
            o = [INTERMEDIATE, [[b, h] for b, h in self.children]]
        return migrate.msgpack.packb(o, use_bin_type=True)

    @classmethod
    def unpack(cls, raw: Optional[bytes]) -> "MerkleNode":
        if raw is None:
            return cls.empty()
        o = migrate.msgpack.unpackb(raw, raw=True)
        if o[0] == EMPTY:
            return cls.empty()
        if o[0] == LEAF:
            return cls.leaf(o[1], o[2])
        return cls.intermediate([(b, h) for b, h in o[1]])

    def node_hash(self) -> bytes:
        """Hash of this (sub)tree; the empty tree hashes to zeros."""
        if self.kind == EMPTY:
            return EMPTY_HASH
        return blake2sum(self.pack())


def node_key(partition: int, prefix: bytes) -> bytes:
    return partition.to_bytes(2, "big") + prefix


def _group_by_byte(items: list, i: int) -> list:
    """Group khash-sorted items by khash[i] (consecutive runs)."""
    out: list = []
    cur_b = -1
    for it in items:
        b = it[1][i]
        if b != cur_b:
            out.append((b, []))
            cur_b = b
        out[-1][1].append(it)
    return out


class MerkleUpdater(Worker):
    """Drains merkle_todo into the trie (ref: merkle.rs worker).
    Batched: todo rows fold into the trie one walk per subtree
    (update_batch), not one root-to-leaf walk per row."""

    BATCH = 1024

    def __init__(self, data: TableData):
        self.data = data
        self.name = f"{data.name} merkle"

    # ---- trie read api (used by sync) ----------------------------------

    def read_node(self, partition: int, prefix: bytes) -> MerkleNode:
        return MerkleNode.unpack(
            self.data.merkle_tree.get(node_key(partition, prefix))
        )

    def root_hash(self, partition: int) -> bytes:
        return self.read_node(partition, b"").node_hash()

    def leaf_rows(self, partition: int, prefix: bytes,
                  limit: int = 1 << 30) -> list[bytes]:
        """Row keys of all leaves under a trie prefix (ref: sync.rs uses
        the subtree itself to enumerate items to push)."""
        out: list[bytes] = []
        stack = [prefix]
        while stack and len(out) < limit:
            p = stack.pop()
            n = self.read_node(partition, p)
            if n.kind == LEAF:
                out.append(n.key)
            elif n.kind == INTERMEDIATE:
                stack.extend(p + bytes([b]) for b, _ in reversed(n.children))
        return out

    # ---- updates -------------------------------------------------------

    def _partition_of_row(self, row_key: bytes) -> int:
        # row keys start with blake2(P); replication decides how many
        # partition bits matter (sharded: top byte; fullcopy: single 0)
        return self.data.replication.partition_of(row_key[:32])

    def update_item(self, row_key: bytes, new_hash: bytes) -> None:
        """Apply one todo entry (new_hash empty = row deleted), folding
        hashes up the trie inside one db transaction."""
        self.data.db.transaction(
            lambda tx: self._apply_one(tx, row_key, new_hash))

    def update_batch(self, todo: list[tuple[bytes, bytes]]) -> None:
        """Apply a batch of todo rows with ONE walk per touched subtree
        instead of one root-to-leaf walk per row: rows are grouped by
        partition, sorted by khash, and folded bottom-up, so a burst of
        inserts into one partition repacks/rehashes the root (and every
        shared upper node) once per batch instead of once per item.
        The resulting trie shape and hashes are identical to sequential
        `update_item` application (tests/test_table.py asserts it) —
        the shape stays a pure function of the key set."""
        by_part: dict[int, list] = {}
        for k, v in todo:
            by_part.setdefault(self._partition_of_row(k), []).append(
                (k, blake2sum(k), v if v else None))

        def body(tx):
            for partition, items in by_part.items():
                items.sort(key=lambda it: it[1])
                self._update_many_rec(tx, partition, b"", items)
            for k, v in todo:
                # only clear a todo entry unchanged since we read it
                # (a concurrent write may have requeued the row)
                if tx.get(self.data.merkle_todo, k) == v:
                    tx.remove(self.data.merkle_todo, k)

        self.data.db.transaction(body)

    def _update_many_rec(self, tx, partition: int, prefix: bytes,
                         items: list) -> Optional[bytes]:
        """Bulk form of _update_rec. `items` is [(row_key, khash,
        vhash|None)] sorted by khash, all sharing `prefix` in khash.
        Returns the node's new hash, EMPTY_HASH if it vanished, or None
        if unchanged."""
        i = len(prefix)
        k = node_key(partition, prefix)
        node = MerkleNode.unpack(tx.get(self.data.merkle_tree, k))

        if node.kind == INTERMEDIATE:
            changed = False
            for byte, group in _group_by_byte(items, i):
                sub = self._update_many_rec(
                    tx, partition, prefix + bytes([byte]), group)
                if sub is None:
                    continue
                node = node.with_child(byte,
                                       None if sub == EMPTY_HASH else sub)
                changed = True
            if not changed:
                return None
            if node.is_empty():
                tx.remove(self.data.merkle_tree, k)
                return EMPTY_HASH
            if len(node.children) == 1:
                # single child left: a leaf child pulls up (canonical
                # shape, same as _update_rec / merkle.rs:164-183)
                cb = node.children[0][0]
                ck = node_key(partition, prefix + bytes([cb]))
                child = MerkleNode.unpack(tx.get(self.data.merkle_tree, ck))
                if child.kind == LEAF:
                    tx.remove(self.data.merkle_tree, ck)
                    node = child
            tx.insert(self.data.merkle_tree, k, node.pack())
            return node.node_hash()

        # EMPTY or LEAF: the whole subtree is the final key set below;
        # compose it from the existing leaf (if any, not superseded by
        # an update) plus the batch's inserts, then build in place.
        final: list = []
        if node.kind == LEAF:
            upd = next((it for it in items if it[0] == node.key), None)
            if upd is None:
                final.append((node.key, blake2sum(node.key), node.hash))
            elif upd[2] is not None:
                final.append(upd)
        final.extend(it for it in items
                     if it[2] is not None
                     and not (node.kind == LEAF and it[0] == node.key))
        final.sort(key=lambda it: it[1])

        if not final:
            if node.kind == LEAF:
                tx.remove(self.data.merkle_tree, k)
                return EMPTY_HASH
            return None  # deletes of keys we never held
        if len(final) == 1:
            rk, _, vh = final[0]
            if node.kind == LEAF and node.key == rk and node.hash == vh:
                return None
            leaf = MerkleNode.leaf(rk, vh)
            tx.insert(self.data.merkle_tree, k, leaf.pack())
            return leaf.node_hash()
        # two or more keys: this node becomes an intermediate; the
        # subtrees below are built fresh (nothing deeper can exist
        # under an EMPTY/LEAF node)
        children = []
        for byte, group in _group_by_byte(final, i):
            sub = self._update_many_rec(
                tx, partition, prefix + bytes([byte]), group)
            children.append((byte, sub))
        inter = MerkleNode.intermediate(children)
        tx.insert(self.data.merkle_tree, k, inter.pack())
        return inter.node_hash()

    def _apply_one(self, tx, row_key: bytes, new_hash: bytes,
                   cache: Optional[dict] = None) -> None:
        partition = self._partition_of_row(row_key)
        khash = blake2sum(row_key)
        self._update_rec(tx, partition, b"", row_key, khash,
                         new_hash if new_hash else None, cache)
        # only clear the todo entry if it hasn't changed since we
        # read it (a concurrent write may have requeued the row)
        cur = tx.get(self.data.merkle_todo, row_key)
        if cur == (new_hash if new_hash else b""):
            tx.remove(self.data.merkle_todo, row_key)

    # ---- node access with an optional per-transaction cache: a batch
    # of todo rows re-walks the same top trie nodes (root + first
    # levels) for every row; caching raw node bytes inside the tx
    # removes those repeated SELECT/INSERT round trips ---------------

    def _nget(self, tx, cache, k: bytes):
        if cache is not None and k in cache:
            return cache[k]
        raw = tx.get(self.data.merkle_tree, k)
        if cache is not None:
            cache[k] = raw
        return raw

    def _nput(self, tx, cache, k: bytes, raw: bytes) -> None:
        tx.insert(self.data.merkle_tree, k, raw)
        if cache is not None:
            cache[k] = raw

    def _ndel(self, tx, cache, k: bytes) -> None:
        tx.remove(self.data.merkle_tree, k)
        if cache is not None:
            cache[k] = None

    def _update_rec(self, tx, partition: int, prefix: bytes, row_key: bytes,
                    khash: bytes, new_vhash: Optional[bytes],
                    cache: Optional[dict] = None) -> Optional[bytes]:
        """Returns the node's new hash (EMPTY_HASH if it vanished), or
        None if the subtree was unchanged. ref: merkle.rs:131-247."""
        i = len(prefix)
        k = node_key(partition, prefix)
        node = MerkleNode.unpack(self._nget(tx, cache, k))
        mutate: Optional[MerkleNode]

        if node.kind == EMPTY:
            mutate = MerkleNode.leaf(row_key, new_vhash) if new_vhash else None
        elif node.kind == INTERMEDIATE:
            byte = khash[i]
            sub = self._update_rec(tx, partition, prefix + bytes([byte]),
                                   row_key, khash, new_vhash, cache)
            if sub is None:
                mutate = None
            else:
                node = node.with_child(byte, None if sub == EMPTY_HASH else sub)
                if node.is_empty():
                    mutate = node
                elif len(node.children) == 1:
                    # single child left: if it's a leaf, pull it up
                    # (canonical shape; ref: merkle.rs:164-183)
                    cb = node.children[0][0]
                    ck = node_key(partition, prefix + bytes([cb]))
                    child = MerkleNode.unpack(self._nget(tx, cache, ck))
                    if child.kind == LEAF:
                        self._ndel(tx, cache, ck)
                        mutate = child
                    else:
                        mutate = node
                else:
                    mutate = node
        else:  # LEAF
            if node.key == row_key:
                if new_vhash is None:
                    mutate = MerkleNode.empty()
                elif node.hash == new_vhash:
                    mutate = None
                else:
                    mutate = MerkleNode.leaf(row_key, new_vhash)
            elif new_vhash is None:
                mutate = None  # deleting a key we don't hold here
            else:
                # split: push the existing leaf down one level, then
                # insert ours; shared khash bytes recurse further down
                exk = node.key
                exkhash = blake2sum(exk)
                sub1 = self._update_rec(tx, partition,
                                        prefix + bytes([exkhash[i]]),
                                        exk, exkhash, node.hash, cache)
                inter = MerkleNode.intermediate([(exkhash[i], sub1)])
                sub2 = self._update_rec(tx, partition,
                                        prefix + bytes([khash[i]]),
                                        row_key, khash, new_vhash, cache)
                mutate = inter.with_child(khash[i], sub2)

        if mutate is None:
            return None
        if mutate.is_empty():
            self._ndel(tx, cache, k)
            return EMPTY_HASH
        self._nput(tx, cache, k, mutate.pack())
        return mutate.node_hash()

    # ---- worker loop ---------------------------------------------------

    # rows per db transaction: the batched walk amortizes the upper
    # trie levels across the whole step, so bigger steps cut the
    # per-row cost further — 256 balances that against db-lock hold
    # time (the PUT path shares the lock)
    TX_STEP = 256

    async def work(self):
        import asyncio

        # bounded cursor read: a deep backlog (bulk load, resync storm)
        # must not be materialized whole just to take the first BATCH
        todo = await asyncio.to_thread(
            lambda: list(self.data.merkle_todo.iter(limit=self.BATCH)))
        if not todo:
            return WState.IDLE

        for i in range(0, len(todo), self.TX_STEP):
            await asyncio.to_thread(self.update_batch,
                                    todo[i:i + self.TX_STEP])
        return WState.BUSY

    async def wait_for_work(self):
        import asyncio

        while not len(self.data.merkle_todo):
            await asyncio.sleep(0.1)

    def info(self):
        from ..utils.background import WorkerInfo

        return WorkerInfo(name=self.name, queue_length=len(self.data.merkle_todo))
