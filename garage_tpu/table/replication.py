"""Replication strategies: which nodes store which partition.

Ref parity: src/table/replication/ (parameters.rs:5-43, sharded.rs:16-83,
fullcopy.rs:21-73). Sharded tables follow the ring (layout write sets +
ack-locked transitions); full-copy tables live on every node (control
plane: buckets, keys) with local reads and n-1 write quorum.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..rpc.layout.version import partition_of

if TYPE_CHECKING:
    from ..rpc.system import System


class TableReplication:
    """ref: table/replication/parameters.rs:5-43."""

    def storage_nodes(self, hash32: bytes) -> list[bytes]:
        raise NotImplementedError

    def read_nodes(self, hash32: bytes) -> list[bytes]:
        raise NotImplementedError

    def read_quorum(self) -> int:
        raise NotImplementedError

    def write_sets(self, hash32: bytes) -> list[list[bytes]]:
        raise NotImplementedError

    def write_quorum(self) -> int:
        raise NotImplementedError

    def partition_of(self, hash32: bytes) -> int:
        return partition_of(hash32)

    def sync_partitions(self) -> list["SyncPartition"]:
        raise NotImplementedError

    # the ack lock context for writes; default: no-op
    def write_lock(self):
        import contextlib

        return contextlib.nullcontext()


class SyncPartition:
    """One unit of anti-entropy work (ref: parameters.rs SyncPartition)."""

    __slots__ = ("partition", "first_hash", "storage_sets")

    def __init__(self, partition: int, first_hash: bytes, storage_sets: list[list[bytes]]):
        self.partition = partition
        self.first_hash = first_hash
        self.storage_sets = storage_sets


def partition_first_hash(partition: int) -> bytes:
    """Smallest 32-byte hash in a ring partition (top 8 bits)."""
    return bytes([partition]) + b"\x00" * 31


class TableShardedReplication(TableReplication):
    """Ring-based sharding with quorum R/W (ref: sharded.rs:16-83)."""

    def __init__(self, system: "System", read_quorum: int, write_quorum: int):
        self.system = system
        self._rq = read_quorum
        self._wq = write_quorum

    @property
    def _helper(self):
        return self.system.layout_helper

    def storage_nodes(self, hash32):
        return self._helper.current_storage_nodes_of(hash32)

    def read_nodes(self, hash32):
        return self._helper.read_nodes_of(hash32)

    def read_quorum(self):
        return self._rq

    def write_sets(self, hash32):
        return self._helper.write_sets_of(hash32)

    def write_quorum(self):
        return self._wq

    def write_lock(self):
        return self._helper.write_lock()

    def sync_partitions(self):
        out = []
        for p in range(256):
            fh = partition_first_hash(p)
            out.append(SyncPartition(p, fh, self._helper.storage_sets_of(p)))
        return out


class TableFullReplication(TableReplication):
    """Every (non-gateway) node stores everything; local reads.
    ref: fullcopy.rs:21-73."""

    def __init__(self, system: "System"):
        self.system = system

    def _all_nodes(self) -> list[bytes]:
        nodes = self.system.layout_helper.history.all_nongateway_nodes()
        if not nodes:
            return [self.system.id]
        return sorted(nodes)

    def storage_nodes(self, hash32):
        return self._all_nodes()

    def read_nodes(self, hash32):
        # reads are served locally: a STORAGE node always has a full
        # copy. A gateway node (capacity-less; e.g. a multi-process
        # gateway API worker) holds none — it reads from the holders
        # over RPC instead of answering from its empty local table.
        nodes = self._all_nodes()
        if self.system.id in nodes:
            return [self.system.id]
        return nodes

    def read_quorum(self):
        return 1

    def write_sets(self, hash32):
        return [self._all_nodes()]

    def write_quorum(self):
        # tolerate one lagging node, like the reference (fullcopy.rs:59:
        # n - 1, so a new node joining doesn't block all control writes)
        n = len(self._all_nodes())
        return max(1, n - 1)

    def partition_of(self, hash32):
        # single logical partition: the whole keyspace (fullcopy.rs:67)
        return 0

    def sync_partitions(self):
        # one big "partition" 0 covering the whole keyspace
        return [SyncPartition(0, b"\x00" * 32, [self._all_nodes()])]
