"""3-phase tombstone garbage collection.

Ref parity: src/table/gc.rs. Tombstones (fully-deleted CRDT entries) can
only be dropped once every storage node holds them — otherwise a replica
that missed the deletion would resurrect the entry on the next sync. The
protocol (gc.rs:73-275):

  1. the partition leader waits TABLE_GC_DELAY after the tombstone lands,
  2. pushes the tombstone to ALL storage nodes ("update" + mark "save"),
  3. then asks all nodes to delete-if-equal-hash, so a concurrent newer
     write is never clobbered.

RPC ops on endpoint "garage_tpu/table_gc:{name}":
  {op: "update", entries}   -> push tombstones + remember them
  {op: "delete_if_eq", items: [(key, vhash)..]}
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..net.message import PRIO_BACKGROUND
from ..utils.background import Worker, WState

log = logging.getLogger("garage_tpu.table.gc")

TABLE_GC_DELAY = 24 * 3600.0
TABLE_GC_BATCH_SIZE = 1024


class GcTodoEntry:
    """Row in the gc_todo tree, keyed by (deadline ms ++ row key).
    ref: gc.rs GcTodoEntry."""

    def __init__(self, deadline_ms: int, row_key: bytes, value_hash: bytes):
        self.deadline_ms = deadline_ms
        self.row_key = row_key
        self.value_hash = value_hash

    @classmethod
    def new(cls, row_key: bytes, value_hash: bytes,
            delay: float = TABLE_GC_DELAY) -> "GcTodoEntry":
        return cls(int((time.time() + delay) * 1000), row_key, value_hash)

    def todo_key(self) -> bytes:
        return self.deadline_ms.to_bytes(8, "big") + self.row_key

    def save(self, tx, gc_todo_tree) -> None:
        tx.insert(gc_todo_tree, self.todo_key(), self.value_hash)

    @classmethod
    def parse(cls, k: bytes, v: bytes) -> "GcTodoEntry":
        return cls(int.from_bytes(k[:8], "big"), k[8:], v)


class TableGc(Worker):
    def __init__(self, table, delay: float = TABLE_GC_DELAY):
        self.table = table
        self.data = table.data
        self.name = f"{table.name} gc"
        self.delay = delay
        self.endpoint = table.system.netapp.endpoint(
            f"garage_tpu/table_gc:{table.name}"
        ).set_handler(self._handle)

    async def work(self):
        now_ms = int(time.time() * 1000)

        def collect() -> list[GcTodoEntry]:
            batch: list[GcTodoEntry] = []
            for k, v in self.data.gc_todo.iter():
                e = GcTodoEntry.parse(k, v)
                if e.deadline_ms > now_ms:
                    break
                batch.append(e)
                if len(batch) >= TABLE_GC_BATCH_SIZE:
                    break
            return batch

        batch = await asyncio.to_thread(collect)
        if not batch:
            return WState.IDLE
        await self.gc_batch(batch)
        return WState.BUSY

    async def wait_for_work(self):
        await asyncio.sleep(60.0)

    async def gc_batch(self, batch: list[GcTodoEntry]) -> None:
        """Group by storage-node set, then run the 2 RPC phases.
        ref: gc.rs:152-275."""
        me = self.table.system.id
        # drop entries whose row changed since (no longer that tombstone);
        # per-entry sqlite read + digest runs off the event loop (GL01)
        from ..utils.data import blake2sum

        def filter_live() -> list[GcTodoEntry]:
            out: list[GcTodoEntry] = []
            for e in batch:
                cur = self.data.store.get(e.row_key)
                if cur is None or blake2sum(cur) != e.value_hash:
                    self.data.gc_todo.remove(e.todo_key())
                else:
                    out.append(e)
            return out

        live = await asyncio.to_thread(filter_live)

        by_nodes: dict[tuple, list[GcTodoEntry]] = {}
        for e in live:
            nodes = tuple(sorted(self.data.replication.storage_nodes(e.row_key[:32])))
            by_nodes.setdefault(nodes, []).append(e)

        for nodes, entries in by_nodes.items():
            raws = await asyncio.to_thread(
                lambda es=entries: [self.data.store.get(e.row_key)
                                    for e in es])
            pairs = [(e, r) for e, r in zip(entries, raws) if r is not None]
            if not pairs:
                continue
            try:
                # phase 2: make sure every node stores the tombstone
                for n in nodes:
                    if n != me:
                        await self.endpoint.call(
                            n, {"op": "update",
                                "entries": [r for _, r in pairs]},
                            PRIO_BACKGROUND,
                        )
                # phase 3: delete-if-equal everywhere (including locally)
                items = [(e.row_key, e.value_hash) for e, _ in pairs]
                for n in nodes:
                    if n == me:
                        await asyncio.to_thread(self._delete_if_eq, items)
                    else:
                        await self.endpoint.call(
                            n, {"op": "delete_if_eq", "items": items},
                            PRIO_BACKGROUND,
                        )
                await asyncio.to_thread(
                    lambda ps=pairs: [
                        self.data.gc_todo.remove(e.todo_key())
                        for e, _ in ps])
            except Exception as ex:
                log.info("%s: gc batch failed (will retry): %s", self.name, ex)

    def _delete_if_eq(self, items) -> None:
        for key, vhash in items:
            self.data.delete_if_equal_hash(key, vhash)

    async def _handle(self, from_node: bytes, payload, stream):
        op = payload["op"]
        if op == "update":
            await asyncio.to_thread(self.data.update_many, payload["entries"])
            return {"ok": True}
        if op == "delete_if_eq":
            await asyncio.to_thread(self._delete_if_eq, payload["items"])
            return {"ok": True}
        raise ValueError(f"unknown gc op {op!r}")
