"""Operator CLI: `python -m garage_tpu.cli.main <command>`.

Ref parity: src/garage/cli/ (structs.rs:9-530, cmd.rs). Connects to a
running node's RPC port (config from --config / GARAGE_CONFIG_FILE) with
an ephemeral identity and drives the AdminRpc endpoint.

Commands: status, node connect, layout {show,assign,remove,apply},
bucket {list,create,delete,info,allow,deny}, key {new,list,info,delete,
import}, worker list, stats.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from ..model.garage import parse_addr, parse_peer
from ..net import NetApp
from ..net.message import PRIO_NORMAL
from ..utils.config import read_config


def fmt_table(rows: list[list[str]], header: list[str]) -> str:
    """ref: src/format-table/lib.rs — tab-aligned columns."""
    all_rows = [header] + rows
    widths = [max(len(str(r[i])) for r in all_rows)
              for i in range(len(header))]
    lines = []
    for i, r in enumerate(all_rows):
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


class AdminClient:
    def __init__(self, cfg):
        self.cfg = cfg
        netid = (bytes.fromhex(cfg.rpc_secret) if cfg.rpc_secret
                 else b"garage-tpu-insecure-dev")
        self.netapp = NetApp(netid)
        self.node = None

    async def connect(self) -> None:
        addr = parse_addr(self.cfg.rpc_public_addr or self.cfg.rpc_bind_addr)
        self.node = await self.netapp.try_connect(addr)
        self.ep = self.netapp.endpoint("garage_tpu/admin")

    async def call(self, op: str, **kw):
        resp, _ = await self.ep.call(self.node, {"op": op, **kw},
                                     PRIO_NORMAL, timeout=30.0)
        return resp

    async def close(self):
        await self.netapp.shutdown()


async def cmd(args) -> int:
    if args.cmd in ("convert-db", "repair-offline"):
        return await _offline(args)  # no server connection
    cfg = await asyncio.to_thread(read_config, args.config)
    cli = AdminClient(cfg)
    try:
        await cli.connect()
        return await _dispatch(cli, args)
    finally:
        await cli.close()


async def _offline(args) -> int:
    """Offline maintenance: operates directly on the metadata db with
    NO server running (ref: src/garage/cli/convert_db.rs +
    src/garage/repair/offline.rs)."""
    if args.cmd == "convert-db":
        from ..db import open_db

        src_db_file = (args.src if args.src.endswith(".sqlite")
                       else os.path.join(args.src, "db.sqlite"))
        if args.src_engine == "sqlite" and not os.path.exists(src_db_file):
            # open_db would CREATE an empty db at a typo'd path and the
            # "conversion" would silently produce nothing
            print(f"source database {src_db_file} does not exist",
                  file=sys.stderr)
            return 1
        if os.path.abspath(args.src) == os.path.abspath(args.dst):
            print("--src and --dst are the same path", file=sys.stderr)
            return 1
        # a live server on this metadata dir would mutate trees while we
        # snapshot them — take the same lock the server holds. The db
        # usually lives at {metadata_dir}/db, so the server's lock sits
        # in the PARENT of src; guard both.
        from ..utils import lockfile

        src_dir = os.path.abspath(args.src) if os.path.isdir(args.src) \
            else os.path.dirname(os.path.abspath(args.src))
        lock_fds = []
        try:
            for d in dict.fromkeys([src_dir, os.path.dirname(src_dir)]):
                lock_fds.append(lockfile.acquire(d, "convert-db"))
        except lockfile.AlreadyLocked as e:
            for fd in lock_fds:
                lockfile.release(fd)
            print(str(e), file=sys.stderr)
            return 1
        def _convert() -> int:
            # runs in a worker thread (GL10): the whole-db copy is
            # minutes of sqlite/LSM I/O and must not pin the loop
            src = open_db(args.src, engine=args.src_engine)
            dst = open_db(args.dst, engine=args.dst_engine)
            try:
                if dst.list_trees():
                    print("destination database is not empty; refusing "
                          "to interleave rows", file=sys.stderr)
                    return 1
                total = 0
                for name in src.list_trees():
                    st = src.open_tree(name)
                    dt = dst.open_tree(name)
                    rows, cursor = 0, None
                    while True:  # batched: never materialize a tree
                        batch = list(st.iter(start=cursor, limit=10000))
                        if not batch:
                            break

                        def copy(tx, batch=batch, dt=dt):
                            for k, v in batch:
                                tx.insert(dt, k, v)

                        dst.transaction(copy)
                        rows += len(batch)
                        if len(batch) < 10000:
                            break
                        cursor = batch[-1][0] + b"\x00"
                    total += rows
                    print(f"  {name}: {rows} rows")
                print(f"converted {total} rows "
                      f"({args.src_engine} -> {args.dst_engine})")
            finally:
                src.close()
                dst.close()
            return 0

        try:
            return await asyncio.to_thread(_convert)
        finally:
            for fd in lock_fds:
                lockfile.release(fd)
    if args.cmd == "repair-offline":
        cfg = await asyncio.to_thread(read_config, args.config)
        from ..model.garage import Garage
        from ..utils import lockfile

        # a live server holds this lock: a recount racing a live
        # count() would win the CRDT merge with stale totals
        try:
            lock_fd = lockfile.acquire(cfg.metadata_dir, "repair-offline")
        except lockfile.AlreadyLocked as e:
            print(str(e), file=sys.stderr)
            return 1
        try:
            garage = Garage(cfg)
            if args.what == "object-counters":
                n = await asyncio.to_thread(
                    garage.object_counter.recount,
                    garage.object_table.data)
                n += await asyncio.to_thread(
                    garage.mpu_counter.recount, garage.mpu_table.data)
                print(f"recomputed {n} object/mpu counter rows")
            elif args.what == "k2v-counters":
                n = await asyncio.to_thread(
                    garage.k2v_counter.recount,
                    garage.k2v_item_table.data)
                print(f"recomputed {n} k2v counter rows")
            else:
                print(f"unknown offline repair {args.what!r}",
                      file=sys.stderr)
                return 1
            garage.db.close()
        finally:
            lockfile.release(lock_fd)
        return 0
    return 1


async def _dispatch(cli: AdminClient, args) -> int:
    c = args.cmd
    if c == "status":
        r = await cli.call("status")
        h = r["health"]
        print(f"node id:  {r['node_id'].hex()}")
        print(f"health:   {h['status']} "
              f"({h['connected_nodes']}/{h['known_nodes']} nodes, "
              f"{h['storage_nodes_up']}/{h['storage_nodes']} storage, "
              f"{h['partitions_quorum']}/256 partitions with quorum)")
        print(f"layout:   v{r['layout_version']}")
        rows = []
        for n in r["nodes"]:
            role = n.get("role") or {}
            rows.append([
                n["id"].hex()[:16], n.get("hostname", ""),
                "up" if n["is_up"] else "DOWN",
                role.get("zone", "-"),
                str(role.get("capacity", "-")),
            ])
        print(fmt_table(rows, ["id", "host", "status", "zone", "capacity"]))
        return 0
    if c == "connect":
        addr, nid = parse_peer(args.peer)
        await cli.call("connect", addr=list(addr), id=nid)
        print("ok")
        return 0
    if c == "layout":
        return await _layout(cli, args)
    if c == "bucket":
        return await _bucket(cli, args)
    if c == "key":
        return await _key(cli, args)
    if c == "worker":
        s = getattr(args, "subcmd", None) or "list"
        if s == "list":
            r = await cli.call("worker_list")
            rows = [[w["id"], w["name"], str(w.get("queue") or ""),
                     str(w.get("errors") or "")] for w in r["workers"]]
            print(fmt_table(rows, ["id", "name", "queue", "errors"]))
            return 0
        if s == "get":
            r = await cli.call("worker_get", name=args.name)
            for k, v in sorted(r["vars"].items()):
                print(f"{k} = {v}")
            return 0
        if s == "set":
            r = await cli.call("worker_set", name=args.name,
                               value=args.value)
            print(f"{args.name} = {r['value']}")
            return 0
        return 1
    if c == "repair":
        r = await cli.call("repair", what=args.what,
                           cmd=getattr(args, "scrub_cmd", None))
        print(r.get("msg", "ok"))
        return 0
    if c == "block":
        return await _block(cli, args)
    if c == "meta":
        if args.subcmd == "snapshot":
            r = await cli.call("meta_snapshot")
            print(f"snapshot written to {r['path']}")
            return 0
        return 1
    if c == "stats":
        r = await cli.call("stats")
        print(json.dumps(r, indent=2, default=str))
        return 0
    print(f"unknown command {c}", file=sys.stderr)
    return 1


async def _layout(cli, args) -> int:
    s = args.subcmd
    if s == "show":
        r = await cli.call("layout_show")
        print(f"current layout version: {r['version']}")
        rows = [[nid[:16], v["zone"], str(v["capacity"])]
                for nid, v in sorted(r["roles"].items())]
        print(fmt_table(rows, ["id", "zone", "capacity"]))
        if r["staged"]:
            print("\nstaged changes:")
            for nid, v in sorted(r["staged"].items()):
                print(f"  {nid[:16]} -> {v}")
        return 0
    if s == "assign":
        from ..utils.config import parse_capacity

        node = bytes.fromhex(args.node) if len(args.node) == 64 else None
        if node is None:
            # prefix match against known nodes
            r = await cli.call("status")
            cands = [n["id"] for n in r["nodes"]
                     if n["id"].hex().startswith(args.node)]
            if len(cands) != 1:
                print(f"node prefix {args.node!r} matches {len(cands)} nodes",
                      file=sys.stderr)
                return 1
            node = bytes(cands[0])
        cap = parse_capacity(args.capacity) if args.capacity else None
        await cli.call("layout_assign", node=node, zone=args.zone,
                       capacity=cap, tags=args.tags or [])
        print("staged; run `layout apply` to activate")
        return 0
    if s == "remove":
        node = bytes.fromhex(args.node)
        await cli.call("layout_remove", node=node)
        print("staged removal")
        return 0
    if s == "apply":
        r = await cli.call("layout_apply", version=args.version)
        print(f"layout applied, now at version {r['version']}")
        return 0
    if s == "revert":
        r = await cli.call("layout_revert")
        print(f"staged changes reverted (layout stays at "
              f"v{r['version']})")
        return 0
    if s == "config":
        r = await cli.call("layout_config",
                           zone_redundancy=args.zone_redundancy)
        print(f"staged parameters: {r['staged_parameters']} "
              f"(run `layout apply` to activate)")
        return 0
    if s == "skip-dead-nodes":
        r = await cli.call("layout_skip_dead_nodes", version=args.version,
                           allow_missing_data=args.allow_missing_data)
        if r["updated"]:
            print(f"advanced trackers to v{r['version']} for "
                  f"{len(r['updated'])} dead node(s):")
            for n in r["updated"]:
                print(f"  {n[:16]}")
        else:
            print("no dead nodes with stale trackers")
        return 0
    return 1


async def _bucket(cli, args) -> int:
    s = args.subcmd
    if s == "list":
        r = await cli.call("bucket_list")
        print(fmt_table([[b["name"], b["id"][:16]] for b in r["buckets"]],
                        ["name", "id"]))
        return 0
    if s == "create":
        r = await cli.call("bucket_create", name=args.name)
        print(f"bucket {args.name} created, id {r['id']}")
        return 0
    if s == "delete":
        await cli.call("bucket_delete", name=args.name)
        print("deleted")
        return 0
    if s == "info":
        r = await cli.call("bucket_info", name=args.name)
        print(json.dumps(r, indent=2))
        return 0
    if s in ("allow", "deny"):
        await cli.call(f"bucket_{s}", bucket=args.name, key=args.key,
                       read=args.read, write=args.write, owner=args.owner)
        print("ok")
        return 0
    return 1


async def _block(cli, args) -> int:
    s = args.subcmd
    if s == "list-errors":
        r = await cli.call("block_list_errors")
        rows = [[e["hash"][:16], str(e["failures"]),
                 str(e["next_try_ms"])] for e in r["errors"]]
        print(fmt_table(rows, ["hash", "failures", "next_try_ms"]))
        return 0
    if s == "info":
        r = await cli.call("block_info", hash=args.hash)
        print(json.dumps(r, indent=2, default=str))
        return 0
    if s == "retry-now":
        r = await cli.call("block_retry_now", all=args.all,
                           hashes=args.hashes or [])
        print(f"{r['count']} block(s) queued for retry")
        return 0
    if s == "purge":
        if not args.yes:
            print("refusing to purge without --yes", file=sys.stderr)
            return 1
        r = await cli.call("block_purge", hashes=args.hashes or [])
        print(f"purged {r['versions']} version(s), "
              f"{r['objects']} object(s)")
        return 0
    return 1


async def _key(cli, args) -> int:
    s = args.subcmd
    if s == "new":
        r = await cli.call("key_new", name=args.name or "")
        print(f"Key ID:     {r['key_id']}")
        print(f"Secret key: {r['secret_key']}")
        return 0
    if s == "list":
        r = await cli.call("key_list")
        print(fmt_table([[k["id"], k["name"]] for k in r["keys"]],
                        ["id", "name"]))
        return 0
    if s == "info":
        r = await cli.call("key_info", key=args.key, show_secret=args.show_secret)
        print(json.dumps(r, indent=2))
        return 0
    if s == "delete":
        await cli.call("key_delete", key=args.key)
        print("deleted")
        return 0
    if s == "import":
        r = await cli.call("key_import", key_id=args.key_id,
                           secret_key=args.secret_key, name=args.name or "")
        print(f"imported {r['key_id']}")
        return 0
    if s in ("allow", "deny"):
        await cli.call(f"key_{s}", key=args.key,
                       create_bucket=args.create_bucket)
        print("ok")
        return 0
    return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="garage")
    p.add_argument("--config", "-c",
                   default=os.environ.get("GARAGE_CONFIG_FILE",
                                          "/etc/garage.toml"))
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    pc = sub.add_parser("connect")
    pc.add_argument("peer")  # id@host:port
    pl = sub.add_parser("layout")
    pls = pl.add_subparsers(dest="subcmd", required=True)
    pls.add_parser("show")
    pa = pls.add_parser("assign")
    pa.add_argument("node")
    pa.add_argument("--zone", "-z", default="dc1")
    pa.add_argument("--capacity", "-c", default=None)
    pa.add_argument("--tags", "-t", nargs="*")
    pr = pls.add_parser("remove")
    pr.add_argument("node")
    pap = pls.add_parser("apply")
    pap.add_argument("--version", type=int, default=None)
    pls.add_parser("revert")
    pcf = pls.add_parser("config")
    pcf.add_argument("--zone-redundancy", "-r", dest="zone_redundancy",
                     required=True,
                     help="int or 'maximum' (zones per partition)")
    psd = pls.add_parser("skip-dead-nodes")
    psd.add_argument("--version", type=int, default=None)
    psd.add_argument("--allow-missing-data", action="store_true",
                     help="also advance sync trackers (accepts data "
                          "loss on the dead nodes)")
    pb = sub.add_parser("bucket")
    pbs = pb.add_subparsers(dest="subcmd", required=True)
    pbs.add_parser("list")
    for name in ("create", "delete", "info"):
        x = pbs.add_parser(name)
        x.add_argument("name")
    for name in ("allow", "deny"):
        x = pbs.add_parser(name)
        x.add_argument("name")
        x.add_argument("--key", required=True)
        x.add_argument("--read", action="store_true")
        x.add_argument("--write", action="store_true")
        x.add_argument("--owner", action="store_true")
    pk = sub.add_parser("key")
    pks = pk.add_subparsers(dest="subcmd", required=True)
    kn = pks.add_parser("new")
    kn.add_argument("--name", default="")
    pks.add_parser("list")
    ki = pks.add_parser("info")
    ki.add_argument("key")
    ki.add_argument("--show-secret", action="store_true")
    kd = pks.add_parser("delete")
    kd.add_argument("key")
    kim = pks.add_parser("import")
    kim.add_argument("key_id")
    kim.add_argument("secret_key")
    kim.add_argument("--name", default="")
    for name in ("allow", "deny"):
        x = pks.add_parser(name)
        x.add_argument("key")
        x.add_argument("--create-bucket", action="store_true")
    pw = sub.add_parser("worker")
    pws = pw.add_subparsers(dest="subcmd")
    pws.add_parser("list")
    wg = pws.add_parser("get")
    wg.add_argument("name", nargs="?", default=None)
    ws = pws.add_parser("set")
    ws.add_argument("name")
    ws.add_argument("value")
    prp = sub.add_parser("repair")
    prp.add_argument("what", choices=["tables", "versions", "mpu",
                                      "block-refs", "block-rc", "blocks",
                                      "rebalance", "scrub"])
    prp.add_argument("scrub_cmd", nargs="?", default="start",
                     choices=["start", "pause", "resume", "cancel"])
    pbl = sub.add_parser("block")
    pbls = pbl.add_subparsers(dest="subcmd", required=True)
    pbls.add_parser("list-errors")
    bi = pbls.add_parser("info")
    bi.add_argument("hash")
    br = pbls.add_parser("retry-now")
    br.add_argument("--all", action="store_true")
    br.add_argument("hashes", nargs="*")
    bp = pbls.add_parser("purge")
    bp.add_argument("--yes", action="store_true")
    bp.add_argument("hashes", nargs="*")
    pm = sub.add_parser("meta")
    pms = pm.add_subparsers(dest="subcmd", required=True)
    pms.add_parser("snapshot")
    sub.add_parser("stats")
    pcv = sub.add_parser("convert-db",
                         help="offline: copy all metadata trees between "
                              "db engines/paths (server must be stopped)")
    pcv.add_argument("--src", required=True)
    pcv.add_argument("--src-engine", default="sqlite")
    pcv.add_argument("--dst", required=True)
    pcv.add_argument("--dst-engine", default="sqlite")
    pro = sub.add_parser("repair-offline",
                         help="offline: recompute index counters from "
                              "the stored tables (server must be stopped)")
    pro.add_argument("what", choices=["object-counters", "k2v-counters"])
    return p


def main() -> None:
    from . import reset_sigpipe

    reset_sigpipe()
    args = build_parser().parse_args()
    if args.cmd == "worker" and getattr(args, "subcmd", None) is None:
        args.subcmd = "list"
    sys.exit(asyncio.run(cmd(args)))


if __name__ == "__main__":
    main()
