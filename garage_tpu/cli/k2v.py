"""k2v-cli: command-line client for the K2V API.

Ref parity: src/k2v-client/bin/k2v-cli.rs:392 — the operator/debug CLI
over the K2V HTTP API, built on the same SDK applications use
(garage_tpu/k2v_client.py). Connection comes from flags or environment
(K2V_HOST/K2V_PORT/K2V_BUCKET/AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY).

  python -m garage_tpu.cli.k2v --bucket b -k GK.. -s .. read pk sk
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys

from ..k2v_client import K2vClient, K2vError


def _client(args) -> K2vClient:
    key_id = args.key_id or os.environ.get("AWS_ACCESS_KEY_ID", "")
    secret = args.secret or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
    bucket = args.bucket or os.environ.get("K2V_BUCKET", "")
    if not (key_id and secret and bucket):
        print("need --bucket/--key-id/--secret (or env K2V_BUCKET, "
              "AWS_ACCESS_KEY_ID, AWS_SECRET_ACCESS_KEY)", file=sys.stderr)
        raise SystemExit(2)
    return K2vClient(args.host, args.port, bucket, key_id, secret,
                     region=args.region)


def _print_value(v) -> None:
    out = {"causality": v.causality, "values": []}
    for b in v.values:
        if b is None:
            out["values"].append({"tombstone": True})
        else:
            try:
                out["values"].append({"utf8": b.decode()})
            except UnicodeDecodeError:
                out["values"].append(
                    {"base64": base64.b64encode(b).decode()})
    print(json.dumps(out, indent=2))


def main(argv=None) -> int:
    from . import reset_sigpipe

    reset_sigpipe()
    p = argparse.ArgumentParser(prog="k2v-cli")
    p.add_argument("--host", default=os.environ.get("K2V_HOST", "127.0.0.1"))
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("K2V_PORT", "3904")))
    p.add_argument("--bucket", "-b", default=None)
    p.add_argument("--key-id", "-k", default=None)
    p.add_argument("--secret", "-s", default=None)
    p.add_argument("--region", default="garage")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("read", help="read one item (all causal values)")
    pr.add_argument("partition_key")
    pr.add_argument("sort_key")

    pi = sub.add_parser("insert", help="insert/overwrite one item")
    pi.add_argument("partition_key")
    pi.add_argument("sort_key")
    pi.add_argument("value", help="value bytes; @file reads a file, "
                                  "- reads stdin")
    pi.add_argument("--causality", "-c", default=None)
    pi.add_argument("--b64", action="store_true",
                    help="value argument is base64")

    pd = sub.add_parser("delete", help="delete one item")
    pd.add_argument("partition_key")
    pd.add_argument("sort_key")
    pd.add_argument("--causality", "-c", required=True)

    px = sub.add_parser("read-index",
                        help="list partition keys with item counts")
    px.add_argument("--prefix", default=None)
    px.add_argument("--limit", type=int, default=None)

    prr = sub.add_parser("read-range", help="list items of one partition")
    prr.add_argument("partition_key")
    prr.add_argument("--prefix", default=None)
    prr.add_argument("--limit", type=int, default=None)

    pp = sub.add_parser("poll-item",
                        help="long-poll one item for a newer value")
    pp.add_argument("partition_key")
    pp.add_argument("sort_key")
    pp.add_argument("--causality", "-c", required=True)
    pp.add_argument("--timeout", type=float, default=10.0)

    ppr = sub.add_parser("poll-range",
                         help="long-poll a partition for changes")
    ppr.add_argument("partition_key")
    ppr.add_argument("--prefix", default=None)
    ppr.add_argument("--seen-marker", default=None)
    ppr.add_argument("--timeout", type=float, default=10.0)

    args = p.parse_args(argv)
    cli = _client(args)
    try:
        if args.cmd == "read":
            _print_value(cli.read_item(args.partition_key, args.sort_key))
        elif args.cmd == "insert":
            raw = args.value
            if raw == "-":
                data = sys.stdin.buffer.read()
            elif raw.startswith("@"):
                with open(raw[1:], "rb") as f:
                    data = f.read()
            else:
                data = (base64.b64decode(raw) if args.b64
                        else raw.encode())
            cli.insert_item(args.partition_key, args.sort_key, data,
                            causality=args.causality)
            print("ok")
        elif args.cmd == "delete":
            cli.delete_item(args.partition_key, args.sort_key,
                            args.causality)
            print("ok")
        elif args.cmd == "read-index":
            infos = cli.read_index(prefix=args.prefix, limit=args.limit)
            for pi_ in infos:
                print(json.dumps({"partitionKey": pi_.pk,
                                  "entries": pi_.entries,
                                  "values": pi_.values,
                                  "bytes": pi_.bytes}))
        elif args.cmd == "read-range":
            q = {"partitionKey": args.partition_key}
            if args.prefix:
                q["prefix"] = args.prefix
            if args.limit:
                q["limit"] = args.limit
            for resp in cli.read_batch([q]):
                print(json.dumps(resp, indent=2))
        elif args.cmd == "poll-item":
            v = cli.poll_item(args.partition_key, args.sort_key,
                              args.causality, timeout=args.timeout)
            if v is None:
                print("timeout: no new value")
                return 3
            _print_value(v)
        elif args.cmd == "poll-range":
            r = cli.poll_range(args.partition_key, prefix=args.prefix,
                               seen_marker=args.seen_marker,
                               timeout=args.timeout)
            if r is None:
                print("timeout: no changes")
                return 3
            items, marker = r
            for it in items:
                print(json.dumps({
                    "sk": it["sk"], "ct": it["ct"],
                    "v": [None if v is None
                          else base64.b64encode(v).decode()
                          for v in it["v"]]}))
            print(json.dumps({"seenMarker": marker}))
    except K2vError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
