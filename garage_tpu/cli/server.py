"""Node server entrypoint: `python -m garage_tpu.cli.server --config x.toml`.

Ref parity: src/garage/server.rs:30-215 (startup sequence) +
garage/main.rs. Builds the Garage root, starts RPC listen + gossip +
workers, then the S3 / admin HTTP frontends; exits cleanly on
SIGINT/SIGTERM.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from ..admin.rpc import AdminRpcHandler
from ..api.s3.api_server import S3ApiServer
from ..model.garage import Garage, parse_addr
from ..utils.config import read_config

log = logging.getLogger("garage_tpu.server")


async def run_server(cfg_path: str) -> None:
    from ..utils.runtime import tune

    tune()
    cfg = await asyncio.to_thread(read_config, cfg_path)
    from ..utils import lockfile

    # held for the server's lifetime; repair-offline/convert-db take the
    # same lock, so offline maintenance can't race a live node
    lock_fd = lockfile.acquire(cfg.metadata_dir, "server")
    try:
        await _run_server_locked(cfg, cfg_path)
    finally:
        # released on EVERY exit (GL11): a failed Garage boot or
        # frontend bind must not leave the lock held when the caller
        # (tests, repair-offline in the same process) survives us
        lockfile.release(lock_fd)


async def _run_server_locked(cfg, cfg_path: str) -> None:
    garage = Garage(cfg)
    admin = AdminRpcHandler(garage)
    otlp = None
    if cfg.admin_trace_sink:
        from ..utils.otlp import setup_otlp

        otlp = setup_otlp(cfg.admin_trace_sink, garage.system.id)
    stop = asyncio.Event()

    loop = asyncio.get_event_loop()
    # SIGHUP is a shutdown signal like the reference's
    # (server.rs:185-189), not a reload; absent on some platforms
    for name in ("SIGINT", "SIGTERM", "SIGHUP"):
        sig = getattr(signal, name, None)
        if sig is None:
            continue
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass

    async def start_frontend(srv, bind: str) -> None:
        # bind addr is "host:port" or an absolute path -> Unix socket
        # (ref: util/socket_address.rs UnixOrTCPSocketAddress)
        if bind.startswith("/"):
            await srv.start(bind)
        else:
            host, port = parse_addr(bind)
            await srv.start(host, port)

    # multi-process gateway ([gateway] workers != 1 with at least one
    # TCP frontend bind): this process becomes the store node +
    # supervisor — it keeps RPC, tables, block/resync/scrub workers and
    # the admin API, while N forked workers bind the S3/K2V/web ports
    # with SO_REUSEPORT (gateway/). workers = 1 (the default) keeps the
    # single-process frontends below, byte-compatible with before.
    from ..gateway.supervisor import GatewaySupervisor, resolve_workers

    n_workers = resolve_workers(cfg.gateway.workers)
    gateway_mode = n_workers > 1 and any(
        b and not b.startswith("/")
        for b in (cfg.s3_api_bind_addr, cfg.k2v_api_bind_addr,
                  cfg.web_bind_addr))
    if n_workers > 1 and not gateway_mode:
        # same misconfiguration class GatewaySupervisor.start rejects
        # loudly for MIXED unix+TCP binds — the all-unix (or no-
        # frontend) shape must not silently run single-process while
        # the operator believes they have N workers
        log.warning(
            "[gateway] workers = %d ignored: no TCP frontend binds "
            "(SO_REUSEPORT does not apply to unix sockets); running "
            "the single-process frontend", n_workers)

    system_task = asyncio.create_task(garage.run())
    servers = []
    supervisor = None
    s3 = None
    if cfg.s3_api_bind_addr and not gateway_mode:
        s3 = S3ApiServer(garage)
        await start_frontend(s3, cfg.s3_api_bind_addr)
        servers.append(s3)
    if cfg.admin_api_bind_addr:
        from ..admin.http import AdminHttpServer

        ad = AdminHttpServer(garage, admin_rpc=admin)
        await start_frontend(ad, cfg.admin_api_bind_addr)
        servers.append(ad)
    if cfg.k2v_api_bind_addr and not gateway_mode:
        from ..api.k2v.api_server import K2VApiServer

        k2v = K2VApiServer(garage)
        await start_frontend(k2v, cfg.k2v_api_bind_addr)
        servers.append(k2v)
    if cfg.web_bind_addr and not gateway_mode:
        from ..web.server import WebServer

        web = WebServer(garage, s3)
        await start_frontend(web, cfg.web_bind_addr)
        servers.append(web)
    if gateway_mode:
        supervisor = GatewaySupervisor(garage, cfg_path,
                                       n_workers=n_workers)
        await supervisor.start()

    log.info("node %s up (rpc %s)", garage.system.id.hex()[:16],
             cfg.rpc_bind_addr)
    print(f"garage_tpu node {garage.system.id.hex()} ready", flush=True)
    await stop.wait()
    log.info("shutting down")
    if supervisor is not None:
        await supervisor.stop()
    for s in servers:
        await s.stop()
    await garage.stop()
    system_task.cancel()
    if otlp is not None:
        otlp.stop()


def main() -> None:
    p = argparse.ArgumentParser(prog="garage_tpu.cli.server")
    p.add_argument("--config", "-c",
                   default=os.environ.get("GARAGE_CONFIG_FILE",
                                          "/etc/garage.toml"))
    p.add_argument("--log-level", default=os.environ.get("RUST_LOG", "info"))
    args = p.parse_args()
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    from ..utils.lockfile import AlreadyLocked

    try:
        asyncio.run(run_server(args.config))
    except AlreadyLocked as e:
        import sys

        print(str(e), file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
