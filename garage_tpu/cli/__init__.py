"""CLI entry points (server, operator CLI, k2v-cli)."""

from __future__ import annotations


def reset_sigpipe() -> None:
    """Default SIGPIPE so `| head`/`| grep -q` closing the pipe kills
    the process quietly instead of raising BrokenPipeError (standard
    unix CLI behavior)."""
    import signal

    try:
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    except (AttributeError, ValueError):
        pass  # no SIGPIPE on this platform / not main thread
