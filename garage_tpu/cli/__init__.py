"""Server entrypoint + operator CLI (ref: src/garage/)."""
